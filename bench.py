"""apex_tpu benchmark — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 images/sec/chip at amp O2
(bf16 compute, fp32 masters, fused SGD update) — one fully-jitted train
step per iteration, synthetic ImageNet-shaped data.  Secondary metrics
(in ``extra``): amp-O0 fp32 baseline, BERT-base FusedAdam train step
(exercises the Pallas FusedLayerNorm + xentropy kernels on chip,
BASELINE config 4), FusedAdam whole-model step vs an eager per-tensor
loop, a fused DCGAN joint-loss step, and — as real subprocesses on the
same chip — the flagship example entry points: ``examples/imagenet``
(the north-star "runs unmodified" claim) and ``examples/dcgan`` (the
imperative amp surface with three loss scalers, BASELINE config 5).

Honesty contract (VERDICT r1 "What's weak" #1):

* On this TPU path (axon tunnel) ``jax.block_until_ready`` is a NO-OP —
  round 1 timed dispatch, not compute (101,959 img/s ≈ 6x chip peak).
  Every timing here forces execution with a real device->host scalar
  fetch that depends on the final step's full output chain.
* The emitted JSON self-validates: implied model TFLOP/s must be below
  the chip's bf16 peak or the bench fails loudly instead of reporting.
* The config that actually ran (backend, batch, image size, ms/step,
  MFU) is part of the JSON, so a degraded CPU run is distinguishable
  from the headline TPU metric (ADVICE r1 #4).
"""

import functools
import json
import os
import re
import subprocess
import sys
import time

import jax

# Persistent compilation cache: the 8k-matmul calibration and the ResNet-50
# program each take minutes to compile on the tunneled chip; caching makes
# repeated driver runs (and the example subprocesses below, which inherit
# the dir via env) pay that once per machine instead of once per process.
_XLA_CACHE = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(__import__("tempfile").gettempdir(),
                 f"apex_tpu_xla_cache_{os.getuid()}"))
jax.config.update("jax_compilation_cache_dir", _XLA_CACHE)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAKS = (
    ("v5 lite", 197e12),
    ("v6 lite", 918e12),
    ("v5", 459e12),      # v5p
    ("v4", 275e12),
    ("v3", 123e12),
)


def _chip_peak_flops():
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAKS:
        if key in kind:
            return peak
    return 197e12  # conservative default


_CALIB_FN = {}     # (n, iters) -> jitted chain + operands, compiled once


def _calibrate_peak(iters=48, reps=3, n=8192):
    """Measure the chip's *achievable* wall-clock bf16 matmul rate.

    Design (round-3 fix of VERDICT r2 weak #1):

    * The loop is a **provably serial chain** ``x <- bf16(x @ b)`` — each
      matmul consumes the previous result, so XLA can neither hoist a
      loop-invariant matmul (the r2 kernel's ``acc*0`` perturbation was
      foldable, which let small-shape runs report one matmul as ``iters``)
      nor CSE iterations.  ``b`` is scaled by 1/sqrt(n) so the chain is
      self-normalizing in bf16 (unit variance, no overflow) with zero
      non-matmul work in the body.
    * n=8192: small shapes badly under-measure this virtualized chip
      (4096^3 chained reads ~9 TFLOP/s vs ~60 at 8192^3 — per-program
      tunnel overhead dominates); the r2 "ceiling" of 36.9 TFLOP/s was
      that artifact, which is how a real BERT step could "exceed" it.
    * iters=48 (r5, VERDICT r4 weak #3): the r4 12-iter chain (~0.2 s)
      was short enough that one tunnel stall swung a pass ±33%; a ~1 s
      chain amortizes the per-call overhead AND the stall tail.  The
      HEADLINE denominator is the MEDIAN of all passes (robust to a
      stalled outlier in either direction); the max still feeds the
      sanity gate (a workload beating the best the chip demonstrably did
      means the timing loop did not force execution).
    * Returns a LIST of per-pass rates; the caller runs this before and
      after the workloads, reports median + [min, max] band, and gates
      against the max.
    """
    key = (n, iters)
    if key not in _CALIB_FN:
        rs = np.random.RandomState(0)

        @jax.jit
        def run(x, b):
            def it(i, x):
                return (x @ b).astype(jnp.bfloat16)
            # Consume EVERY element of the final iterate: reading a single
            # entry would leave only one row of each iterate live (x_k[0,:]
            # depends only on x_{k-1}[0,:] @ b), inviting the same class of
            # slice-narrowing rewrite that broke the r2 kernel.
            return jnp.sum(jax.lax.fori_loop(0, iters, it, x)
                           .astype(jnp.float32))

        # Cache host copies + the jitted fn, NOT device arrays: the two
        # n x n operands (~256 MB at 8k) must not squat in HBM through the
        # timed workloads between the before/after calibration passes.
        x_host = rs.randn(n, n).astype(np.float32)
        b_host = (rs.randn(n, n) / np.sqrt(n)).astype(np.float32)
        _CALIB_FN[key] = (run, x_host, b_host)
    run, x_host, b_host = _CALIB_FN[key]
    x0 = jnp.asarray(x_host, jnp.bfloat16)       # transfers, untimed
    b = jnp.asarray(b_host, jnp.bfloat16)
    float(run(x0, b))                      # compile (first time) + warm
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(run(x0, b))  # jaxlint: disable=J001 -- timing fence: the calibration pass must block until the matmul completes
        dt = (time.perf_counter() - t0) / iters
        rates.append(2 * n ** 3 / dt)
    del x0, b                              # free HBM before the workloads
    return rates


# Wall-clock throughput on the tunneled chip is noisy (measured calibration
# spread ~±30% across a bench run); a workload whose implied TFLOP/s lands
# above tol * max(measured calibration) means the timing loop did not force
# execution — fail loudly instead of reporting (VERDICT r2 next #3).
_GATE_TOL = 1.25

# Timing policy stamp: every wall timing in this file is min-of-reps
# (_best_pass / _time_steps reps=3).  Recorded in BENCH_EXTRA.json so the
# next round's regression guard only compares like-for-like (ADVICE r4).
_TIMING_POLICY = "min_of_3_passes"

# Example steady-vs-best-window gap (ISSUE 2): target the examples must
# hold on chip, and the looser self-validation gate that fails the bench
# loudly (tunnel noise swings single windows ~±18% pass to pass; the
# regression class this catches is 10x, not 1.2x).
_WINDOW_GAP_TARGET_PCT = 10.0
_WINDOW_GAP_GATE_PCT = 25.0

# Conv-path fusion + warm-start acceptance (ISSUE 7): per-example
# steady/best-window RATIO floors (the inverse view of the gap gate —
# "steady demonstrates at least this fraction of the chip's own best
# window"; with AOT warmup killing the step-0/1 compiles the steady
# clock has no excuse left), and a ResNet MFU floor so the fused conv
# epilogues must show up as device time, not just as code.  ISSUE 14
# switched the MFU floor from the static >26% to a RATCHET against the
# previous round's committed bench via prof.regress (name-inferred
# higher-is-better, the ratchet tolerance below + regress's 2-pt-point
# slack for pct metrics): each release must hold — and can only raise —
# the measured floor.  The static constant remains as the backstop when
# no comparable previous summary exists.
_STEADY_OVER_BEST_FLOORS = {"imagenet": 0.75, "dcgan": 0.75}
_RESNET_MFU_FLOOR_PCT = 26.0
_RESNET_MFU_RATCHET_TOL_PCT = 5.0

# DCGAN steady-rate floor (ISSUE 3 acceptance): >= 3x its r05 value
# (4.67 it/s, the imperative 10-dispatch/iter loop) — the pipelined
# default + pre-staged native synthetic pool must clear this on chip or
# the input/dispatch engines have regressed to the old steady floor.
_DCGAN_STEADY_GATE_IT_S = 3.0 * 4.67

# FusedAdam dispatch-overhead gates (ISSUE 4 acceptance): the bucketed
# step's wall/device ratio must stay <= 1.8 (r05 leafwise sat at 3.5x:
# pure per-leaf marshalling), and on the >=200-leaf deep tree the
# bucketed path must cut wall time >= 2x vs leafwise — dispatch-overhead
# regressions in the update half of the step fail the bench loudly.
_ADAM_WOD_GATE = 1.8
_ADAM_DEEP_SPEEDUP_GATE = 2.0

# Run-telemetry gates (ISSUE 5 acceptance): enabling the event stream
# must cost at most this factor of the disabled wall rate on the probe
# loop (the stream emits 2-3 events per WINDOW; the generous gate
# absorbs host noise — the regression class is "an event per step on
# the hot path" or a stray device sync, which shows up as 2x+); the
# disabled path must produce BITWISE-identical parameters (telemetry
# must never perturb numerics or dispatch); and the analyzer's
# loader-stall attribution must agree with the number the example
# prints (same LoaderStats.as_dict snapshot, so the tolerance only
# covers snapshot-time drift).
_TEL_OVERHEAD_GATE = 1.5
_TEL_STALL_TOL_PCT = 2.0

# ISSUE 9 (elastic runtime): the async checkpoint engine's stall
# contract — the train loop pays only the snapshot's D2H copy, the
# serialize+fsync rides the writer thread.  Gate: async stall per step
# <= 20% of the synchronous write's, measured on the SAME loop/state
# (the gate only arms when the sync stall is big enough to measure —
# below the floor the division is host-scheduler noise).
_CKPT_ASYNC_OVER_SYNC_GATE = 0.20
_CKPT_SYNC_FLOOR_MS = 1.0

# ISSUE 12 (mesh frontend): ZeRO-3 per-device param+optimizer-state
# bytes must scale ~1/shard_count on the probe mesh (8-way: ideal
# 0.125; the gate leaves room for the replicated scaler scalars and
# step counters), and the REAL 2-process CPU multi-host fixture
# (gloo collectives, per-host checkpoint shards, fleet merge of the
# two real streams) must pass end to end.
_MESH_Z3_RATIO_GATE = 0.16
_MESH_PROBE_DEVICES = 8


def _gate_implied(name, implied, peak, measured_max):
    if implied >= peak:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: {name} implies "
            f"{implied/1e12:.1f} TFLOP/s >= nameplate peak "
            f"{peak/1e12:.0f} TFLOP/s — the timing loop did not force "
            f"execution; refusing to report.")
    if measured_max and implied > _GATE_TOL * measured_max:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: {name} implies "
            f"{implied/1e12:.1f} TFLOP/s > {_GATE_TOL}x the measured "
            f"matmul ceiling {measured_max/1e12:.1f} TFLOP/s — "
            f"inconsistent with what this chip demonstrably achieves; "
            f"refusing to report.")


def _force(tree):
    """Force execution via one scalar device->host fetch
    (``block_until_ready`` is a no-op on the axon tunnel).  The device
    executes enqueued programs in order, so fetching a single output of the
    LAST enqueued program drains the whole pipeline; touching every leaf
    would instead enqueue hundreds of eager ops inside the timed window."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    return float(jnp.ravel(leaves[-1])[0].astype(jnp.float32))


def _best_pass(pass_fn, reps=3):
    """Min of ``reps`` calls to ``pass_fn() -> seconds_per_step`` — the
    shared timing policy (see _time_steps for why single passes cannot be
    trusted through the tunnel)."""
    best = float("inf")
    for _ in range(reps):
        best = min(best, pass_fn())
    return best


def _time_steps(step, state, batch, iters, warmup=3, reps=3):
    """Returns (seconds/step, final state) — the state is returned so
    callers can keep driving the step (e.g. under a profiler trace) after
    the original buffers were consumed by ``donate_argnums``.

    Min over ``reps`` timed passes: a single pass through the tunnel can
    eat a multi-second stall (one r4 run recorded 2,635 ms/step against a
    46.9 ms device time) — the best pass is what the chip demonstrably
    does, the same policy as the flash timing and the calibration max."""
    for _ in range(warmup):
        state, m = step(state, batch)
    _force((m["loss"], state))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, batch)
        _force((m["loss"], state))  # full chain: metrics AND final state
        best = min(best, (time.perf_counter() - t0) / iters)
    return best, state


def _time_steps_device_loop(step_fn, state, batch, k=32, calls=2, reps=3):
    """Seconds/step with K steps chained into one program
    (:func:`apex_tpu.training.chain_steps`): the TPU device-loop rate,
    free of the tunnel's per-call dispatch overhead (~7 ms + ~22 us/arg
    measured here — a 9-11 ms/step tax the jitted-per-step numbers pay).
    The batch pool is the same batch broadcast K times; every step still
    runs the full train-step math on its own carry.

    ``donate_argnums=(0, 1)``: the loop donates BOTH the carried state
    and the consumed window (ISSUE 2 satellite — the [K, ...] stack is K
    full batches of HBM, ~2.4 GB at k=32/b128/224px, and un-donated it
    stays pinned for the whole call).  A donated window is consumed, so
    each call re-stages it with a tiny jitted broadcast program — the
    device-side analog of the runtime's fresh staged windows (an HBM
    write at memory bandwidth, ~3 ms for 2.4 GB, amortized over K
    steps)."""
    from apex_tpu.training import chain_steps

    chained = jax.jit(chain_steps(step_fn), donate_argnums=(0, 1))
    stage = jax.jit(lambda b: jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (k,) + a.shape), b))
    for _ in range(2):                     # compile + resharding warmup
        state, m = chained(state, stage(batch))
    _force((m["loss"], state))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = chained(state, stage(batch))
        _force((m["loss"], state))
        best = min(best, (time.perf_counter() - t0) / (calls * k))
    return best


def _time_steps_pipeline(step_fn, state, batch, k=32, calls=2, reps=3):
    """Wall seconds/step of the USER-FACING training path
    (:class:`apex_tpu.runtime.StepPipeline`): K steps per host dispatch
    through the runtime engine itself — its Python overhead, window
    dispatch, and the deferred (one-dispatch-behind) metric read all
    included.  This is the number the ISSUE-2 acceptance compares
    against ``ms_per_step_o2_device_loop``: the dispatch gap the
    step-pipelining runtime closes for a real training loop.  The reused
    synthetic window is NOT donated (the examples' synthetic-pool
    shape); each rep is fenced by one stacked metric fetch."""
    from apex_tpu import runtime as rt

    pipe = rt.StepPipeline(step_fn, k, donate_window=False)
    window = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (k,) + a.shape), batch)
    for _ in range(2):                     # compile + resharding warmup
        state, m = pipe.step_window(state, window)
    _force((m["loss"], state))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = pipe.step_window(state, window)
        _force((m["loss"], state))   # drain: metrics AND final state
        best = min(best, (time.perf_counter() - t0) / (calls * k))
    return best


_PROF_TRACE_STEPS = 3   # shared with the bytes ledger below


def _prof_top_ops(step, state, batch, steps=_PROF_TRACE_STEPS, top=5):
    """Dogfood the profiler on a headline workload (VERDICT r2 next #3):
    capture a real XLA device trace around ``steps`` executions with
    :func:`apex_tpu.prof.capture.trace`, parse it with
    :func:`apex_tpu.prof.parse.parse_trace`, and return the top measured
    ops plus on-device totals.  On the TPU the trace is the device-event
    format (hlo_category per op); this is the parse stage proving itself
    on the same workload the bench reports.

    Round-4 lesson (VERDICT r3 missing #1 was a mis-read of this table):
    grouping by HLO *name* is misleading — XLA names a fusion after its
    root op, so a weight-gradient convolution whose epilogue is the SGD
    update shows up as ``multiply_subtract_fusion`` and a forward conv
    with a BN-stats epilogue as ``convert_reduce_fusion``.  The r3 table
    was read as "precision plumbing eats 72% of the step" when those
    fusions ARE the convolutions.  The ``by_category`` table (XLA's own
    hlo_category, which calls both of those "convolution fusion") is the
    truthful attribution and is now reported alongside."""
    import shutil
    import tempfile

    from apex_tpu.prof import capture
    from apex_tpu.prof import parse as prof_parse

    logdir = tempfile.mkdtemp(prefix="apex_bench_trace_")
    try:
        with capture.trace(logdir):
            s = state
            for _ in range(steps):
                s, m = step(s, batch)
            _force((m["loss"], s))
        tp = prof_parse.parse_trace(logdir)
        if not tp.records:
            return {"error": "trace produced no device events"}, None
        ops = sorted(tp.by_op().items(), key=lambda kv: -kv[1]["total_us"])
        by_cat = [
            {"category": k, "count": v["count"],
             "us_per_step": round(v["total_us"] / steps, 1),
             "pct": round(100 * v["total_us"] / tp.total_us, 1),
             "tflops": round(v["tflops_per_sec"], 1),
             "gb_per_s": round(v["bytes"] / (v["total_us"] * 1e-6) / 1e9, 0)
             if v["total_us"] else 0.0}
            for k, v in sorted(tp.by_category().items(),
                               key=lambda kv: -kv[1]["total_us"])[:6]]
        return {
            "steps_traced": steps,
            "device_us_per_step": round(tp.total_us / steps, 1),
            "top_ops": [
                {"op": name, "count": agg["count"],
                 "total_us": round(agg["total_us"], 1),
                 "mean_us": round(agg["mean_us"], 2)}
                for name, agg in ops[:top]],
            "by_category": by_cat,
        }, tp
    except Exception as e:               # never fail the bench on prof
        return {"error": f"{type(e).__name__}: {e}"}, None
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def _measure_precision_plumbing(steps=3):
    """Measure the O2 precision machinery IN ISOLATION on the real
    ResNet-50 parameter tree: bf16 compute-cast of all params (what
    ``compute_cast`` traces into the step), the unscale-with-overflow
    check, and the momentum-SGD master update with the skip mask.  This
    is everything `apex` implements in ``multi_tensor_scale_kernel.cu``
    and ``multi_tensor_sgd_kernel.cu`` — measured on-device as its own
    program, so its cost can be stated without untangling XLA's fusion
    attribution (the full-step profile fuses the update into the wgrad
    convolutions, where it is effectively free)."""
    import shutil
    import tempfile

    from apex_tpu.amp import policy as _policy
    from apex_tpu.models import ResNet50
    from apex_tpu.multi_tensor import multi_tensor_scale
    from apex_tpu.optimizers import functional as F
    from apex_tpu.prof import capture
    from apex_tpu.prof import parse as prof_parse

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, train=False)["params"]
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-4, jnp.float32), params)
    opt_state = F.sgd_init(params, momentum=0.9)

    @jax.jit
    def plumbing(params, grads, opt_state):
        # 1. compute-cast: fp32 masters -> bf16 model copy (keep-bn fp32)
        cast = _policy.convert_params(params, jnp.bfloat16,
                                      keep_norm_fp32=True)
        # 2. unscale + overflow flag (multi_tensor_scale contract)
        unscaled, overflow = multi_tensor_scale(grads, 1.0 / 1024.0)
        # 3. skip-masked momentum-SGD master update
        new_p, new_s = F.sgd_update(unscaled, opt_state, params, lr=0.1,
                                    momentum=0.9,
                                    apply_mask=jnp.logical_not(overflow))
        return cast, new_p, new_s

    out = plumbing(params, grads, opt_state)
    _force(out[1])
    logdir = tempfile.mkdtemp(prefix="apex_plumb_trace_")
    try:
        with capture.trace(logdir):
            for _ in range(steps):
                out = plumbing(params, grads, opt_state)
            _force(out[1])
        tp = prof_parse.parse_trace(logdir)
        if not tp.records:
            return None
        return round(tp.total_us / steps / 1e3, 3)    # ms per step
    except Exception:
        return None
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


# -- ResNet-50 (headline, BASELINE configs 1-2) -------------------------------

def _resnet_flops_per_step(batch, image_size):
    """Analytic ResNet-50 training FLOPs: ~4.09 GFLOP forward per 224x224
    image (multiply+add counted separately), x3 for fwd+bwd."""
    return 3 * 4.089e9 * (image_size / 224.0) ** 2 * batch


def _make_resnet_step(opt_level, batch, image_size=224, num_classes=1000,
                      fused=True):
    from apex_tpu import training
    from apex_tpu.models import ResNet50
    from apex_tpu.training import make_train_step

    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    if fused:
        # The shipping hot path (ISSUE 7): contrib GroupBN NHWC through
        # the ResNet norm-factory hook (bn->relu->(+residual) chains as
        # ONE Pallas bn_relu_residual epilogue each) + the NHWC
        # implicit-GEMM Pallas convs (ISSUE 18, per-site XLA fallback
        # for unservable shapes) + the contrib fused softmax-xentropy —
        # exactly what examples/imagenet runs with its default
        # --fused-bn/--fused-loss/--pallas-conv flags.
        import functools
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
        from apex_tpu.ops import PallasConv
        model = ResNet50(num_classes=num_classes, dtype=dtype,
                         norm_cls=functools.partial(BatchNorm2d_NHWC),
                         conv_cls=PallasConv)
    else:
        model = ResNet50(num_classes=num_classes, dtype=dtype)
    x = jnp.asarray(np.random.RandomState(0).rand(
        batch, image_size, image_size, 3), jnp.float32)
    y = jnp.asarray(np.arange(batch) % num_classes)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    if fused:
        from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        if fused:
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits.astype(jnp.float32), yb, smoothing=0.0,
                padding_idx=-1))
        else:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    tx = training.sgd(lr=0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       has_model_state=True)
    state = init_fn(params, batch_stats)
    step = jax.jit(step_fn, donate_argnums=(0,))
    return step, state, (x, y), step_fn


# -- BERT-base FusedAdam (BASELINE config 4; Pallas layernorm + xentropy) -----

def _bert_flops_per_step(n_dense_params, batch, seq, hidden, vocab, layers):
    """Matmul-only analytic training FLOPs (VERDICT r2 next #3: do not
    charge matmul FLOPs to lookup params).

    * ``dense``: 6·N·B·S over **dense-kernel params only** — embedding
      tables (word/position/token-type) are gathers/adds, no MXU work.
    * ``head``: the tied-embedding projection ``feats @ emb.T`` IS a
      matmul (fwd 2·B·S·H·V, bwd dgrad+wgrad 4·B·S·H·V); counted here
      explicitly since its weight was excluded from ``dense``.
    * ``attn``: QK^T and PV, fwd+bwd, both mult+add counted.
    """
    dense = 6 * n_dense_params * batch * seq
    head = 6 * batch * seq * hidden * vocab
    attn = 3 * layers * 4 * seq * seq * hidden * batch
    return dense + head + attn


def _make_bert_step(batch=16, seq=128):
    from apex_tpu import training
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models import bert_base
    from apex_tpu.training import make_train_step

    # attention_impl="flash": the Pallas flash-attention kernel on TPU
    # (falls back to the jnp blockwise path off-TPU).
    model = bert_base(dtype=jnp.bfloat16, num_classes=None,
                      attention_impl="flash")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 30522, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, 30522, (batch, seq)))
    variables = model.init(jax.random.PRNGKey(0), ids)
    params = variables["params"]
    n_params = int(sum(np.prod(l.shape) for l in
                       jax.tree_util.tree_leaves(params)))
    n_emb = int(sum(
        np.prod(l.shape) for name in
        ("word_embeddings", "position_embeddings", "token_type_embeddings")
        for l in jax.tree_util.tree_leaves(params[name])))
    n_dense = n_params - n_emb       # matmul-participating params

    emb_kernel = params["word_embeddings"]["embedding"]
    vocab = int(emb_kernel.shape[0])

    def loss_fn(p, b):
        ids_b, labels_b = b
        feats = model.apply({"params": p}, ids_b)          # [b, s, h] fp32
        logits = feats @ p["word_embeddings"]["embedding"].T  # tied head
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]),
            labels_b.reshape(-1), smoothing=0.1, padding_idx=-1)
        return jnp.mean(losses)

    tx = training.adam(lr=1e-4)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    hidden = int(emb_kernel.shape[1])
    return (step, state, (ids, labels), n_params, n_dense, hidden, vocab,
            step_fn)


def _bert_mfu_bound(ledger, flops, measured_med, prof):
    """Additive no-overlap reference model for the BERT step: matmuls at
    the calibration-median rate PLUS the intrinsic Adam state sweep (30
    B/param) at the trace's loop-fusion bandwidth, as if the two never
    overlapped.

    NOT a hard ceiling: XLA fuses part of the update into wgrad-matmul
    epilogues and real matmuls can beat the calibration median, so a
    measured step may land under the additive total (an r5 run did:
    13.64 ms vs 14.18 additive).  Its value is the decomposition — how
    much of the step the non-matmul intrinsic traffic explains — not a
    gate.  Falls back to ~800 GB/s (v5e HBM) when the trace lacks a
    loop-fusion row.
    """
    if not (ledger and measured_med) or "error" in (ledger or {}):
        return None
    ideal_ms = flops / measured_med * 1e3
    opt_gb = ledger["intrinsic"].get("optimizer_gb")
    if not opt_gb:
        return None
    from apex_tpu.prof.parse import LOOP_FUSION_CATEGORY
    bw, bw_source = 800.0, "fallback_v5e_hbm"
    for row in (prof or {}).get("by_category", []):
        if row.get("category") == LOOP_FUSION_CATEGORY \
                and row.get("gb_per_s"):
            bw, bw_source = row["gb_per_s"], "measured_" \
                + LOOP_FUSION_CATEGORY.replace(" ", "_")
            break
    floor_ms = opt_gb / bw * 1e3
    return {
        "ideal_matmul_ms": round(ideal_ms, 2),
        "optimizer_sweep_ms": round(floor_ms, 2),
        "optimizer_sweep_bw_gb_s": round(bw, 1),
        # drift guard (ADVICE r5): says whether the bandwidth above was
        # measured from the trace's loop-fusion row or is the hardcoded
        # 800 GB/s fallback — a renamed category can no longer silently
        # change the additive model without signal.
        "optimizer_sweep_bw_source": bw_source,
        "additive_model_mfu_pct": round(
            100 * ideal_ms / (ideal_ms + floor_ms), 1),
        "note": ("additive no-overlap model at the calibration median; "
                 "a measured step can beat it (epilogue fusion, "
                 "above-median matmuls) — reference point, not a ceiling"),
    }


# -- FusedAdam whole-model step vs eager per-tensor loop ----------------------

def _adam_fused_vs_eager(iters):
    """BASELINE metric 'FusedAdam step time vs eager': one jitted
    whole-model update (the multi-tensor capability) vs a per-tensor
    dispatch loop (the analog of an unfused eager optimizer)."""
    from apex_tpu.models import bert_base
    from apex_tpu.optimizers import functional as F

    model = bert_base(dtype=jnp.bfloat16, num_classes=None)
    ids = jnp.asarray(np.zeros((1, 16), np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-4, p.dtype), params)

    # fused: whole pytree in ONE program.  donate_argnums=(1, 2): the
    # consumed optimizer state + params alias the outputs (ISSUE 3
    # satellite — un-donated, the ~790-leaf update marshalled a full
    # copy of every master/momentum buffer per call, a pure dispatch
    # tax the reference's in-place multi_tensor_adam never pays).
    upd = functools.partial(F.adam_update, lr=1e-3)
    state = F.adam_init(params)
    fused = jax.jit(upd, donate_argnums=(1, 2))

    def run_fused(params, state):
        return fused(grads, state, params)

    def _fresh():
        # Donation consumes (params, state): every pass starts from
        # live copies, materialized before the clock starts.
        p, s = jax.tree_util.tree_map(jnp.copy, (params, state))
        _force(p)
        return p, s

    p, s = run_fused(*_fresh())
    _force(p)

    # min-of-reps (_best_pass): the ~600-leaf arg dispatch dominates this
    # number and swings 1.5x pass-to-pass through the tunnel.
    def fused_pass():
        p, s = _fresh()
        t0 = time.perf_counter()
        for _ in range(iters):
            p, s = run_fused(p, s)
        _force(p)
        return (time.perf_counter() - t0) / iters

    t_fused = _best_pass(fused_pass)

    # eager: one dispatch per tensor (same math), jit per shape
    @jax.jit
    def one(g, p, m, v, t):
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3
        gf = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * gf
        v = b2 * v + (1 - b2) * gf * gf
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        return (p - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype), m, v

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_g = jax.tree_util.tree_leaves(grads)
    ms = [jnp.zeros(l.shape, jnp.float32) for l in leaves_p]
    vs = [jnp.zeros(l.shape, jnp.float32) for l in leaves_p]

    def run_eager(ps, ms, vs, t):
        out_p, out_m, out_v = [], [], []
        for g, pp, m, v in zip(leaves_g, ps, ms, vs):
            npp, nm, nv = one(g, pp, m, v, t)
            out_p.append(npp); out_m.append(nm); out_v.append(nv)
        return out_p, out_m, out_v

    ps2, ms2, vs2 = run_eager(leaves_p, ms, vs, 1.0)   # compile all shapes
    _force(ps2)

    def eager_pass():
        t0 = time.perf_counter()
        ps2, ms2, vs2 = leaves_p, ms, vs
        for i in range(iters):
            ps2, ms2, vs2 = run_eager(ps2, ms2, vs2, float(i + 1))
        _force(ps2)
        return (time.perf_counter() - t0) / iters

    t_eager = _best_pass(eager_pass)

    # -- the kernel itself, not the tunnel (VERDICT r4 weak #4 / next #4):
    # (a) device time of ONE fused update, traced as its own program —
    #     the honest analog of the reference's multi_tensor_adam kernel
    #     time (roofline: ~2.6 GB of param+state traffic);
    # (b) K-chained wall time (lax.scan of K updates in one program), so
    #     the ~790-leaf dispatch tax amortizes like a real train loop.
    def _device_ms(run, fresh):
        if jax.default_backend() != "tpu":
            return None
        import shutil
        import tempfile

        from apex_tpu.prof import capture
        from apex_tpu.prof import parse as prof_parse

        logdir = tempfile.mkdtemp(prefix="apex_adam_trace_")
        try:
            with capture.trace(logdir):
                p, s = fresh()        # donation consumes the operands
                for _ in range(3):
                    p, s = run(p, s)
                _force(p)
            tp = prof_parse.parse_trace(logdir)
            if tp.records:
                return round(tp.total_us / 3 / 1e3, 3)
            return None
        except Exception:
            return None
        finally:
            shutil.rmtree(logdir, ignore_errors=True)

    t_dev_ms = _device_ms(run_fused, _fresh)

    K = 16

    @jax.jit
    def chained(p, s):
        def one_step(carry, _):
            p, s = carry
            return fused(grads, s, p), None
        (p, s), _ = jax.lax.scan(one_step, (p, s), None, length=K)
        return p, s

    p, s = chained(params, state)
    p, s = chained(p, s)          # resharding warmup (2 calls compile)
    _force(p)

    def chained_pass():
        t0 = time.perf_counter()
        p, s = params, state
        for _ in range(max(2, iters // K)):
            p, s = chained(p, s)
        _force(p)
        return (time.perf_counter() - t0) / (max(2, iters // K) * K)

    t_chained = _best_pass(chained_pass)

    # -- bucketed flat-bucket path (ISSUE 4): masters + optimizer state
    # live as a few large per-dtype buffers (the FusedOptimizer bucketed
    # contract), grads arrive as packed fp32 buckets (the amp unscale
    # output) — the jit call boundary passes O(buckets) arguments instead
    # of ~4 per leaf, which is exactly the wall-vs-device gap above.
    from apex_tpu.multi_tensor.buckets import BucketStore
    store = BucketStore(params)
    g_packed = store.pack_jit(grads, dtype=jnp.float32)
    state_b = F.adam_init(params, store=store)
    p_packed = store.pack_jit(params)
    fused_b = jax.jit(functools.partial(F.adam_update, lr=1e-3, store=store),
                      donate_argnums=(1, 2))

    def run_bucketed(p, s):
        return fused_b(g_packed, s, p)

    def _fresh_b():
        p, s = jax.tree_util.tree_map(jnp.copy, (p_packed, state_b))
        _force(p)
        return p, s

    p, s = run_bucketed(*_fresh_b())
    _force(p)

    def bucketed_pass():
        p, s = _fresh_b()
        t0 = time.perf_counter()
        for _ in range(iters):
            p, s = run_bucketed(p, s)
        _force(p)
        return (time.perf_counter() - t0) / iters

    t_bucketed = _best_pass(bucketed_pass)
    t_bucketed_dev_ms = _device_ms(run_bucketed, _fresh_b)

    return {
        "fused_s": t_fused, "eager_s": t_eager, "n_tensors": len(leaves_p),
        "device_ms": t_dev_ms, "chained_s": t_chained,
        "bucketed_s": t_bucketed, "bucketed_device_ms": t_bucketed_dev_ms,
        "n_buckets": store.n_buckets,
    }


def _adam_deep_pytree(iters, n_leaves=240):
    """ISSUE 4 satellite: FusedAdam over a DEEP (>=200-leaf) pytree,
    leafwise vs bucketed — wall ms/step AND first-compile seconds.  Deep
    trees are where the O(leaves) floors bite twice: ~4 jit arguments
    per leaf of per-call marshalling on the wall clock, and one update
    subgraph per leaf at compile time."""
    from apex_tpu.multi_tensor.buckets import BucketStore
    from apex_tpu.optimizers import functional as F

    rng = np.random.RandomState(0)
    shapes = ([(256, 32)] * (n_leaves // 4)
              + [(512,)] * (n_leaves // 2)
              + [(64, 16)] * (n_leaves - n_leaves // 4 - n_leaves // 2))
    params = {f"p{i:03d}": jnp.asarray(rng.randn(*s).astype(np.float32))
              for i, s in enumerate(shapes)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-4, p.dtype), params)

    def _measure(make_step, make_operands):
        """(first_compile_seconds, best_pass_seconds_per_step)."""
        step = make_step()
        p0, s0 = make_operands()
        t0 = time.perf_counter()
        p, s = step(p0, s0)
        _force(p)
        compile_s = time.perf_counter() - t0

        def one_pass():
            p, s = make_operands()
            t0 = time.perf_counter()
            for _ in range(iters):
                p, s = step(p, s)
            _force(p)
            return (time.perf_counter() - t0) / iters

        return compile_s, _best_pass(one_pass)

    # leafwise: the pre-ISSUE-4 hot path (donated, jitted, one program).
    state_l = F.adam_init(params)

    def make_leafwise():
        fused = jax.jit(functools.partial(F.adam_update, lr=1e-3),
                        donate_argnums=(1, 2))
        return lambda p, s: fused(grads, s, p)

    def operands_leafwise():
        p, s = jax.tree_util.tree_map(jnp.copy, (params, state_l))
        _force(p)
        return p, s

    compile_l, t_leafwise = _measure(make_leafwise, operands_leafwise)

    # bucketed: params + state as Packed buckets across calls.
    store = BucketStore(params)
    g_packed = store.pack_jit(grads, dtype=jnp.float32)
    p_packed = store.pack_jit(params)
    state_b = F.adam_init(params, store=store)

    def make_bucketed():
        fused = jax.jit(
            functools.partial(F.adam_update, lr=1e-3, store=store),
            donate_argnums=(1, 2))
        return lambda p, s: fused(g_packed, s, p)

    def operands_bucketed():
        p, s = jax.tree_util.tree_map(jnp.copy, (p_packed, state_b))
        _force(p)
        return p, s

    compile_b, t_bucketed = _measure(make_bucketed, operands_bucketed)

    return {
        "n_leaves": len(shapes),
        "n_params": int(sum(np.prod(s) for s in shapes)),
        "leafwise_ms": round(t_leafwise * 1e3, 3),
        "bucketed_ms": round(t_bucketed * 1e3, 3),
        "speedup_bucketed": round(t_leafwise / t_bucketed, 2),
        "leafwise_first_compile_s": round(compile_l, 2),
        "bucketed_first_compile_s": round(compile_b, 2),
    }


# -- long-context flash attention (beyond-parity, SURVEY §5) ------------------

def _bench_flash_attention(seq, batch=1, heads=12, head_dim=64, iters=10):
    """Causal fwd+bwd of the Pallas flash kernel vs the jnp blockwise
    oracle at long context — the long-sequence story on one chip."""
    from apex_tpu.ops.attention import blockwise_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, seq, heads, head_dim),
                           jnp.bfloat16) for _ in range(3))

    def timed(fn, reps=3):
        """Best of ``reps`` timing passes: wall-clock through the tunnel
        swings +-18% pass-to-pass (r4 measured the same binary at 16.07
        and 18.92 ms twenty minutes apart), so a single pass cannot anchor
        a cross-round regression guard.  Min-of-reps reports what the
        chip demonstrably achieves — same policy as the calibration's
        max-of-passes."""
        loss = lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = g(q, k, v)
        _force(out[0])
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(q, k, v)
            _force(out[0])
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    t_flash = timed(lambda q, k, v: flash_attention(q, k, v, causal=True))
    t_block = timed(lambda q, k, v: blockwise_attention(q, k, v, causal=True))
    return t_flash, t_block


# -- DCGAN multi-loss O1 (BASELINE config 5) ----------------------------------

def _make_dcgan_step(batch=64):
    from apex_tpu import training
    from apex_tpu.models import Discriminator, Generator
    from apex_tpu.training import make_train_step

    gen = Generator(dtype=jnp.bfloat16)
    disc = Discriminator(dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    z = jax.random.normal(rng, (batch, 100), jnp.float32)
    real = jnp.asarray(np.random.RandomState(0).rand(
        batch, 64, 64, 3), jnp.float32)
    gv = gen.init(rng, z, train=False)
    gp, g_bs = gv["params"], gv["batch_stats"]
    fake0 = gen.apply(gv, z, train=False)
    dv = disc.init(rng, fake0, train=False)
    dp, d_bs = dv["params"], dv["batch_stats"]

    from apex_tpu.ops.losses import binary_cross_entropy_with_logits

    def bce(logits, target):
        return binary_cross_entropy_with_logits(
            logits, jnp.full(logits.shape, target), reduction="mean")

    def loss_fn(params, b):
        z_b, real_b = b
        g = {"params": params["gen"], "batch_stats": g_bs}
        d = {"params": params["disc"], "batch_stats": d_bs}
        fake = gen.apply(g, z_b, train=False)
        d_loss = (bce(disc.apply(d, real_b, train=False), 1.0)
                  + bce(disc.apply(d, jax.lax.stop_gradient(fake),
                                   train=False), 0.0))
        g_loss = bce(disc.apply(d, fake, train=False), 1.0)
        return d_loss + g_loss       # two losses, one multi-model step

    tx = training.adam(lr=2e-4, beta1=0.5)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                      loss_scale="dynamic")
    state = init_fn({"gen": gp, "disc": dp})
    return jax.jit(step_fn, donate_argnums=(0,)), state, (z, real)


# -- flagship examples as subprocesses (VERDICT r2 next #1) -------------------

_ITER_RE = re.compile(
    r"iter (\d+)\s+loss ([\d.infa+-]+)\s+speed ([\d.]+) img/s")
_STEADY_RE = re.compile(r"steady ([\d.]+) img/s over (\d+) iters")
_BESTWIN_RE = re.compile(r"best-window ([\d.]+) img/s")
_DCGAN_FLOOR_RE = re.compile(
    r"floor ~([\d.]+) ms/iter \(([\d.]+) it/s tunnel-physics bound\)")
_DCGAN_RE = re.compile(r"Loss_D: ([\d.infa+-]+) Loss_G: ([\d.infa+-]+)")
_DONE_RE = re.compile(r"done in ([\d.]+)s \(([\d.]+) it/s\)")
_DCGAN_STEADY_RE = re.compile(r"steady ([\d.]+) it/s over (\d+) iters")
_DCGAN_BEST_RE = re.compile(r"best-of-3 windows: ([\d.]+) it/s")
# Input-engine attribution printed by every example (ISSUE 3): the share
# of the wall clock the train loop spent waiting on the loader.
_LOADER_RE = re.compile(r"loader: stall ([\d.]+)%")


def _run_example(rel_path, argv, timeout=2400):
    """Run a repo example as a subprocess (its own TPU client through the
    tunnel — verified to coexist with this process) and return its stdout.
    The driver-facing point: the REAL entry points under ``examples/`` run
    unmodified on the chip, not a bench-local reconstruction of them."""
    root = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(root, rel_path)] + argv
    env = dict(os.environ)     # inherits JAX_COMPILATION_CACHE_DIR
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, cwd=root, env=env)
    except subprocess.TimeoutExpired as e:
        raise SystemExit(
            f"BENCH EXAMPLE FAILED (timeout {timeout}s): {' '.join(cmd)}\n"
            f"--- stdout ---\n{(e.stdout or '')[-2000:]}\n"
            f"--- stderr ---\n{(e.stderr or '')[-2000:]}")
    wall = time.perf_counter() - t0
    if r.returncode != 0:
        raise SystemExit(
            f"BENCH EXAMPLE FAILED (rc={r.returncode}): {' '.join(cmd)}\n"
            f"--- stdout ---\n{r.stdout[-2000:]}\n"
            f"--- stderr ---\n{r.stderr[-2000:]}")
    return r.stdout, wall


def _window_gap_pct(steady, best_window):
    """Steady-vs-best-window gap, percent of the best window: how much
    of the rate the chip DEMONSTRABLY reached the example's steady loop
    leaves on the table (ISSUE 2: DCGAN's 12x gap hid behind the steady
    number alone).  0 when steady meets or beats the best window."""
    if not steady or not best_window:
        return None
    return round(max(0.0, 100.0 * (1.0 - steady / best_window)), 1)


def _bench_telemetry():
    """ISSUE 5 self-validation: run the SAME pipelined training loop with
    telemetry disabled and enabled, and prove three contracts —

    * **no-op when disabled**: the enabled run's final parameters are
      BITWISE identical to the disabled run's (instrumentation never
      perturbs numerics or dispatch);
    * **zero retraces**: both runs compile the hot program exactly once
      (instrumentation must not change trace signatures);
    * **bounded overhead**: min-of-3 wall time with the recorder active
      is within ``_TEL_OVERHEAD_GATE`` of the disabled rate.

    Also sanity-checks the offline analyzer on the emitted stream (step
    count, dispatch accounting).  Runs on CPU and TPU alike — the
    contracts are backend-independent.
    """
    import tempfile

    from apex_tpu import runtime, telemetry, training
    from apex_tpu.prof import assert_trace_count, timeline
    from apex_tpu.training import make_train_step

    # Isolate the probe from any env-driven recorder (APEX_TPU_TELEMETRY
    # on the whole bench): the DISABLED baseline below must really be
    # disabled — with a live ambient recorder both runs would be
    # instrumented and the 1.5x gate would compare telemetry against
    # itself.  Restored (not cleared) on exit so the ambient stream
    # keeps recording the rest of the bench (review finding).
    prev_ambient = telemetry.set_recorder(None)

    k, n_batches, reps = 4, 16, 3
    rs = np.random.RandomState(0)
    w0 = rs.randn(512, 512).astype(np.float32) / 23.0
    batches = [(rs.randn(64, 512).astype(np.float32),
                rs.randn(64, 512).astype(np.float32))
               for _ in range(n_batches)]

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    export_info = {}

    def one_run(tel_path):
        init_fn, step_fn = make_train_step(
            loss_fn, training.sgd(lr=0.01), opt_level="O2",
            loss_scale="dynamic")
        # watchdog=True (ISSUE 6): the overhead/bitwise gates below now
        # cover the rule engine folding every event on the hot path —
        # the acceptance pins the WATCHDOG-enabled probe loop under the
        # same 1.5x ceiling.  export_* (ISSUE 10): the enabled probe
        # ALSO renders the Prometheus textfile on the event threads and
        # serves the http endpoint, so the same ceiling now covers the
        # full telemetry+watchdog+export stack.
        rec = telemetry.start(tel_path, watchdog=True,
                              example="bench-telemetry",
                              export_textfile=(tel_path + ".prom"),
                              export_port=0, export_every_s=0.05) \
            if tel_path else None
        try:
            pipe = runtime.StepPipeline(step_fn, k)
            state = init_fn({"w": jnp.asarray(w0)})

            def one_pass(state):
                t0 = time.perf_counter()
                state, reader = pipe.run(
                    state, runtime.window_batches(iter(batches), k))
                _force(reader.flush()[0].metrics)   # fence the pipeline
                return time.perf_counter() - t0, state

            with assert_trace_count(pipe.loop, 1):
                _, state = one_pass(state)          # compile pass
                best = float("inf")
                for _ in range(reps):
                    dt, state = one_pass(state)
                    best = min(best, dt)
            if rec is not None and rec.exporter is not None:
                # Scrape-under-load (ISSUE 10): hit the live endpoint
                # while the recorder is still open, prove the exposition
                # carries the loop's own instruments.
                import urllib.request
                body = urllib.request.urlopen(
                    f"http://localhost:{rec.exporter.port}/metrics",
                    timeout=10).read().decode()
                export_info["scrape_ok"] = (
                    "apex_tpu_steps_dispatched_total" in body
                    and "apex_tpu_watchdog_ok" in body
                    and "apex_tpu_run_info" in body)
                export_info["endpoint"] = rec.exporter.describe()
        finally:
            if rec is not None:
                rec.close()
                if rec.exporter is not None:
                    # close() wrote the final render; count it
                    export_info["textfile_renders"] = rec.exporter.renders
                    export_info["textfile_ok"] = os.path.exists(
                        tel_path + ".prom")
        # deep-copy: on CPU device_get can return zero-copy views into
        # device buffers, and the second run's buffer reuse would
        # corrupt the first snapshot — a spurious bitwise-gate failure
        return best, jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True),
            jax.device_get(state.params))

    try:
        t_off, params_off = one_run(None)
        tel_path = os.path.join(
            tempfile.gettempdir(),
            f"apex_tpu_bench_telemetry_{os.getpid()}.jsonl")
        t_on, params_on = one_run(tel_path)
    finally:
        telemetry.set_recorder(prev_ambient)

    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(params_off),
                        jax.tree_util.tree_leaves(params_on)))
    stream_events = timeline.load_events(tel_path)
    analysis = timeline.analyze(stream_events)
    steps_per_pass = n_batches
    analyzer_ok = (
        analysis["steps"] == steps_per_pass * (reps + 1)
        and analysis["retraces"]["retraces"] == 0
        and 0.0 <= analysis["attribution"]["dispatch_gap_pct"] <= 100.0)
    # Regression-differ self-check (ISSUE 6 acceptance): a self-diff of
    # the analysis must be clean, and a synthetically degraded copy
    # (half the throughput, 3x the p50, fresh retraces) must fail —
    # prof.regress is only a CI gate if both directions hold.
    import copy

    from apex_tpu.prof import regress
    self_diff = regress.diff_summaries(analysis, analysis)
    degraded = copy.deepcopy(analysis)
    if degraded.get("steps_per_s"):
        degraded["steps_per_s"] = degraded["steps_per_s"] / 2.0
    for key in ("mean_ms", "p50_ms", "p90_ms", "p99_ms"):
        if (degraded.get("step_time") or {}).get(key):
            degraded["step_time"][key] *= 3.0
    degraded["retraces"]["retraces"] = (
        degraded["retraces"].get("retraces", 0) + 2)
    deg_diff = regress.diff_summaries(analysis, degraded)
    return {
        "disabled_wall_s": round(t_off, 4),
        "enabled_wall_s": round(t_on, 4),
        "overhead_ratio": round(t_on / t_off, 3) if t_off else None,
        "overhead_gate": _TEL_OVERHEAD_GATE,
        "bitwise_identical_disabled": bool(identical),
        "zero_retraces": analysis["retraces"]["retraces"] == 0,
        "analyzer_consistent": bool(analyzer_ok),
        "analyzer_steps": analysis["steps"],
        "stream": tel_path,
        "stream_events": analysis["n_events"],
        # The enabled run folded every event through the watchdog.  The
        # DETERMINISTIC rules (nonfinite / scale_collapse /
        # retrace_storm — all critical) must stay silent on the clean
        # probe and are gated in main(); the warning-level timing
        # heuristics (step_time, loader_stall) are load-sensitive on a
        # shared host (the probe's pass-boundary fetch IS a host stall)
        # and stay reported, not gated.
        "watchdog_alerts": (analysis.get("alerts") or {}).get("total", 0),
        "watchdog_critical_alerts": sum(
            1 for e in stream_events
            if e.get("kind") == "alert"
            and e.get("severity") == "critical"),
        "regress_self_diff_clean": not self_diff["regressions"],
        "regress_detects_degradation": bool(deg_diff["regressions"]),
        # Live-export self-validation (ISSUE 10): the overhead/bitwise
        # numbers above were measured WITH the exporter attached, so
        # export adds nothing the 1.5x gate does not already cover.
        "export": export_info,
    }


def _bench_fleet():
    """ISSUE 10 self-validation: the fleet merge must identify the
    injected slow host on EVERY window of the deterministic synthetic
    4-host fixture, and the clock aligner must recover the injected
    wall-anchor skew from the per-window dispatch indices.  Pure host
    JSON — backend-independent."""
    import shutil
    import tempfile

    from apex_tpu.prof import fleet

    n_hosts, n_windows, slow = 4, 12, 2
    clock_err = (0.040, -0.040, 0.080, -0.080)   # seconds, per host
    d = tempfile.mkdtemp(prefix="apex_tpu_bench_fleet_")
    try:
        fleet.synthetic_fleet(n_hosts, n_windows, 4, slow_host=slow,
                              clock_err_s=clock_err, dir=d)
        streams = fleet.load_fleet([os.path.join(d, "host*.jsonl")])
        a = fleet.analyze_fleet(streams)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    windows = a.get("windows") or []
    skews = {h["host"]: float(h["clock_skew_ms"])
             for h in a.get("hosts", [])}
    # relative to host 0's clock: skew_h = err_h - err_0, in ms
    expected = {h: (clock_err[h] - clock_err[0]) * 1e3
                for h in range(n_hosts)}
    align_ok = all(abs(skews.get(h, 1e9) - expected[h]) <= 5.0
                   for h in expected)
    return {
        "n_hosts": a.get("n_hosts"),
        "windows": len(windows),
        "straggler_host": (a.get("straggler") or {}).get("host"),
        "straggler_every_window": bool(
            windows and len(windows) == n_windows
            and all(w["slowest_host"] == slow for w in windows)),
        "straggler_consistent": (a.get("straggler") or {})
        .get("consistent"),
        "clock_skew_ms": {str(h): v for h, v in sorted(skews.items())},
        "clock_align_ok": bool(align_ok),
        "loader_worst_host": (a.get("loader") or {}).get("worst_host"),
        "loader_asymmetric": (a.get("loader") or {}).get("asymmetric"),
    }


def _bench_mesh():
    """ISSUE 12 self-validation, backend-independent (both probes run
    as CPU subprocesses so the on-chip bench and the CI smoke measure
    the same thing):

    * **ZeRO-3 memory scaling** — ``tools/mesh_memory_probe.py`` on a
      forced 8-device CPU mesh: per-device param+optimizer-state bytes
      from the committed shardings (exact), corroborated by the
      compiled sharded step's ``memory_analysis`` through
      ``prof.memory`` where the backend exposes it.  main() gates the
      ratio at ~1/shard_count.
    * **multi-host fixture** — ``tools/multihost_smoke.py --nproc 2``:
      REAL processes joined via ``multiproc.initialize`` (gloo
      collectives), bitwise cross-host metric parity, one checkpoint
      shard per host, fleet merge of the two real telemetry streams.
    """
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(f"--xla_force_host_platform_device_count="
                          f"{_MESH_PROBE_DEVICES}"),
               APEX_PROBE_REPO=root)
    out = {}
    probe = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "mesh_memory_probe.py")],
        env=env, capture_output=True, text=True, timeout=600)
    if probe.returncode == 0:
        try:
            out["memory"] = json.loads(probe.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            out["memory"] = {"error": "unparseable probe output"}
    else:
        out["memory"] = {"error": f"probe exited {probe.returncode}",
                         "stderr": probe.stderr[-2000:]}
    smoke = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "multihost_smoke.py"),
         "--nproc", "2"],
        env=dict(os.environ), capture_output=True, text=True, timeout=600)
    try:
        out["multihost"] = json.loads(smoke.stdout)
    except ValueError:
        out["multihost"] = {"ok": False,
                            "error": f"smoke exited {smoke.returncode}",
                            "stderr": smoke.stderr[-2000:]}
    return out


def _bench_checkpoint():
    """ISSUE 9 self-validation: measure ``checkpoint_stall_ms_per_step``
    on one pipelined training loop under three regimes — no
    checkpointing (the wall baseline), the SYNCHRONOUS write (serialize
    + fsync on the loop thread, the v1 shape), and the ASYNC engine
    (snapshot trigger only; serialize/fsync on the writer thread).

    The stall is the summed ON-LOOP-THREAD duration of the save
    triggers divided by steps — a direct measurement of the engine's
    contract ("the loop pays only the snapshot"), robust to host
    contention: a wall-clock difference would also charge the async
    writer's background CPU time to the loop on a CPU backend (where
    XLA compute and the writer share cores), which is exactly the
    regime CI runs this probe in.  Whole-pass walls are recorded for
    context.  main() gates async <= 20% of sync (when sync is
    measurable), and every checkpoint either regime produced must
    validate + restore bitwise against the live state."""
    import shutil
    import tempfile

    from apex_tpu import checkpoint as ckpt_mod
    from apex_tpu import runtime, training
    from apex_tpu.training import make_train_step

    k, n_windows, reps = 4, 8, 3
    # One save per timed pass: a cadence that outruns the writer thread
    # degrades async to sync THROUGH the backpressure path by design —
    # the stall gate measures the sustainable-cadence contract, and the
    # backlog case is the watchdog's checkpoint_stall rule's job.
    save_every = n_windows * k
    rs = np.random.RandomState(0)
    # ~8 MB of fp32 params -> ~32 MB serialized per save under O2
    # (masters + two moments + model copy): enough that a synchronous
    # npz+fsync visibly stalls the loop.
    w0 = rs.randn(1024, 2048).astype(np.float32) / 45.0
    batches = [(rs.randn(16, 1024).astype(np.float32),
                rs.randn(16, 2048).astype(np.float32))
               for _ in range(n_windows * k)]

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    def one_run(mode):
        init_fn, step_fn = make_train_step(
            loss_fn, training.sgd(lr=0.01), opt_level="O2",
            loss_scale="dynamic")
        pipe = runtime.StepPipeline(step_fn, k)
        state = init_fn({"w": jnp.asarray(w0)})
        ck_dir = tempfile.mkdtemp(prefix=f"apex_tpu_bench_ckpt_{mode}_")
        mgr = None
        if mode != "none":
            mgr = ckpt_mod.CheckpointManager(
                ck_dir, every_steps=save_every, keep=2,
                async_write=(mode == "async"))

        gstep = {"n": 0}                        # cumulative across passes
        acc = {"save_s": 0.0, "saves": 0}       # loop-thread trigger time

        def one_pass(state):
            t0 = time.perf_counter()
            for window, n_valid in runtime.window_batches(
                    iter(batches), k):
                state, metrics = pipe.step_window(state, window, n_valid)
                gstep["n"] += n_valid
                if mgr is not None:
                    # cumulative step: the cadence keeps saving on every
                    # timed pass, not only the first.  The time THIS
                    # call holds the loop thread IS the stall under
                    # measurement (sync: snapshot+serialize+fsync;
                    # async: snapshot + any backpressure).
                    ts = time.perf_counter()
                    if mgr.maybe_save(gstep["n"], state):
                        acc["save_s"] += time.perf_counter() - ts
                        acc["saves"] += 1
            _force(metrics)                     # fence the pipeline
            return time.perf_counter() - t0, state

        _, state = one_pass(state)              # compile pass
        acc["save_s"], acc["saves"] = 0.0, 0    # exclude the compile pass
        best = float("inf")
        for _ in range(reps):
            dt, state = one_pass(state)
            best = min(best, dt)
        restored_ok = True
        if mgr is not None:
            # the trailing async writes finish OFF the timed loop; the
            # published checkpoint must still validate and restore the
            # live state bitwise
            if mgr.last_saved != gstep["n"]:
                mgr.save(gstep["n"], state, block=True)
            mgr.wait()
            restored = mgr.restore(like=state)
            restored_ok = restored is not None and all(
                np.array_equal(np.asarray(a, np.float32),
                               np.asarray(b, np.float32))
                for a, b in zip(
                    jax.tree_util.tree_leaves(
                        jax.device_get(restored.state)),
                    jax.tree_util.tree_leaves(jax.device_get(state))))
            mgr.close()
        shutil.rmtree(ck_dir, ignore_errors=True)
        stall_ms = (acc["save_s"] / (reps * n_windows * k) * 1e3
                    if acc["saves"] else 0.0)
        return best, stall_ms, acc["saves"], restored_ok

    steps = n_windows * k
    t_none, _, _, _ = one_run("none")
    t_sync, sync_stall, sync_saves, sync_ok = one_run("sync")
    t_async, async_stall, async_saves, async_ok = one_run("async")
    return {
        "steps_per_pass": steps,
        "save_every_steps": save_every,
        "saves_timed": {"sync": sync_saves, "async": async_saves},
        "baseline_wall_s": round(t_none, 4),
        "sync_wall_s": round(t_sync, 4),
        "async_wall_s": round(t_async, 4),
        "checkpoint_stall_ms_per_step_sync": round(sync_stall, 3),
        "checkpoint_stall_ms_per_step_async": round(async_stall, 3),
        "async_over_sync": (round(async_stall / sync_stall, 3)
                            if sync_stall > 0 else None),
        "async_over_sync_gate": _CKPT_ASYNC_OVER_SYNC_GATE,
        "sync_floor_ms": _CKPT_SYNC_FLOOR_MS,
        "restore_bitwise_ok": bool(sync_ok and async_ok),
    }


def _bench_serving():
    """ISSUE 11 self-validation: a closed-loop load generator against
    the serving engine — submit a burst of mixed-length requests, drive
    the scheduler to completion, and prove the acceptance contracts:

    * **zero compiles after warmup across ALL sequence-length buckets**
      — the engine's jit callables are trace-count pinned at 0 for the
      whole load (every dispatch went through the AOT table) and no
      lookup ever missed;
    * **no failed requests**, including through a MID-LOAD weight
      hot-swap: a new checkpoint published while requests are in
      flight is staged and adopted between decode steps;
    * **post-swap decode output matches the new checkpoint's
      single-request output bitwise** (greedy decode is deterministic,
      so "the swap really took and really serves the new weights" is
      an equality, not a tolerance);
    * throughput (**tokens/sec**) and **p50/p99 request
      latency-under-load** are measured and recorded in
      BENCH_EXTRA/BENCH_SUMMARY.

    Runs on CPU and TPU alike — the contracts are backend-independent
    (absolute rates are only meaningful on chip)."""
    import shutil
    import tempfile

    from apex_tpu import serving
    from apex_tpu.checkpoint import CheckpointManager
    from apex_tpu.models import gpt_tiny
    from apex_tpu.prof import assert_trace_count

    model = gpt_tiny(max_len=128)
    rs = np.random.RandomState(0)
    probe = jnp.asarray(rs.randint(1, 1024, (1, 8)))
    params = model.init(jax.random.PRNGKey(1), probe)["params"]
    params_v2 = jax.tree_util.tree_map(lambda x: x * 1.01, params)

    buckets, page, max_seqs, max_new = (32, 64), 8, 4, 8
    n_requests = 12
    prompts = [rs.randint(1, 1024, (int(n),)).astype(np.int32)
               for n in rs.randint(4, 48, n_requests)]
    ckpt_dir = tempfile.mkdtemp(prefix="apex_tpu_bench_serving_")
    eng = serving.ServingEngine(model, params, buckets=buckets,
                                page_size=page, max_seqs=max_seqs,
                                watch_dir=ckpt_dir, poll_every_s=3600)
    try:
        t0 = time.perf_counter()
        eng.warmup()
        warmup_s = time.perf_counter() - t0
        pins = [assert_trace_count(fn, 0) for fn in eng._jit.values()]
        for p in pins:
            p.__enter__()
        try:
            # phase 1: half the load on the v1 weights
            comps = [eng.submit(p, max_new) for p in prompts[:6]]
            for _ in range(8):
                eng.step()
            # phase 2: publish v2 MID-LOAD; stage + adopt between steps
            mgr = CheckpointManager(ckpt_dir, keep=2, procs=(0, 1),
                                    async_write=False)
            mgr.save(11, params_v2)
            mgr.close()
            staged = eng.watcher.poll_once()
            comps += [eng.submit(p, max_new) for p in prompts[6:]]
            eng.run_until_idle()
            wall = time.perf_counter() - t0 - warmup_s
            results = [c.result(timeout=0) for c in comps]
        finally:
            for p in pins:
                p.__exit__(None, None, None)
        failed = [r for r in results if not r.ok]
        lat = sorted(r.timings["total_s"] for r in results if r.ok)
        tokens = int(eng.stats["tokens_out"])
        # post-swap probe: bitwise vs a fresh engine on the v2 weights
        post = eng.generate([prompts[0]], max_new_tokens=max_new)[0]
        ref_eng = serving.ServingEngine(model, params_v2,
                                        buckets=buckets, page_size=page,
                                        max_seqs=max_seqs)
        ref_eng.warmup(buckets=(post.bucket,))
        ref = ref_eng.generate([prompts[0]], max_new_tokens=max_new)[0]
        ref_eng.close()
        hotswap_ok = (staged and eng.stats["hotswaps"] == 1
                      and np.array_equal(post.tokens, ref.tokens))
        misses = int(eng.stats["aot_misses"])
        tracing = _serving_trace_probe(model, params_v2, buckets, page,
                                       max_seqs, max_new, prompts)
        return {
            "tracing": tracing,
            "n_requests": n_requests,
            "buckets": list(buckets),
            "max_seqs": max_seqs,
            "tokens_out": tokens,
            "tokens_per_s": round(tokens / wall, 2) if wall > 0 else None,
            "warmup_s": round(warmup_s, 3),
            "p50_latency_ms": round(
                _pct(lat, 50.0) * 1e3, 2) if lat else None,
            "p99_latency_ms": round(
                _pct(lat, 99.0) * 1e3, 2) if lat else None,
            "failed_requests": len(failed),
            "aot_misses": misses,
            "zero_compiles_after_warmup": misses == 0,
            "hotswaps": eng.stats["hotswaps"],
            "hotswap_ok": bool(hotswap_ok),
            "decode_steps": eng.stats["decode_steps"],
            "kv_pages_leaked": (
                eng.pages.total_pages - eng.pages.free_pages),
        }
    finally:
        eng.close()
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def _serving_trace_probe(model, params, buckets, page, max_seqs,
                         max_new, prompts):
    """ISSUE 20 self-validation: the request-tracing/SLO surface.

    * **tracing must not steer generation** — tokens with
      ``trace_sample_n=1`` + an SLO fold attached are BITWISE identical
      to a recorder-less engine's over the same prompts (greedy decode
      is deterministic, so this is an equality);
    * **no tracer, no spans** — a telemetry run without a tracer emits
      ZERO ``span`` events (the strict-no-op contract);
    * **overhead** — full sampling + SLO stays within the telemetry
      engine's ``_TEL_OVERHEAD_GATE`` of the recorder-less wall
      (min-of-3 loads on a warmed engine);
    * **offline == online** — ``prof.requests`` re-derives TTFT/TPOT
      percentiles from the stream's ``done`` events within 2% of the
      engine's own in-run reservoirs (both use the one shared
      nearest-rank definition), and reports goodput against the SLO
      spec the run served under.
    """
    import shutil
    import tempfile

    from apex_tpu import serving, telemetry
    from apex_tpu.prof import requests as prof_requests

    probe = prompts[:8]
    slo_spec = "ttft_p99<60s,tpot_p99<60s"   # gates mechanism, not speed
    d = tempfile.mkdtemp(prefix="apex_tpu_bench_trace_")
    stream = os.path.join(d, "serve.jsonl")

    def load(rec, reps):
        eng = serving.ServingEngine(model, params, buckets=buckets,
                                    page_size=page, max_seqs=max_seqs,
                                    telemetry=rec)
        try:
            eng.warmup()
            best, toks = float("inf"), None
            for _ in range(reps):
                t0 = time.perf_counter()
                res = eng.generate(probe, max_new_tokens=max_new)
                best = min(best, time.perf_counter() - t0)
                if toks is None:
                    toks = [np.asarray(r.tokens) for r in res]
            return best, toks
        finally:
            eng.close()

    try:
        wall_off, toks_off = load(None, reps=3)

        # no tracer attached -> the stream must hold zero span events
        rec0 = telemetry.start(os.path.join(d, "notrace.jsonl"),
                               trace_sample_n=0)
        load(rec0, reps=1)
        rec0.close()
        with open(os.path.join(d, "notrace.jsonl")) as f:
            dark_spans = sum(1 for ln in f if '"kind": "span"' in ln)

        rec = telemetry.start(stream, watchdog=True, trace_sample_n=1,
                              slo=slo_spec, example="bench_trace")
        wall_on, toks_on = load(rec, reps=3)
        eng_p = {
            name: rec.metrics.histogram(f"serving_{name}_s")
                     .percentiles((50.0, 99.0))
            for name in ("ttft", "tpot")}
        rec.close()

        bitwise_ok = (len(toks_off) == len(toks_on) and all(
            np.array_equal(a, b) for a, b in zip(toks_off, toks_on)))

        events = prof_requests.load_request_events([stream])
        a = prof_requests.analyze(events, slo=slo_spec)
        spans = sum(1 for e in events if e.get("kind") == "span")
        agree = []
        for name in ("ttft", "tpot"):
            st = (a["requests"] or {}).get(name) or {}
            for q, ms_key in ((0, "p50_ms"), (1, "p99_ms")):
                eng_v, ana_ms = eng_p[name][q], st.get(ms_key)
                if eng_v and ana_ms is not None:
                    agree.append(abs(ana_ms / 1e3 - eng_v) / eng_v * 100)
        slo_res = a.get("slo") or {}
        return {
            "tokens_bitwise_ok": bool(bitwise_ok),
            "zero_spans_without_tracer": dark_spans == 0,
            "overhead_ratio": (round(wall_on / wall_off, 3)
                               if wall_off > 0 else None),
            "overhead_gate": _TEL_OVERHEAD_GATE,
            "span_events": spans,
            "sampled_requests": a.get("n_sampled", 0),
            "analyzer_vs_engine_pct": (round(max(agree), 3)
                                       if agree else None),
            "analyzer_ttft_p99_ms": ((a["requests"] or {}).get("ttft")
                                     or {}).get("p99_ms"),
            "engine_ttft_p99_ms": (round(eng_p["ttft"][1] * 1e3, 3)
                                   if eng_p["ttft"][1] else None),
            "slo_spec": slo_spec,
            "goodput_pct": slo_res.get("goodput_pct"),
            "slo_met": slo_res.get("met"),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list."""
    from apex_tpu.telemetry.metrics import nearest_rank_percentiles
    return nearest_rank_percentiles(sorted_vals, (q,))[0]


def _bench_quant(on_tpu):
    """ISSUE 13 self-validation: the int8 engine's three acceptance
    surfaces, measured on whatever backend runs the bench:

    * **matmul probe** — the calibrated :func:`quantized_matmul` vs the
      bf16 ``jnp.dot`` at a projection-sized shape.  main() gates
      ``o4_over_bf16 <= 1.0`` ON CHIP only (the MXU's int8 path is the
      2x; the CPU jnp fallback pays quantize/dequant with no int8 MAC
      rate to buy it back and is reported, not gated).
    * **LM step probe** — ms/step of the convergence harness's small
      GPT at O2 vs O4 (same model, same data, quantized sites the only
      difference), compile excluded (:func:`_time_steps` warmup).
    * **int8 KV capacity** — pages the pool admits at the SAME HBM
      budget under bf16 vs int8 storage (scales included), plus
      tokens/sec of a real closed-loop generate on both engines.
      Backend-independent gates in main(): capacity ratio >= 1.5 and
      the int8-KV engine completes its load bitwise-greedy with zero
      AOT misses.
    * the committed **CONVERGENCE_QUANT.json** gate file (O4 tracks O2
      on the LM trajectory) — present and green, re-read here so the
      bench fails loudly if the artifact regresses or goes missing.
    """
    import jax.random as jrandom

    from apex_tpu import quant
    from apex_tpu.models import gpt_tiny
    from apex_tpu import serving

    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    import convergence_quant as cq

    out = {}

    # -- matmul probe: calibrated int8 vs the bf16 dot --------------------
    m, k, n = (8192, 4096, 4096) if on_tpu else (2048, 512, 512)
    key = jrandom.PRNGKey(0)
    x = (jrandom.normal(key, (m, k), jnp.float32)).astype(jnp.bfloat16)
    w = (jrandom.normal(jrandom.PRNGKey(1), (k, n), jnp.float32) * 0.05
         ).astype(jnp.bfloat16)
    x_scale = float(np.abs(np.asarray(x, np.float32)).max() / 127.0)

    bf16_mm = jax.jit(lambda a, b: jnp.dot(a, b))
    q_mm = jax.jit(functools.partial(quant.quantized_matmul,
                                     x_scale=x_scale))

    def _mm_ms(fn):
        jax.block_until_ready(fn(x, w))            # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                r = fn(x, w)
            jax.block_until_ready(r)  # jaxlint: disable=J001 -- timing fence: the probe must block until the last matmul completes
            best = min(best, (time.perf_counter() - t0) / 10)
        return best * 1e3

    t_bf16, t_q = _mm_ms(bf16_mm), _mm_ms(q_mm)
    out["matmul"] = {
        "shape": [m, k, n],
        "bf16_ms": round(t_bf16, 3),
        "o4_ms": round(t_q, 3),
        "o4_over_bf16": round(t_q / t_bf16, 3) if t_bf16 > 0 else None,
    }

    # -- LM step probe: O2 vs O4 ms/step on the convergence model ---------
    steps = 12 if on_tpu else 6

    def _lm_ms(opt_level):
        from apex_tpu import training
        from apex_tpu.training import make_train_step
        batches = cq.make_lm_dataset(8, 8, 32, 64)
        params = cq.build_model(None, vocab=64).init(
            jrandom.PRNGKey(0), jnp.asarray(batches[0][:, :-1]))["params"]
        if opt_level == "O4":
            calib = cq.calibrate(params, batches, vocab=64)
            model = cq.build_model(quant.QuantConfig.frozen(calib),
                                   vocab=64)
        else:
            model = cq.build_model(None, vocab=64)

        def loss_fn(p, b):
            logits = model.apply({"params": p}, b[:, :-1])
            logp = jax.nn.log_softmax(
                logits.reshape(-1, 64).astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(
                logp, b[:, 1:].reshape(-1)[:, None], axis=1))

        init_fn, step_fn = make_train_step(loss_fn, training.adam(3e-3),
                                           opt_level=opt_level,
                                           loss_scale="dynamic")
        step = jax.jit(step_fn, donate_argnums=(0,))
        sec, _ = _time_steps(step, init_fn(params),
                             jnp.asarray(batches[0]), steps)
        return sec * 1e3

    out["lm_ms_per_step_o2"] = round(_lm_ms("O2"), 3)
    out["lm_ms_per_step_o4"] = round(_lm_ms("O4"), 3)

    # -- int8 KV: equal-HBM capacity + tokens/sec on a real load ----------
    model = gpt_tiny(max_len=128)
    page = 8
    budget = 64 * 1024 * 1024
    cap_bf16 = serving.kv_cache.pages_for_budget(model, page, budget,
                                                 jnp.bfloat16)
    cap_int8 = serving.kv_cache.pages_for_budget(model, page, budget,
                                                 jnp.int8)
    rs = np.random.RandomState(0)
    probe = jnp.asarray(rs.randint(1, 1024, (1, 8)))
    params = model.init(jrandom.PRNGKey(1), probe)["params"]
    prompts = [rs.randint(1, 1024, (int(ln),)).astype(np.int32)
               for ln in rs.randint(4, 24, 8)]

    def _tokens_per_s(cache_dtype):
        eng = serving.ServingEngine(model, params, buckets=(32,),
                                    page_size=page, max_seqs=4,
                                    cache_dtype=cache_dtype)
        try:
            eng.warmup()
            t0 = time.perf_counter()
            res = eng.generate(prompts, max_new_tokens=8)
            wall = time.perf_counter() - t0
            toks = [tuple(np.asarray(r.tokens).tolist()) for r in res]
            return {
                "tokens_per_s": round(
                    int(eng.stats["tokens_out"]) / wall, 2),
                "kv_bytes_per_token": eng.stats["kv_bytes_per_token"],
                "kv_cache_dtype": eng.kv_cache_dtype,
                "aot_misses": int(eng.stats["aot_misses"]),
            }, toks
        finally:
            eng.close()

    srv_ref, toks_ref = _tokens_per_s(None)
    srv_int8, toks_int8 = _tokens_per_s(jnp.int8)
    agree = sum(a == b for a, b in zip(toks_ref, toks_int8))
    out["kv"] = {
        "page_size": page,
        "budget_mb": budget // (1024 * 1024),
        "pages_bf16": cap_bf16,
        "pages_int8": cap_int8,
        "capacity_ratio": (round(cap_int8 / cap_bf16, 3)
                           if cap_bf16 else None),
        "serving_ref": srv_ref,
        "serving_int8": srv_int8,
        "token_agreement": f"{agree}/{len(prompts)}",
        "int8_aot_misses": srv_int8["aot_misses"],
    }

    # -- the committed convergence gate file ------------------------------
    art_path = os.path.join(root, "CONVERGENCE_QUANT.json")
    try:
        with open(art_path) as f:
            art = json.load(f)
        v = art.get("verdict", {})
        out["convergence"] = {
            "file": "CONVERGENCE_QUANT.json", "ok": bool(v.get("ok")),
            "rel_tail_gap": v.get("rel_tail_gap"),
            "track_tol": v.get("track_tol"),
            "steps": art.get("config", {}).get("steps"),
        }
    except (OSError, ValueError) as e:
        out["convergence"] = {"file": "CONVERGENCE_QUANT.json",
                              "ok": False,
                              "error": f"{type(e).__name__}: {e}"}
    return out


def _bench_tune(on_tpu, ledger=None):
    """ISSUE 14 self-validation: the kernel autotuner end to end.

    For every registered kernel (flash_attention fwd+bwd,
    fused_layer_norm, bn_relu_residual, xentropy, quantized_matmul,
    conv2d fwd+bwd):
    search the config space on this backend (real device timing on
    chip; interpreter-mode probe on CPU so the whole machinery still
    runs in CI), candidate priority driven by the freshest resnet
    roofline ``ledger`` when one was harvested this run.  Recorded per
    kernel: the winning config, default-vs-tuned ms, and
    ``tuned_over_default`` — gated <= 1.0 in main() on EVERY kernel
    (the fallback guarantee: the default config is always a candidate,
    so tuning can only ever match or beat it).  The persisted cache is
    then re-read from disk with the in-memory memo dropped (the
    process-restart probe) and every kernel's lookup must hit.
    """
    import tempfile

    from apex_tpu.tune import measure, registry, store

    registry.load_builtin()
    cache_dir = tempfile.mkdtemp(prefix="apex_tpu_bench_tune_")
    cache_path = os.path.join(cache_dir, "tune_configs.json")
    out = {"kernels": {}, "cache_path": cache_path,
           "device_kind": store.device_kind(),
           "ledger_driven": ledger is not None}
    iters, reps = (5, 3) if on_tpu else (1, 1)
    lookups = []
    for spec in registry.all_specs():
        bound = (measure.bound_from_ledger(ledger, spec)
                 if ledger else None)
        res = measure.tune_kernel(spec, bound=bound,
                                  interpret=not on_tpu,
                                  iters=iters, reps=reps,
                                  path=cache_path)
        out["kernels"][spec.name] = {
            "bucket": res.bucket,
            "bound": res.bound,
            "config": res.config,
            "default_config": res.default_config,
            "default_ms": res.default_ms,
            "tuned_ms": res.best_ms,
            "tuned_over_default": res.tuned_over_default,
            "candidates": res.candidates,
            "rejected_constraint": res.rejected_constraint,
            "rejected_oracle": res.rejected_oracle,
            "truncated": res.truncated,
            "source": res.source,
        }
        lookups.append((spec.name, spec.version, res.bucket))
    # restart-survival probe: only the persisted file may answer
    store.load(cache_path, reload=True)
    out["persisted_ok"] = all(
        store.lookup(name, ver, bucket, path=cache_path) is not None
        for name, ver, bucket in lookups)
    out["max_tuned_over_default"] = max(
        (k["tuned_over_default"] for k in out["kernels"].values()
         if k["tuned_over_default"] is not None), default=None)
    return out


def _bench_examples(on_tpu):
    """Execute the flagship example entry points and distill their own
    printed metrics.  Gates: the run completed, every printed loss is
    finite, and the steady-state throughput is nonzero."""
    out = {}

    # examples/imagenet — the north-star "runs unmodified" claim
    # (reference examples/imagenet/main_amp.py), O2 + dynamic scaling.
    # steps-per-call 16: the device-loop shape (training.chain_steps) —
    # r5 K-sweep: the ~16 ms/call dispatch tax is ~2 ms/step at K=8,
    # ~1 ms at K=16; print-freq 32: each print is a full pipeline-drain
    # + round-trip on the tunnel (~0.5 s), so per-step printing measures
    # the tunnel, not training (127 img/s in round 3 vs 2,570 print-free
    # in round 4).  prof 80 = 5 calls of 16; print cadence 32/16 = every
    # 2nd call, so the LAST call (ci=4) prints and the speed line covers
    # all 80 iters.
    args = (["--synthetic", "-a", "resnet50", "-b", "128", "--opt-level",
             "O2", "--loss-scale", "dynamic", "--prof", "80",
             "--print-freq", "32", "--steps-per-call", "16"] if on_tpu else
            ["--synthetic", "-a", "resnet18", "-b", "8", "--image-size",
             "64", "--opt-level", "O2", "--prof", "5", "--print-freq", "1"])
    # ISSUE 5: record the run's telemetry stream alongside — the offline
    # analyzer's stall/gap attribution is cross-checked against the
    # numbers the example prints (parsed below) in main().
    tel_path = os.path.join(
        __import__("tempfile").gettempdir(),
        f"apex_tpu_bench_imagenet_{os.getpid()}.jsonl")
    args = args + ["--telemetry", tel_path]
    stdout, wall = _run_example("examples/imagenet/main_amp.py", args)
    iters = [(int(i), float(l), float(s))
             for i, l, s in _ITER_RE.findall(stdout)]
    if not iters or "done" not in stdout:
        raise SystemExit(
            f"BENCH EXAMPLE FAILED: imagenet printed no iteration lines\n"
            f"{stdout[-2000:]}")
    losses = [l for _, l, _ in iters]
    if not all(np.isfinite(losses)):
        raise SystemExit(f"BENCH EXAMPLE FAILED: imagenet non-finite loss "
                         f"trajectory {losses}")
    steady = _STEADY_RE.search(stdout)
    bestwin = _BESTWIN_RE.search(stdout)
    out["imagenet_main_amp"] = {
        "argv": " ".join(args),
        "iters_run": iters[-1][0] + 1,
        "first_loss": losses[0], "last_loss": losses[-1],
        # averaged from loop start, i.e. includes the jit compile:
        "img_per_sec_incl_compile": iters[-1][2],
        # post-compile rate the example prints itself (excl 2 warmup
        # iters).  Still includes the example's per-print host syncs,
        # which cost whole round-trips on the tunneled chip — the
        # device-resident step time is resnet50.ms_per_step_o2 above.
        "img_per_sec_steady": float(steady.group(1)) if steady else None,
        # best of 3 post-loop windows (2 calls each) — the min-of-reps
        # policy applied to the example subprocess: robust to the
        # multi-second tunnel stalls a single steady window can eat.
        "img_per_sec_best_window": (float(bestwin.group(1))
                                    if bestwin else None),
        # steady-vs-best-window gap, regression-gated in main() next to
        # the MFU sanity check (ISSUE 2 acceptance: <= 10% on chip).
        "window_gap_pct": _window_gap_pct(
            float(steady.group(1)) if steady else None,
            float(bestwin.group(1)) if bestwin else None),
        # The ISSUE-7 ratio-floor view of the same number (gated in
        # main(): >= _STEADY_OVER_BEST_FLOORS["imagenet"]).
        "steady_over_best_window": (
            round(float(steady.group(1)) / float(bestwin.group(1)), 3)
            if steady and bestwin and float(bestwin.group(1)) else None),
        # Input-engine attribution (ISSUE 3): % of the loop's wall time
        # spent waiting on the loader (0.0 for the pre-staged synthetic
        # pool; real-data runs report PrefetchLoader's measured stall).
        "loader_stall_pct": (float(m.group(1)) if
                             (m := _LOADER_RE.search(stdout)) else None),
        "wall_s": round(wall, 1),
    }
    # Offline analysis of the stream the example just emitted (ISSUE 5):
    # step count, step-time percentiles, and the stall/gap attribution
    # main() validates against the example's own printed numbers.
    try:
        from apex_tpu.prof import timeline
        ta = timeline.analyze(timeline.load_events(tel_path))
        out["imagenet_main_amp"]["telemetry"] = {
            "stream": tel_path,
            "events": ta["n_events"],
            "steps": ta["steps"],
            "step_p50_ms": (ta.get("step_time") or {}).get("p50_ms"),
            "step_p99_ms": (ta.get("step_time") or {}).get("p99_ms"),
            "loader_stall_pct": (ta.get("attribution")
                                 or {}).get("loader_stall_pct"),
            "dispatch_gap_pct": (ta.get("attribution")
                                 or {}).get("dispatch_gap_pct"),
            "retraces": ta["retraces"]["retraces"],
        }
    except Exception as e:            # analysis must never mask the run
        out["imagenet_main_amp"]["telemetry"] = {
            "error": f"{type(e).__name__}: {e}"}

    # examples/dcgan — the three-scaler multi-loss path (BASELINE config
    # 5), now step-pipelined by default (ISSUE 2): the whole iteration —
    # both D backwards, the G phase, and all three dynamic loss-scale
    # machines — is ONE program, chained --steps-per-call iterations per
    # dispatch through runtime.StepPipeline.  The reference-parity
    # imperative surface (amp.initialize num_losses=3 + scale_loss
    # loss_id + FusedAdam.step) remains under --imperative; r05 measured
    # it at 4.67 it/s steady vs 57 best-window — 10 dispatches/iter of
    # pure tunnel tax, which is the gap the pipelined default closes.
    # 64 iters = 8 calls of 8: the steady clock starts after the 2
    # compile calls and covers 48 iters; print-freq 16 = every 2nd call.
    args = (["--niter", "1", "--iters-per-epoch", "64", "--opt_level", "O1",
             "--print-freq", "16", "--steps-per-call", "8"]
            if on_tpu else
            ["--niter", "1", "--iters-per-epoch", "3", "--batchSize", "4",
             "--opt_level", "O1", "--steps-per-call", "2"])
    stdout, wall = _run_example("examples/dcgan/main_amp.py", args)
    pairs = [(float(d), float(g)) for d, g in _DCGAN_RE.findall(stdout)]
    done = _DONE_RE.search(stdout)
    steady = _DCGAN_STEADY_RE.search(stdout)
    if not pairs or not done:
        raise SystemExit(
            f"BENCH EXAMPLE FAILED: dcgan printed no loss/done lines\n"
            f"{stdout[-2000:]}")
    flat = [v for p in pairs for v in p]
    if not all(np.isfinite(flat)):
        raise SystemExit(f"BENCH EXAMPLE FAILED: dcgan non-finite losses")
    best = _DCGAN_BEST_RE.search(stdout)
    # Renamed from dcgan_main_amp_imperative_3scaler: the three-scaler
    # example now runs step-pipelined by default; "mode" records which
    # path produced the numbers.
    out["dcgan_main_amp_3scaler"] = {
        "argv": " ".join(args),
        "mode": ("imperative" if "--imperative" in args else "pipelined"),
        "it_per_sec_incl_compile": float(done.group(2)),
        # min-of-reps policy applied to the loop: the rate it
        # demonstrably achieves (single windows eat tunnel stalls;
        # device work is ~2 ms/iter)
        "it_per_sec_best_window": (float(best.group(1)) if best else None),
        # compile-excluded rate the example prints itself (VERDICT r3
        # next #6); the fused single-program joint-loss step is benched
        # separately in dcgan_fused_joint_step_o2.
        "it_per_sec_steady": float(steady.group(1)) if steady else None,
        # steady-vs-best-window gap (ISSUE 2: this example's 12x gap hid
        # behind the steady number) — regression-gated in main().
        "window_gap_pct": _window_gap_pct(
            float(steady.group(1)) if steady else None,
            float(best.group(1)) if best else None),
        "steady_over_best_window": (
            round(float(steady.group(1)) / float(best.group(1)), 3)
            if steady and best and float(best.group(1)) else None),
        "loader_stall_pct": (float(m.group(1)) if
                             (m := _LOADER_RE.search(stdout)) else None),
        "last_loss_d": pairs[-1][0], "last_loss_g": pairs[-1][1],
        "wall_s": round(wall, 1),
    }
    # Dispatch-budget floor the example computes for itself (VERDICT r4
    # next #6, imperative mode only): programs/iter x ~7 ms + leaves x
    # ~22 us — the tunnel-physics bound the imperative loop's measured
    # rate is judged against.
    floor = _DCGAN_FLOOR_RE.search(stdout)
    if floor:
        out["dcgan_main_amp_3scaler"].update(
            dispatch_floor_ms=float(floor.group(1)),
            dispatch_floor_it_s=float(floor.group(2)))
    return out


def _harvest_or_none(name, step_fn, args, on_tpu):
    """Trace-time roofline cost harvest of one workload's step
    (ISSUE 6) — never fails the bench.  XLA's cost analysis (a lowering)
    only on chip; the jaxpr walk (regions + matmul split) runs
    everywhere."""
    from apex_tpu.prof import roofline

    try:
        return roofline.harvest_costs(step_fn, *args, xla=on_tpu)
    except Exception as e:                           # pragma: no cover
        print(f"{name} roofline harvest failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return None


# Harvested-vs-analytic FLOPs cross-check (ISSUE 6): the jaxpr-walk
# matmul count and the hand-derived formula must agree within 10% or
# one of them is wrong (the gate that keeps the MFU numerator honest
# while the harvested path replaces the hand-coded one).
_HARVEST_XCHECK_TOL = 0.10


def _roofline_entry(harvest, step_time_s, peaks, top=5, memory=None):
    """One workload's MFU ledger for BENCH_EXTRA (top regions by
    modeled device time, MFU, boundedness, and — ISSUE 10 — the
    peak-HBM column when a memory harvest is supplied); never fails
    the bench."""
    if harvest is None:
        return None
    from apex_tpu.prof import roofline

    try:
        return roofline.mfu_ledger(harvest, step_time_s=step_time_s,
                                   peaks=peaks, top=top, memory=memory)
    except Exception as e:                           # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _memory_or_none(name, step_fn, args):
    """Trace/AOT-compile memory harvest of one workload's step
    (ISSUE 10) — never fails the bench and never touches the step's
    own jit cache (harvest_memory compiles its OWN jit instance;
    nothing runs, nothing is donated)."""
    from apex_tpu.prof import memory as memory_mod

    try:
        return memory_mod.harvest_memory(step_fn, *args)
    except Exception as e:                           # pragma: no cover
        print(f"{name} memory harvest failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return None


def _load_prev_bench():
    """Previous round's full bench data (``BENCH_EXTRA.json`` committed at
    the end of the prior round) for the regression guard (VERDICT r3 next
    #4): every headline timing gets a ``vs_prev`` ratio, and ratios > 1.05
    are flagged loudly in the summary instead of sliding silently."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_PREV.json")
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None


def _vs_prev(cur_ms, prev_ms):
    if not prev_ms:
        return None
    return round(cur_ms / prev_ms, 3)


def main():
    # Flags-free instrumentation (ISSUE 10 satellite): APEX_TPU_TELEMETRY
    # (+ APEX_TPU_WATCHDOG / APEX_TPU_METRICS_*) records this whole
    # bench run's stream without any new CLI surface; close() is
    # idempotent and atexit-safe across the gate SystemExits.
    from apex_tpu import telemetry as _tel
    rec_env = _tel.start_from_env(example="bench")
    if rec_env is not None:
        import atexit
        atexit.register(rec_env.close)
    on_tpu = jax.default_backend() == "tpu"
    peak = _chip_peak_flops()
    device_kind = jax.devices()[0].device_kind

    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 32
    iters = 20 if on_tpu else 3

    # Calibrate BEFORE the workloads; repeated after, so every gate uses
    # the max the chip demonstrably reached during THIS bench run and the
    # JSON reports the spread (VERDICT r2 next #3).
    cal_before = _calibrate_peak() if on_tpu else []

    step2, state2, data2, step_fn2 = _make_resnet_step("O2", batch, size)
    # Copy the state BEFORE the donated jitted-per-step timing consumes
    # it; the copies seed the device-loop and pipeline timings below.
    state_dl = jax.tree_util.tree_map(jnp.copy, state2)
    state_pl = jax.tree_util.tree_map(jnp.copy, state2)
    # Roofline cost harvest (ISSUE 6): trace-time FLOP/byte totals +
    # per-region attribution of the SAME step, harvested BEFORE the
    # donated timing consumes the state (pure tracing — nothing runs,
    # nothing is donated).  Joined with the measured step times into
    # per-workload MFU ledgers at the bottom of main().
    harvest_resnet = _harvest_or_none("resnet50", step_fn2,
                                     (state2, data2), on_tpu)
    # HBM ledger of the SAME step (ISSUE 10) — also before the donated
    # timing consumes the state (pure trace + AOT compile analysis).
    mem_resnet = _memory_or_none("resnet50", step_fn2, (state2, data2))
    t_o2, state2 = _time_steps(step2, state2, data2, iters)
    prof_resnet, tp_resnet = (_prof_top_ops(step2, state2, data2)
                              if on_tpu else (None, None))
    # Bytes ledger (VERDICT r4 next #1): measured fusion traffic from the
    # trace just captured vs the model-intrinsic traffic of the SAME step
    # (conv/dot operands+outputs at their dtypes + optimizer-side bytes)
    # — the number that says whether "roofline-bound" is the model's
    # fault or the schedule's.
    ledger_resnet = None
    if tp_resnet is not None:
        try:
            from apex_tpu.prof.ledger import bytes_ledger
            n_par = int(sum(np.prod(l.shape) for l in
                            jax.tree_util.tree_leaves(state2.params)))
            ledger_resnet = bytes_ledger(
                step_fn2, (state2, data2), tp_resnet,
                steps=_PROF_TRACE_STEPS, n_params=n_par, optimizer="sgd")
            # keep the JSON small: top-10 intrinsic layers only
            ledger_resnet["intrinsic"]["by_layer"] = (
                ledger_resnet["intrinsic"]["by_layer"][:10])
        except Exception as e:           # never fail the bench on prof
            ledger_resnet = {"error": f"{type(e).__name__}: {e}"}
    # k=32 (r5 sweep: 50.48 / 48.80 / 47.98 ms/step at k=8/16/32 vs
    # 46.87 traced device — deeper chaining amortizes the ~16 ms/call
    # dispatch tax to <1 ms/step; real TPU loops chain hundreds).
    t_o2_dl = (_time_steps_device_loop(step_fn2, state_dl, data2)
               if on_tpu else t_o2)
    # The user-facing wall rate through runtime.StepPipeline — the
    # ISSUE-2 acceptance pins it within 5% of the device-loop rate
    # (the dispatch gap the step-pipelining runtime exists to close).
    t_o2_pipe = (_time_steps_pipeline(step_fn2, state_pl, data2)
                 if on_tpu else t_o2)
    del step2, state2, data2, state_dl, state_pl
    # O2 precision machinery measured in isolation on the same param tree
    # (cast + unscale/overflow + masked SGD update as ONE program): the
    # honest numerator for "plumbing share of step" — the full-step trace
    # can't attribute it because XLA fuses the update into wgrad convs.
    plumbing_ms = _measure_precision_plumbing() if on_tpu else None
    step0, state0, data0, step_fn0 = _make_resnet_step("O0", batch, size)
    state0_dl = jax.tree_util.tree_map(jnp.copy, state0)
    t_o0, _ = _time_steps(step0, state0, data0, iters)
    t_o0_dl = (_time_steps_device_loop(step_fn0, state0_dl, data0)
               if on_tpu else t_o0)
    del step0, state0, data0, state0_dl

    # Headline img/s, MFU and the O2-vs-O0 ratio all use the device-loop
    # rate (the deployment shape of a TPU training loop) for BOTH opt
    # levels — same harness on both sides; the jitted-per-step wall
    # numbers are reported beside them and carry the cross-round
    # regression guard.
    ips_o2, ips_o0 = batch / t_o2_dl, batch / t_o0_dl
    flops = _resnet_flops_per_step(batch, size)
    implied_o2, implied_o0 = flops / t_o2_dl, flops / t_o0_dl

    # BERT-base FusedAdam O2 — Pallas FusedLayerNorm + xentropy + flash
    # attention on chip.
    b_batch, b_seq = (16, 128) if on_tpu else (2, 32)
    (bstep, bstate, bdata, n_params, n_dense,
     hidden, vocab, bstep_fn) = _make_bert_step(b_batch, b_seq)
    bstate_dl = jax.tree_util.tree_map(jnp.copy, bstate)
    # Harvested BEFORE the donated timing consumes bstate.  The
    # harvest's matmul_flops replaces the hand-coded
    # _bert_flops_per_step estimate as the MFU numerator below
    # (ISSUE 6 satellite); the analytic formula stays as a cross-check
    # gated to 10% agreement.
    harvest_bert = _harvest_or_none("bert", bstep_fn, (bstate, bdata),
                                    on_tpu)
    mem_bert = _memory_or_none("bert", bstep_fn, (bstate, bdata))
    t_bert, bstate = _time_steps(bstep, bstate, bdata, max(iters // 2, 2))
    prof_bert, _tp_b = (_prof_top_ops(bstep, bstate, bdata)
                       if on_tpu else (None, None))
    # Bytes ledger for BERT (r5): the mfu_vs_measured gap is bounded by
    # the NON-matmul intrinsic traffic (Adam state sweep, embedding
    # gathers, LN/residual streams) — same evidence the ResNet-50 ledger
    # gives for "roofline vs schedule".
    ledger_bert = None
    if _tp_b is not None:
        try:
            from apex_tpu.prof.ledger import bytes_ledger
            ledger_bert = bytes_ledger(
                bstep_fn, (bstate, bdata), _tp_b,
                steps=_PROF_TRACE_STEPS, n_params=n_params,
                optimizer="adam")
            ledger_bert["intrinsic"]["by_layer"] = (
                ledger_bert["intrinsic"]["by_layer"][:10])
        except Exception as e:           # never fail the bench on prof
            ledger_bert = {"error": f"{type(e).__name__}: {e}"}
    t_bert_dl = (_time_steps_device_loop(bstep_fn, bstate_dl, bdata)
                 if on_tpu else t_bert)
    del bstep, bstate, bdata, bstate_dl
    # BERT FLOPs/step: the harvested cost analysis is the numerator
    # (ISSUE 6); the hand-derived formula survives as a cross-check —
    # a >10% disagreement means either the harvest walk or the formula
    # drifted, and the bench refuses to report an MFU built on it.
    bert_flops_analytic = _bert_flops_per_step(n_dense, b_batch, b_seq,
                                               hidden, vocab, 12)
    bert_flops, bert_flops_source = bert_flops_analytic, "analytic"
    harvest_vs_analytic = None
    if harvest_bert is not None and harvest_bert.matmul_flops:
        harvest_vs_analytic = (harvest_bert.matmul_flops
                               / bert_flops_analytic)
        if abs(harvest_vs_analytic - 1.0) > _HARVEST_XCHECK_TOL:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: harvested BERT matmul FLOPs "
                f"({harvest_bert.matmul_flops:.3e}, source "
                f"{harvest_bert.source}) disagree with the analytic "
                f"formula ({bert_flops_analytic:.3e}) by "
                f"{abs(harvest_vs_analytic - 1.0) * 100:.1f}% "
                f"(> {_HARVEST_XCHECK_TOL * 100:.0f}% gate) — the MFU "
                f"numerator is not trustworthy; refusing to report.")
        bert_flops = harvest_bert.matmul_flops
        bert_flops_source = f"harvested_{harvest_bert.source}"
    bert_implied = bert_flops / t_bert_dl
    from apex_tpu.normalization.fused_layer_norm import _dispatch_pallas
    from apex_tpu.ops.flash_attention import _KERNEL_MIN_KV
    # Report the kernels the step ACTUALLY dispatches to at this shape:
    # LN routes to jnp below its in-context crossover (r5), like
    # attention below _KERNEL_MIN_KV.  Ask the dispatch itself so the
    # report can't drift from the rule — including the itemsize the gate
    # now keys on: the O2 step feeds LN bf16 activations (itemsize 2).
    bert_kernels = (["xentropy"]
                    + (["fused_layer_norm"]
                       if _dispatch_pallas(b_batch * b_seq, hidden, None,
                                           itemsize=2)
                       else [])
                    + (["flash_attention"] if b_seq >= _KERNEL_MIN_KV
                       else []))

    # Long-context flash attention (beyond-parity): causal fwd+bwd at 8k.
    fa_seq = 8192 if on_tpu else 512
    t_flash, t_block = _bench_flash_attention(fa_seq)

    # FusedAdam whole-model step vs eager per-tensor loop (+ the ISSUE-4
    # bucketed flat-buffer path on the same tree).
    adam_res = _adam_fused_vs_eager(max(iters // 2, 2))
    t_fused = adam_res["fused_s"]
    t_eager = adam_res["eager_s"]
    n_tensors = adam_res["n_tensors"]
    t_adam_dev_ms = adam_res["device_ms"]
    t_adam_chained = adam_res["chained_s"]
    t_adam_bucketed = adam_res["bucketed_s"]
    t_adam_bucketed_dev_ms = adam_res["bucketed_device_ms"]

    # Deep-pytree (>=200-leaf) FusedAdam: leafwise vs bucketed wall +
    # first-compile (ISSUE 4 satellite).
    adam_deep = _adam_deep_pytree(max(iters // 2, 2))

    # DCGAN, both BASELINE-config-5 flavors: the fused single-program O2
    # joint-loss step here; the REAL imperative 3-scaler O1 path is timed
    # through the example subprocess below (VERDICT r2 weak #5 / next #6).
    dstep, dstate, ddata = _make_dcgan_step(batch=64 if on_tpu else 4)
    harvest_dcgan = _harvest_or_none("dcgan", dstep, (dstate, ddata),
                                     on_tpu)
    mem_dcgan = _memory_or_none("dcgan", dstep, (dstate, ddata))
    t_dcgan, _ = _time_steps(dstep, dstate, ddata, max(iters // 2, 2))
    del dstep, dstate, ddata

    # Calibrate AFTER all timed workloads; the gate ceiling is the max the
    # chip demonstrably reached during THIS run and the JSON reports every
    # pass, so the chip's throughput noise is visible (VERDICT r2 next #3).
    cal_after = _calibrate_peak() if on_tpu else []
    cals = cal_before + cal_after
    # max = the sanity-gate ceiling (nothing real may beat the chip's best
    # demonstrated rate); MEDIAN = the MFU denominator (VERDICT r4 weak
    # #3: dividing by the max made MFU wobble with one lucky pass).
    measured_peak = max(cals) if cals else None
    measured_med = float(np.median(cals)) if cals else None

    if measured_peak and measured_peak >= peak:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: calibration measured "
            f"{measured_peak/1e12:.1f} TFLOP/s >= nameplate "
            f"{peak/1e12:.0f} TFLOP/s — the chain was optimized away; "
            f"its rates (and the gates built on them) are meaningless.")
    if on_tpu:
        _gate_implied("ResNet-50 O2", implied_o2, peak, measured_peak)
        _gate_implied("ResNet-50 O0", implied_o0, peak, measured_peak)
        _gate_implied("BERT-base O2", bert_implied, peak, measured_peak)

    extra = {
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "timing_policy": _TIMING_POLICY,
        "peak_bf16_tflops": round(peak / 1e12, 1),
        # Achievable wall-clock bf16 matmul rate measured on THIS chip
        # during THIS run (serial 8k chain, see _calibrate_peak): the
        # honest MFU denominator on a tunneled chip.  MEDIAN of the
        # passes; the [min, max] band is the run-to-run truth and every
        # MFU claim downstream carries it (VERDICT r4 weak #3).
        "measured_matmul_tflops": (round(measured_med / 1e12, 1)
                                   if measured_med else None),
        "measured_matmul_tflops_band": (
            [round(min(cals) / 1e12, 1), round(max(cals) / 1e12, 1)]
            if cals else None),
        "measured_matmul_tflops_spread_pct": (
            round(100 * (max(cals) - min(cals)) / measured_med, 1)
            if cals else None),
        "measured_matmul_tflops_passes": [round(c / 1e12, 1) for c in cals],
        "gate_ceiling_tflops": (round(measured_peak / 1e12, 1)
                                if measured_peak else None),
        "gate_tolerance": _GATE_TOL,
        "resnet50": {
            "batch": batch, "image_size": size, "iters": iters,
            "ms_per_step_o2": round(t_o2 * 1e3, 2),
            # K=8 steps per program (apex_tpu.training.chain_steps): the
            # deployment-shape rate the headline img/s and MFU use.
            "ms_per_step_o2_device_loop": round(t_o2_dl * 1e3, 2),
            # Wall rate of the USER-FACING path (runtime.StepPipeline,
            # K steps/dispatch, deferred metric reads) — the gap between
            # this and the device-loop number is the dispatch tax the
            # step-pipelining runtime leaves on the table.
            "ms_per_step_o2_pipeline_wall": round(t_o2_pipe * 1e3, 2),
            "ms_per_step_o0": round(t_o0 * 1e3, 2),
            "ms_per_step_o0_device_loop": round(t_o0_dl * 1e3, 2),
            "images_per_sec_o2": round(ips_o2, 2),
            "images_per_sec_o0": round(ips_o0, 2),
            "mfu_o2_pct": round(100 * implied_o2 / peak, 1),
            "mfu_o0_pct": round(100 * implied_o0 / peak, 1),
            "mfu_o2_vs_measured_pct": (
                round(100 * implied_o2 / measured_med, 1)
                if measured_med else None),
            # prof dogfood: measured per-op device time for this exact
            # step, via prof.capture.trace + prof.parse.parse_trace.
            "prof_measured": prof_resnet,
            # measured vs intrinsic HBM traffic (prof.ledger)
            "bytes_ledger": ledger_resnet,
            # O2 cast + unscale + masked-SGD update measured as their own
            # on-device program over the same tree (see
            # _measure_precision_plumbing): what the precision machinery
            # actually costs, free of fusion attribution.
            "precision_plumbing_ms": plumbing_ms,
            "precision_plumbing_pct_of_step": (
                round(100 * plumbing_ms / (t_o2 * 1e3), 1)
                if plumbing_ms else None),
        },
        "bert_base_fusedadam": {
            "batch": b_batch, "seq": b_seq, "n_params": n_params,
            "n_dense_params": n_dense,
            "ms_per_step": round(t_bert * 1e3, 2),
            "ms_per_step_device_loop": round(t_bert_dl * 1e3, 2),
            "mfu_pct": round(100 * bert_implied / peak, 1),
            "mfu_vs_measured_pct": (
                round(100 * bert_implied / measured_med, 1)
                if measured_med else None),
            # dispatch-aware (r5): below the measured crossover the
            # attention_impl="flash" surface routes to jnp, so the Pallas
            # attention kernel genuinely does not run in this step.
            "pallas_kernels": (bert_kernels if on_tpu else []),
            "prof_measured": prof_bert,
            "bytes_ledger": ledger_bert,
            # Additive no-overlap decomposition of the step (see
            # _bert_mfu_bound): matmul FLOPs at the measured-median
            # rate + the intrinsic Adam state sweep (30 B/param) at the
            # trace's loop-fusion bandwidth.  Explains where the
            # distance to 100% mfu_vs_measured physically goes; not a
            # ceiling (the schedule overlaps part of the sweep).  Now
            # driven by the HARVESTED FLOPs (ISSUE 6).
            "mfu_additive_model": _bert_mfu_bound(
                ledger_bert, bert_flops, measured_med, prof_bert),
            # FLOPs provenance (ISSUE 6): harvested cost analysis is
            # the MFU numerator; the hand formula is the cross-check
            # (gated to 10% agreement in the self-validation above).
            "flops_source": bert_flops_source,
            "flops_g": round(bert_flops / 1e9, 2),
            "flops_g_analytic": round(bert_flops_analytic / 1e9, 2),
            "harvest_vs_analytic": (round(harvest_vs_analytic, 4)
                                    if harvest_vs_analytic else None),
        },
        "flash_attention_causal": {
            "seq": fa_seq, "heads": 12, "head_dim": 64,
            "flash_ms": round(t_flash * 1e3, 2),
            "blockwise_jnp_ms": round(t_block * 1e3, 2),
            "speedup": round(t_block / t_flash, 2),
        },
        "fused_adam_step": {
            "n_tensors": n_tensors,
            "fused_ms": round(t_fused * 1e3, 3),
            # device time of ONE fused update traced as its own program —
            # the kernel, not the tunnel (the wall number above is ≈790
            # leaves x ~22 us/arg of dispatch tax, VERDICT r4 weak #4):
            "fused_device_ms": t_adam_dev_ms,
            # K=16 updates chained in one program: the amortized wall
            # rate a real train loop sees for the optimizer stage.
            "fused_chained_ms_per_step": round(t_adam_chained * 1e3, 3),
            # ISSUE 4: the flat-bucket path — masters/state/grads cross
            # the jit boundary as a few large per-dtype buffers, so the
            # per-leaf marshalling tax is gone by construction.
            "bucketed_ms": round(t_adam_bucketed * 1e3, 3),
            "bucketed_device_ms": t_adam_bucketed_dev_ms,
            "n_buckets": adam_res["n_buckets"],
            # wall_over_device now tracks the BUCKETED hot path (gated in
            # self-validation, <= _ADAM_WOD_GATE); the leafwise ratio —
            # r05 measured 16.9 wall vs 4.8 device (3.5x) — stays
            # reported for the before/after story.
            "wall_over_device": (
                round(t_adam_bucketed * 1e3 / t_adam_bucketed_dev_ms, 2)
                if t_adam_bucketed_dev_ms else None),
            "wall_over_device_leafwise": (
                round(t_fused * 1e3 / t_adam_dev_ms, 2)
                if t_adam_dev_ms else None),
            "eager_per_tensor_ms": round(t_eager * 1e3, 3),
            "speedup_vs_eager": round(t_eager / t_fused, 2),
        },
        # ISSUE 4 satellite: the >=200-leaf deep-pytree variant, where
        # the O(leaves) wall/compile floors are the whole story.
        "fused_adam_deep": adam_deep,
        # Renamed from "dcgan_two_loss": this is the fused single-program
        # joint-loss step, not the multi-scaler imperative path.
        "dcgan_fused_joint_step_o2": {
            "ms_per_step": round(t_dcgan * 1e3, 2)},
    }

    # Per-workload roofline / MFU ledgers (ISSUE 6): harvested costs
    # joined with the measured step times against THIS run's measured
    # matmul peak — top-5 regions by modeled device time, achieved
    # FLOP/s, and compute-vs-memory boundedness per region.
    from apex_tpu.prof import roofline as _roofline_mod
    peaks = {"flops": (measured_med or peak),
             "hbm_gb_s": _roofline_mod.DEFAULT_HBM_GB_S,
             "source": ("measured_matmul_median" if measured_med
                        else "nameplate_bf16"),
             "bw_source": "fallback_v5e_hbm"}
    extra["resnet50"]["roofline"] = _roofline_entry(
        harvest_resnet, t_o2_dl, peaks, memory=mem_resnet)
    extra["bert_base_fusedadam"]["roofline"] = _roofline_entry(
        harvest_bert, t_bert_dl, peaks, memory=mem_bert)
    extra["dcgan_fused_joint_step_o2"]["roofline"] = _roofline_entry(
        harvest_dcgan, t_dcgan, peaks, memory=mem_dcgan)

    # Peak-HBM self-check (ISSUE 10 acceptance): every workload's ledger
    # must carry a NONZERO peak-HBM column, and the recorded (rounded/
    # json-ified) value must agree with the harvest's own bytes within
    # 10% — where memory_analysis() was available the column IS the
    # compiled accounting, so drift means broken plumbing, not noise.
    for wl_name, wl_key, wl_mem in (
            ("resnet50", "resnet50", mem_resnet),
            ("bert", "bert_base_fusedadam", mem_bert),
            ("dcgan", "dcgan_fused_joint_step_o2", mem_dcgan)):
        entry = extra[wl_key].get("roofline") or {}
        recorded = ((entry.get("total") or {}).get("peak_hbm_gb") or 0.0)
        if wl_mem is None:
            continue                     # harvest failure already printed
        if not recorded:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: {wl_name} roofline ledger "
                f"carries no peak-HBM column despite a successful "
                f"memory harvest ({wl_mem.peak_bytes} bytes, source "
                f"{wl_mem.source}) — the mfu_ledger memory join is "
                f"broken; refusing to report.")
        if wl_mem.peak_bytes and abs(recorded * 1e9 / wl_mem.peak_bytes
                                     - 1.0) > 0.10:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: {wl_name} ledger peak-HBM "
                f"{recorded} GB disagrees with the harvested "
                f"{wl_mem.peak_bytes / 1e9:.6f} GB "
                f"({wl_mem.source}) by more than 10%; refusing to "
                f"report.")
        extra[wl_key]["peak_hbm_gb"] = recorded
        extra[wl_key]["peak_hbm_source"] = wl_mem.source
        if wl_mem.source == "memory_analysis" and wl_mem.peak_bytes:
            # walk-vs-XLA ratio, reported not gated: the conservative
            # walk has no donation/remat, so >= ~1 is expected; << 1
            # would mean the walk under-counts.
            extra[wl_key]["hbm_walk_over_xla"] = round(
                wl_mem.walk_peak_bytes / wl_mem.peak_bytes, 3)

    # Flagship examples as subprocesses on this same device (VERDICT r2
    # next #1/#6): the real entry points under examples/, unmodified.
    extra["examples"] = _bench_examples(on_tpu)

    # Run-telemetry self-validation (ISSUE 5), backend-independent: the
    # disabled path must be a bitwise no-op, instrumentation must cause
    # zero retraces, and the enabled stream must cost within the gate.
    extra["telemetry"] = tel = _bench_telemetry()
    if not tel["bitwise_identical_disabled"]:
        raise SystemExit(
            "BENCH SELF-CHECK FAILED: a telemetry-enabled run produced "
            "different parameters than the disabled run — the recorder "
            "perturbed numerics or dispatch; refusing to report.")
    if not tel["zero_retraces"] or not tel["analyzer_consistent"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: telemetry stream inconsistent "
            f"(zero_retraces={tel['zero_retraces']}, "
            f"analyzer_consistent={tel['analyzer_consistent']}, "
            f"steps={tel['analyzer_steps']}) — instrumentation changed "
            f"compile behavior or the analyzer miscounts; refusing to "
            f"report.")
    if tel["overhead_ratio"] and tel["overhead_ratio"] > _TEL_OVERHEAD_GATE:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: telemetry+watchdog-enabled step "
            f"time is {tel['overhead_ratio']}x the disabled rate "
            f"(> {_TEL_OVERHEAD_GATE}x gate) — the event stream or the "
            f"watchdog fold is back on the hot path (per-step events, a "
            f"stray sync, or an expensive rule); refusing to report.")
    if tel["watchdog_critical_alerts"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the watchdog raised "
            f"{tel['watchdog_critical_alerts']} CRITICAL alert(s) on the "
            f"clean probe loop — a deterministic rule (nonfinite / "
            f"scale_collapse / retrace_storm) is crying wolf; refusing "
            f"to report.")
    if not tel["regress_self_diff_clean"] \
            or not tel["regress_detects_degradation"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: prof.regress self-check "
            f"(self_diff_clean={tel['regress_self_diff_clean']}, "
            f"detects_degradation={tel['regress_detects_degradation']}) "
            f"— the regression differ is either crying wolf on identical "
            f"summaries or blind to a 2x slowdown; refusing to report.")
    exp = tel.get("export") or {}
    if not exp.get("scrape_ok") or not exp.get("textfile_ok"):
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: live metrics export "
            f"(scrape_ok={exp.get('scrape_ok')}, "
            f"textfile_ok={exp.get('textfile_ok')}) — the Prometheus "
            f"endpoint or the atomic textfile did not serve the probe "
            f"loop's instruments; refusing to report.")

    # Fleet-merge self-validation (ISSUE 10): straggler attribution on
    # the synthetic 4-host fixture must name the injected slow host on
    # EVERY window, and the aligner must recover the injected skew.
    extra["fleet"] = flv = _bench_fleet()
    if not flv["straggler_every_window"] \
            or flv["straggler_host"] != 2 or not flv["clock_align_ok"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: prof.fleet attribution "
            f"(straggler_host={flv['straggler_host']}, "
            f"every_window={flv['straggler_every_window']}, "
            f"clock_align_ok={flv['clock_align_ok']}, "
            f"skews={flv['clock_skew_ms']}) — the merge cannot name an "
            f"unambiguous injected straggler or recover a known clock "
            f"skew; refusing to report.")
    # Attribution cross-check: the analyzer's loader stall (read from the
    # LoaderStats.as_dict snapshot in the stream) must agree with the
    # 'loader: stall X%' line the imagenet example printed.
    ex_im = extra["examples"].get("imagenet_main_amp") or {}
    tel_im = ex_im.get("telemetry") or {}
    if (ex_im.get("loader_stall_pct") is not None
            and tel_im.get("loader_stall_pct") is not None
            and abs(ex_im["loader_stall_pct"]
                    - tel_im["loader_stall_pct"]) > _TEL_STALL_TOL_PCT):
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: telemetry stall attribution "
            f"{tel_im['loader_stall_pct']}% disagrees with the example's "
            f"printed {ex_im['loader_stall_pct']}% by more than "
            f"{_TEL_STALL_TOL_PCT} points — the stream and "
            f"format_loader_line no longer share one snapshot; refusing "
            f"to report.")

    # Mesh-frontend self-validation (ISSUE 12), backend-independent:
    # ZeRO-3 must actually divide per-device state bytes by the shard
    # count, and the REAL 2-process multi-host fixture must pass.
    extra["mesh"] = mz = _bench_mesh()
    z3 = (mz.get("memory") or {}).get("zero3") or {}
    if z3.get("ratio") is None or z3["ratio"] > _MESH_Z3_RATIO_GATE:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: ZeRO-3 per-device state ratio "
            f"{z3.get('ratio')} (gate <= {_MESH_Z3_RATIO_GATE} on the "
            f"{_MESH_PROBE_DEVICES}-way probe mesh; "
            f"memory={mz.get('memory')}) — the sharded flat buckets are "
            f"not actually dividing param+optimizer-state memory; "
            f"refusing to report.")
    if not (mz.get("multihost") or {}).get("ok"):
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the 2-process multi-host fixture "
            f"did not pass ({mz.get('multihost')}) — real cross-process "
            f"mesh parity, per-host checkpoint shards, or the fleet "
            f"merge of the two live streams is broken; refusing to "
            f"report.")

    # Async-checkpoint self-validation (ISSUE 9), backend-independent:
    # the engine's whole point is that the loop pays only the snapshot
    # trigger — if the async stall creeps toward the synchronous
    # write's, serialization is back on the loop thread.
    extra["checkpoint"] = ckpt_v = _bench_checkpoint()
    if not ckpt_v["restore_bitwise_ok"]:
        raise SystemExit(
            "BENCH SELF-CHECK FAILED: a checkpoint written during the "
            "stall probe did not restore bitwise against the live "
            "state — the async writer is publishing corrupt or stale "
            "snapshots; refusing to report.")
    if (ckpt_v["checkpoint_stall_ms_per_step_sync"]
            >= _CKPT_SYNC_FLOOR_MS
            and ckpt_v["async_over_sync"] is not None
            and ckpt_v["async_over_sync"] > _CKPT_ASYNC_OVER_SYNC_GATE):
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: async checkpoint stall is "
            f"{ckpt_v['async_over_sync']}x the synchronous write's "
            f"(> {_CKPT_ASYNC_OVER_SYNC_GATE}x gate; "
            f"async {ckpt_v['checkpoint_stall_ms_per_step_async']} vs "
            f"sync {ckpt_v['checkpoint_stall_ms_per_step_sync']} "
            f"ms/step) — serialize/fsync leaked back onto the train "
            f"loop; refusing to report.")

    # Serving-engine self-validation (ISSUE 11), backend-independent:
    # the closed-loop load generator's acceptance contracts — zero
    # compiles after warmup across all buckets, no failed requests, and
    # a mid-load hot-swap that really serves the new weights.
    extra["serving"] = srv = _bench_serving()
    if not srv["zero_compiles_after_warmup"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: serving paid "
            f"{srv['aot_misses']} compile(s) after warmup — an AOT "
            f"bucket key is drifting (signature/static-param mismatch) "
            f"or a dispatch fell off the warmed table; steady-state "
            f"serving must pay ZERO compiles; refusing to report.")
    if srv["failed_requests"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: {srv['failed_requests']} serving "
            f"request(s) failed under the closed-loop load (incl. the "
            f"mid-load hot-swap window) — the scheduler dropped or "
            f"errored requests; refusing to report.")
    if not srv["hotswap_ok"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: mid-load weight hot-swap "
            f"(hotswaps={srv['hotswaps']}) did not produce decode "
            f"output bitwise-matching the new checkpoint's "
            f"single-request output — the watcher staged stale/corrupt "
            f"weights or the swap never took; refusing to report.")
    if srv["kv_pages_leaked"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: {srv['kv_pages_leaked']} KV "
            f"page(s) still held after the load drained — the scheduler "
            f"leaks pages on eviction and a long-running server would "
            f"strand its whole pool; refusing to report.")
    # Request-tracing/SLO self-validation (ISSUE 20), backend-independent.
    trc = srv["tracing"]
    if not trc["tokens_bitwise_ok"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: enabling request tracing "
            f"(trace_sample_n=1 + SLO fold) changed the generated "
            f"tokens — observability steered the decode path; the "
            f"traced engine must be bitwise identical; refusing to "
            f"report.")
    if not trc["zero_spans_without_tracer"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: a telemetry run with NO tracer "
            f"attached emitted span events — the strict-no-op contract "
            f"of the disabled tracing path broke; refusing to report.")
    if trc["overhead_ratio"] and trc["overhead_ratio"] > _TEL_OVERHEAD_GATE:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: serving with full request "
            f"tracing ran {trc['overhead_ratio']}x the recorder-less "
            f"load (> {_TEL_OVERHEAD_GATE}x gate) — span emission "
            f"leaked onto the scheduler hot path; refusing to report.")
    if trc["analyzer_vs_engine_pct"] is None \
            or trc["analyzer_vs_engine_pct"] > 2.0:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: prof.requests re-derived "
            f"TTFT/TPOT {trc['analyzer_vs_engine_pct']}% away from the "
            f"engine's in-run reservoirs (gate 2%; analyzer ttft p99 "
            f"{trc['analyzer_ttft_p99_ms']} vs engine "
            f"{trc['engine_ttft_p99_ms']} ms) — the offline and online "
            f"percentile paths diverged; refusing to report.")
    if trc["goodput_pct"] is None:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the SLO evaluation returned no "
            f"goodput for spec {trc['slo_spec']!r} — the done events "
            f"lost their latency fields or the offline evaluator "
            f"matched zero requests; refusing to report.")

    # int8 engine self-validation (ISSUE 13): equal-HBM KV capacity and
    # the committed convergence artifact are backend-independent gates;
    # the matmul speedup is a chip property (the CPU jnp fallback pays
    # quantize/dequant with no int8 MAC rate to buy it back) and gates
    # on TPU only.
    extra["quant"] = qnt = _bench_quant(on_tpu)
    if not qnt["convergence"]["ok"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the CONVERGENCE_QUANT gate file "
            f"is missing or red ({qnt['convergence']}) — O4 no longer "
            f"tracks O2 on the LM trajectory (or the artifact was never "
            f"recorded); rerun tools/convergence_quant.py; refusing to "
            f"report.")
    if qnt["kv"]["capacity_ratio"] is None \
            or qnt["kv"]["capacity_ratio"] < 1.5:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: int8 KV storage admits only "
            f"{qnt['kv']['capacity_ratio']}x the pages bf16 does at the "
            f"same HBM budget (gate >= 1.5x) — the per-row scale "
            f"overhead outgrew the int8 saving or the byte accounting "
            f"broke; refusing to report.")
    if qnt["kv"]["int8_aot_misses"]:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the int8-KV serving load paid "
            f"{qnt['kv']['int8_aot_misses']} compile(s) after warmup — "
            f"the QuantPool pytree is perturbing the AOT signature; "
            f"steady-state quantized serving must pay ZERO compiles; "
            f"refusing to report.")
    if on_tpu and qnt["matmul"]["o4_over_bf16"] is not None \
            and qnt["matmul"]["o4_over_bf16"] > 1.0:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: the calibrated int8 matmul ran "
            f"{qnt['matmul']['o4_over_bf16']}x the bf16 dot on the "
            f"{qnt['matmul']['shape']} probe — the quantized kernel "
            f"must not be SLOWER than what it replaces on chip "
            f"(dequant epilogue unfused, or the dispatch gate routed a "
            f"probe-sized matmul to jnp); refusing to report.")

    # ISSUE 14: the kernel autotuner, ledger-driven by the resnet
    # roofline harvested above when present.
    extra["tune"] = tn = _bench_tune(
        on_tpu, ledger=(extra.get("resnet50") or {}).get("roofline"))
    for kname, krow in tn["kernels"].items():
        tod = krow.get("tuned_over_default")
        if tod is not None and tod > 1.0:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: tuned {kname} config "
                f"{krow['config']} ran {tod}x the default "
                f"{krow['default_config']} — the default config is "
                f"always a candidate, so the tuner can never pick a "
                f"slower winner (fallback guarantee broken: the "
                f"measurement or the oracle gate regressed); refusing "
                f"to report.")
    if not tn["persisted_ok"]:
        raise SystemExit(
            "BENCH SELF-CHECK FAILED: tuned configs did not survive the "
            "process-restart probe (cache re-read from disk missed at "
            "least one (device kind, kernel, version, bucket) key) — "
            "the persistent tune cache is broken; refusing to report.")

    # Self-validation, same contract as the MFU gates above: a steady
    # rate far below the example's own best window means the hot loop is
    # stalling on dispatch/syncs again (the exact regression class the
    # step-pipelining runtime closed — DCGAN sat at 12x for five
    # rounds).  Target is <= _WINDOW_GAP_TARGET_PCT (ISSUE 2); the gate
    # fails at _WINDOW_GAP_GATE_PCT to absorb the tunnel's pass-to-pass
    # noise (~±18%) while still catching order-of-magnitude stalls.
    if on_tpu:
        for ex_key, label in (("imagenet_main_amp", "imagenet"),
                              ("dcgan_main_amp_3scaler", "dcgan")):
            exd = extra["examples"].get(ex_key) or {}
            gap = exd.get("window_gap_pct")
            if gap is not None and gap > _WINDOW_GAP_GATE_PCT:
                raise SystemExit(
                    f"BENCH SELF-CHECK FAILED: {label} example steady "
                    f"throughput trails its own best window by {gap}% "
                    f"(> {_WINDOW_GAP_GATE_PCT}% gate; target "
                    f"<= {_WINDOW_GAP_TARGET_PCT}%) — the example's hot "
                    f"loop is stalling on dispatch or host syncs; "
                    f"refusing to report.")
            # ISSUE 7: the same contract as a FLOOR on steady/best —
            # with cache.enable + AOT warmup the steady loop no longer
            # has compile excuses, so a ratio under the floor means the
            # warm-start engine (or the dispatch path) regressed.
            ratio = exd.get("steady_over_best_window")
            floor = _STEADY_OVER_BEST_FLOORS[label]
            if ratio is not None and ratio < floor:
                raise SystemExit(
                    f"BENCH SELF-CHECK FAILED: {label} example steady "
                    f"rate is only {ratio}x its own best window "
                    f"(floor {floor}) — the warm-start engine (AOT "
                    f"warmup / persistent cache) or the hot loop's "
                    f"dispatch path has regressed; refusing to report.")
        # ResNet MFU ratchet (ISSUE 14, replacing ISSUE 7's static
        # >26% floor): each round's measured MFU is gated against the
        # PREVIOUS committed bench via prof.regress — the same
        # name-inferred higher-is-better differ CI already runs, so
        # the floor rises automatically with every improvement instead
        # of being re-legislated by hand.  With no comparable previous
        # summary the static constant remains as the backstop.
        resnet_mfus = {
            "mfu_o2_vs_measured_pct":
                extra["resnet50"].get("mfu_o2_vs_measured_pct"),
            "roofline.total.mfu_pct":
                ((extra["resnet50"].get("roofline") or {}).get("total")
                 or {}).get("mfu_pct"),
        }
        prev_bench = _load_prev_bench() or {}
        prev_mfus = {
            "mfu_o2_vs_measured_pct":
                (prev_bench.get("resnet50") or {}).get(
                    "mfu_o2_vs_measured_pct"),
            "roofline.total.mfu_pct":
                (((prev_bench.get("resnet50") or {}).get("roofline")
                  or {}).get("total") or {}).get("mfu_pct"),
        }
        from apex_tpu.prof import regress as _regress
        # The static floor stays the ratchet's LOWER BOUND: re-basing on
        # the raw previous value each round would let the 5%+2pt
        # allowance compound downward release over release (30 -> 26.5
        # -> 23.2 ... each passing individually).  base = max(prev,
        # floor) bounds any drift inside the floor's own tolerance band
        # while genuine improvements keep raising the bar.
        ratchet_base = {k: max(v, _RESNET_MFU_FLOOR_PCT)
                        for k, v in prev_mfus.items()
                        if v is not None and resnet_mfus.get(k) is not None}
        if ratchet_base:
            diff = _regress.diff_summaries(
                {"resnet50": ratchet_base},
                {"resnet50": {k: resnet_mfus[k] for k in ratchet_base}},
                default_tol_pct=_RESNET_MFU_RATCHET_TOL_PCT)
            if diff["regressions"]:
                rows = "; ".join(
                    f"{e['metric']} {e['base']}% -> {e['cur']}%"
                    for e in diff["regressions"])
                raise SystemExit(
                    f"BENCH SELF-CHECK FAILED: ResNet-50 O2 MFU fell "
                    f"below the previous round's ratchet ({rows}; tol "
                    f"{_RESNET_MFU_RATCHET_TOL_PCT}% + pct-point "
                    f"slack) — the conv-path fusion engine or the tuned "
                    f"kernel configs regressed the measured device "
                    f"rate; refusing to report.")
            extra["resnet50"]["mfu_ratchet"] = {
                "base": ratchet_base,
                "tol_pct": _RESNET_MFU_RATCHET_TOL_PCT,
                "improvements": len(diff["improvements"]),
            }
        # The static floor stays a HARD lower bound on every current
        # metric, ratcheted or not: the ratchet's tolerance band sits
        # below its base, so without this a sequence of
        # individually-passing rounds could still decay to ~floor*0.95
        # - slack and camp there — and a metric whose baseline went
        # missing (failed prev harvest) must never lose gating at all.
        for mfu_name, mfu_val in resnet_mfus.items():
            if mfu_val is None:
                continue
            if mfu_val <= _RESNET_MFU_FLOOR_PCT:
                raise SystemExit(
                    f"BENCH SELF-CHECK FAILED: ResNet-50 O2 "
                    f"{mfu_name} {mfu_val}% is not above the "
                    f"{_RESNET_MFU_FLOOR_PCT}% hard floor (the ratchet "
                    f"only ever RAISES the bar from here) — the "
                    f"conv-path fusion engine is not reaching the "
                    f"hot path; refusing to report.")
        # Absolute DCGAN floor (ISSUE 3): a window-gap gate alone can't
        # catch "steady AND best-window both collapsed" — pin steady to
        # >= 3x the r05 imperative baseline.
        dc_steady = (extra["examples"].get("dcgan_main_amp_3scaler")
                     or {}).get("it_per_sec_steady")
        if dc_steady is not None and dc_steady < _DCGAN_STEADY_GATE_IT_S:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: dcgan steady {dc_steady} it/s "
                f"below the {_DCGAN_STEADY_GATE_IT_S:.1f} it/s floor "
                f"(3x the r05 imperative baseline) — the pipelined "
                f"default or the input engine has regressed; refusing "
                f"to report.")
        # FusedAdam dispatch-overhead gates (ISSUE 4): wall/device on the
        # bucketed step, and the deep-tree bucketed speedup.
        adam_wod = extra["fused_adam_step"].get("wall_over_device")
        if adam_wod is not None and adam_wod > _ADAM_WOD_GATE:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: bucketed FusedAdam wall/device "
                f"{adam_wod}x > {_ADAM_WOD_GATE}x gate — per-call dispatch "
                f"overhead is back on the optimizer hot path (the exact "
                f"O(leaves) tax the flat-bucket engine removed); refusing "
                f"to report.")
        deep_speedup = adam_deep.get("speedup_bucketed")
        if deep_speedup is not None and deep_speedup < _ADAM_DEEP_SPEEDUP_GATE:
            raise SystemExit(
                f"BENCH SELF-CHECK FAILED: deep-pytree bucketed FusedAdam "
                f"is only {deep_speedup}x the leafwise wall rate "
                f"(gate >= {_ADAM_DEEP_SPEEDUP_GATE}x, "
                f"{adam_deep['n_leaves']} leaves) — the bucketed path has "
                f"regressed toward per-leaf dispatch; refusing to report.")

    # Regression guard vs the previous round (VERDICT r3 next #4): compare
    # each headline timing against the committed BENCH_PREV.json.
    prev = _load_prev_bench()
    vs_prev = {}
    regressions = []
    if prev and not on_tpu:
        prev = None     # prev numbers are TPU numbers; a CPU smoke run
    if prev and prev.get("timing_policy") != _TIMING_POLICY:
        # Like-for-like only (ADVICE r4): the tunnel swings ±18% pass to
        # pass, so comparing min-of-reps numbers against a prev round's
        # single-pass numbers systematically flatters the ratios.
        extra_note = (f"regression guard skipped: prev timing_policy "
                      f"{prev.get('timing_policy')!r} != {_TIMING_POLICY!r}")
        print(extra_note, file=sys.stderr)
        prev = None
    if prev:            # comparing against them would scream regressions
        pairs = [
            ("resnet50_ms_o2", t_o2 * 1e3,
             (prev.get("resnet50") or {}).get("ms_per_step_o2")),
            ("bert_ms", t_bert * 1e3,
             (prev.get("bert_base_fusedadam") or {}).get("ms_per_step")),
            ("flash_ms", t_flash * 1e3,
             (prev.get("flash_attention_causal") or {}).get("flash_ms")),
            ("fused_adam_ms", t_fused * 1e3,
             (prev.get("fused_adam_step") or {}).get("fused_ms")),
        ]
        for name, cur, prev_ms in pairs:
            r = _vs_prev(cur, prev_ms)
            if r is None:
                continue
            vs_prev[name] = r
            if r > 1.05:
                regressions.append(f"{name} {r}x")
    extra["vs_prev"] = vs_prev or None
    extra["regressions_vs_prev"] = regressions

    # The driver captures only the last ~2,000 chars of stdout (round 3's
    # headline outgrew it -> parsed: null).  Keep the final line SHORT and
    # write the full data to BENCH_EXTRA.json next to this script.
    root = os.path.dirname(os.path.abspath(__file__))
    extra_path = os.path.join(root, "BENCH_EXTRA.json")
    with open(extra_path, "w") as f:
        json.dump(extra, f, indent=1)

    if on_tpu:
        # Regenerate the README perf table from the artifact just written
        # (VERDICT r4 next #8: the stale-README class ends here).  Never
        # fail the bench over documentation.
        try:
            sys.path.insert(0, root)
            from tools.gen_readme_perf import update as _update_readme
            _update_readme()
        except Exception as e:                       # pragma: no cover
            print(f"README regen skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    prof_dev_ms = None
    if prof_resnet and "device_us_per_step" in (prof_resnet or {}):
        prof_dev_ms = round(prof_resnet["device_us_per_step"] / 1e3, 2)
    ex = extra["examples"].get("imagenet_main_amp", {})
    dc = extra["examples"].get("dcgan_main_amp_3scaler", {})
    headline = {
        "metric": "resnet50_amp_o2_images_per_sec_per_chip",
        "value": round(ips_o2, 2),
        "unit": "images/sec",
        "vs_baseline": round(t_o0_dl / t_o2_dl, 3),
        "summary": {
            # The user-facing training-path wall rate (StepPipeline):
            # the ISSUE-2 acceptance compares this against the
            # device-loop rate.  The jitted-PER-STEP wall time (which
            # inherently pays ~7 ms dispatch per step through the
            # tunnel) moved to resnet50_ms_o2_per_step_wall.
            "resnet50_ms_o2_wall": round(t_o2_pipe * 1e3, 2),
            "resnet50_ms_o2_per_step_wall": round(t_o2 * 1e3, 2),
            "resnet50_ms_o2_device_loop": round(t_o2_dl * 1e3, 2),
            "resnet50_ms_o2_device": prof_dev_ms,
            "resnet50_mfu_vs_measured_pct": (
                round(100 * implied_o2 / measured_med, 1)
                if measured_med else None),
            "plumbing_ms": plumbing_ms,
            "bert_ms": round(t_bert * 1e3, 2),
            "bert_ms_device_loop": round(t_bert_dl * 1e3, 2),
            "bert_mfu_vs_measured_pct": (
                round(100 * bert_implied / measured_med, 1)
                if measured_med else None),
            "flash8k_ms": round(t_flash * 1e3, 2),
            "fused_adam_ms": round(t_fused * 1e3, 3),
            "fused_adam_device_ms": t_adam_dev_ms,
            "fused_adam_chained_ms": round(t_adam_chained * 1e3, 3),
            "fused_adam_bucketed_ms": round(t_adam_bucketed * 1e3, 3),
            "fused_adam_wall_over_device": (
                extra["fused_adam_step"].get("wall_over_device")),
            "fused_adam_deep_ms": adam_deep["leafwise_ms"],
            "fused_adam_deep_bucketed_ms": adam_deep["bucketed_ms"],
            "imagenet_example_img_s_steady": ex.get("img_per_sec_steady"),
            "imagenet_example_img_s_best_window": ex.get(
                "img_per_sec_best_window"),
            "imagenet_example_window_gap_pct": ex.get("window_gap_pct"),
            "imagenet_example_loader_stall_pct": ex.get("loader_stall_pct"),
            "dcgan_example_it_s_steady": dc.get("it_per_sec_steady"),
            "dcgan_example_it_s_best_window": dc.get(
                "it_per_sec_best_window"),
            "dcgan_example_window_gap_pct": dc.get("window_gap_pct"),
            "dcgan_example_loader_stall_pct": dc.get("loader_stall_pct"),
            "zero3_state_ratio_8way": ((extra["mesh"].get("memory") or {})
                                       .get("zero3") or {}).get("ratio"),
            "multihost_fixture_ok": (extra["mesh"].get("multihost")
                                     or {}).get("ok"),
            "serving_tokens_per_s": extra["serving"].get("tokens_per_s"),
            "serving_p99_latency_ms": (
                extra["serving"].get("p99_latency_ms")),
            "serving_trace_overhead_ratio": (
                extra["serving"]["tracing"].get("overhead_ratio")),
            "serving_goodput_pct": (
                extra["serving"]["tracing"].get("goodput_pct")),
            "serving_ttft_p99_ms": (
                extra["serving"]["tracing"].get("analyzer_ttft_p99_ms")),
            "quant_matmul_o4_over_bf16": (
                extra["quant"]["matmul"].get("o4_over_bf16")),
            "quant_lm_ms_per_step_o4": (
                extra["quant"].get("lm_ms_per_step_o4")),
            "quant_kv_capacity_ratio": (
                extra["quant"]["kv"].get("capacity_ratio")),
            "quant_serving_tokens_per_s_int8kv": (
                extra["quant"]["kv"]["serving_int8"].get("tokens_per_s")),
            "tune_max_tuned_over_default": (
                extra["tune"].get("max_tuned_over_default")),
            "tune_kernels_persisted": (
                len(extra["tune"]["kernels"])
                if extra["tune"].get("persisted_ok") else 0),
            "telemetry_overhead_ratio": (
                extra["telemetry"].get("overhead_ratio")),
            "telemetry_step_p50_ms": (
                (ex.get("telemetry") or {}).get("step_p50_ms")),
            "measured_matmul_tflops": (
                round(measured_med / 1e12, 1) if measured_med else None),
            "measured_matmul_tflops_band": (
                [round(min(cals) / 1e12, 1), round(max(cals) / 1e12, 1)]
                if cals else None),
            "vs_prev": vs_prev or None,
            "regressions_vs_prev": regressions,
        },
        # Top-level too (not only in summary): the regression guard must
        # survive the truncation fallback below.
        "regressions_vs_prev": regressions,
        "extra_file": "BENCH_EXTRA.json",
    }
    # The headline as its own artifact: the cross-run regression
    # differ's current-side input — docker/run_matrix.sh diffs it
    # against the checked-in BENCH_r05.json baseline (ISSUE 7 CI
    # satellite), so a throughput regression fails the matrix instead
    # of only being visible inside BENCH_EXTRA.  On-chip runs only: a
    # CPU smoke summary would diff CPU walls against TPU baselines and
    # turn every matrix run red.
    if on_tpu:
        with open(os.path.join(root, "BENCH_SUMMARY.json"), "w") as f:
            json.dump(headline, f, indent=1)

    line = json.dumps(headline)
    if len(line) > 1500:     # belt-and-braces: never outgrow the driver
        del headline["summary"]
        line = json.dumps(headline)
    print(line)


if __name__ == "__main__":
    main()
