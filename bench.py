"""apex_tpu benchmark — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 images/sec/chip at amp O2
(bf16 compute, fp32 masters, fused SGD update) — one fully-jitted train
step per iteration, synthetic ImageNet-shaped data.

``vs_baseline``: the reference publishes no numbers (BASELINE.md) and the
amp-O0 fp32 run on the same chip is the only in-repo baseline, so we report
the O2/O0 speedup (>1.0 means mixed precision is paying for itself).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _make_step(opt_level, batch, image_size=224, num_classes=1000):
    from apex_tpu import training
    from apex_tpu.models import ResNet50
    from apex_tpu.training import make_train_step

    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    model = ResNet50(num_classes=num_classes, dtype=dtype)
    x = jnp.ones((batch, image_size, image_size, 3), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    tx = training.sgd(lr=0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       has_model_state=True)
    state = init_fn(params, batch_stats)
    step = jax.jit(step_fn, donate_argnums=(0,))
    return step, state, (x, y)


def _time_steps(step, state, batch, warmup=3, iters=20):
    for _ in range(warmup):
        state, m = step(state, batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    jax.block_until_ready(m["loss"])
    return (time.perf_counter() - t0) / iters


def main():
    on_tpu = jax.default_backend() == "tpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 32
    iters = 20 if on_tpu else 5

    step2, state2, data2 = _make_step("O2", batch, size)
    t_o2 = _time_steps(step2, state2, data2, iters=iters)
    ips_o2 = batch / t_o2

    step0, state0, data0 = _make_step("O0", batch, size)
    t_o0 = _time_steps(step0, state0, data0, iters=iters)

    print(json.dumps({
        "metric": "resnet50_amp_o2_images_per_sec_per_chip",
        "value": round(ips_o2, 2),
        "unit": "images/sec",
        "vs_baseline": round(t_o0 / t_o2, 3),
    }))


if __name__ == "__main__":
    main()
