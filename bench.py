"""apex_tpu benchmark — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): ResNet-50 images/sec/chip at amp O2
(bf16 compute, fp32 masters, fused SGD update) — one fully-jitted train
step per iteration, synthetic ImageNet-shaped data.  Secondary metrics
(in ``extra``): amp-O0 fp32 baseline, BERT-base FusedAdam train step
(exercises the Pallas FusedLayerNorm + xentropy kernels on chip,
BASELINE config 4), FusedAdam whole-model step vs an eager per-tensor
loop, and DCGAN multi-loss O1 (BASELINE config 5).

Honesty contract (VERDICT r1 "What's weak" #1):

* On this TPU path (axon tunnel) ``jax.block_until_ready`` is a NO-OP —
  round 1 timed dispatch, not compute (101,959 img/s ≈ 6x chip peak).
  Every timing here forces execution with a real device->host scalar
  fetch that depends on the final step's full output chain.
* The emitted JSON self-validates: implied model TFLOP/s must be below
  the chip's bf16 peak or the bench fails loudly instead of reporting.
* The config that actually ran (backend, batch, image size, ms/step,
  MFU) is part of the JSON, so a degraded CPU run is distinguishable
  from the headline TPU metric (ADVICE r1 #4).
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAKS = (
    ("v5 lite", 197e12),
    ("v6 lite", 918e12),
    ("v5", 459e12),      # v5p
    ("v4", 275e12),
    ("v3", 123e12),
)


def _chip_peak_flops():
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAKS:
        if key in kind:
            return peak
    return 197e12  # conservative default


def _calibrate_peak(iters=30):
    """Measure the chip's *achievable* bf16 matmul rate with a canonical
    4k x 4k x 4k loop fully inside one program (no per-step dispatch).

    Why: nameplate peak (197 TFLOP/s on v5e) is the spec-sheet number; a
    tunneled/virtualized chip can deliver a fraction of it (measured ~29
    TFLOP/s on the axon tunnel).  Reporting MFU against both denominators
    separates "our program wastes the chip" from "the chip is capped".
    """
    n = 4096
    a = jnp.asarray(np.random.RandomState(0).randn(n, n), jnp.bfloat16)
    b = jnp.asarray(np.random.RandomState(1).randn(n, n), jnp.bfloat16)

    @jax.jit
    def run(a, b):
        def it(i, acc):
            # keep the iteration-dependence perturbation in bf16 — adding
            # the f32 acc directly would promote the operand and time an
            # f32 matmul instead of the bf16 MXU rate.
            c = (a + (acc * 0).astype(a.dtype)) @ b
            return acc + c[0, 0].astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, it, jnp.zeros((), jnp.float32))

    float(run(a, b))                       # compile + warm
    t0 = time.perf_counter()
    float(run(a, b))
    dt = (time.perf_counter() - t0) / iters
    return 2 * n ** 3 / dt


def _force(tree):
    """Force execution via one scalar device->host fetch
    (``block_until_ready`` is a no-op on the axon tunnel).  The device
    executes enqueued programs in order, so fetching a single output of the
    LAST enqueued program drains the whole pipeline; touching every leaf
    would instead enqueue hundreds of eager ops inside the timed window."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    return float(jnp.ravel(leaves[-1])[0].astype(jnp.float32))


def _time_steps(step, state, batch, iters, warmup=3):
    for _ in range(warmup):
        state, m = step(state, batch)
    _force((m["loss"], state))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    _force((m["loss"], state))      # full chain: metrics AND final state
    return (time.perf_counter() - t0) / iters


# -- ResNet-50 (headline, BASELINE configs 1-2) -------------------------------

def _resnet_flops_per_step(batch, image_size):
    """Analytic ResNet-50 training FLOPs: ~4.09 GFLOP forward per 224x224
    image (multiply+add counted separately), x3 for fwd+bwd."""
    return 3 * 4.089e9 * (image_size / 224.0) ** 2 * batch


def _make_resnet_step(opt_level, batch, image_size=224, num_classes=1000):
    from apex_tpu import training
    from apex_tpu.models import ResNet50
    from apex_tpu.training import make_train_step

    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    model = ResNet50(num_classes=num_classes, dtype=dtype)
    x = jnp.asarray(np.random.RandomState(0).rand(
        batch, image_size, image_size, 3), jnp.float32)
    y = jnp.asarray(np.arange(batch) % num_classes)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    tx = training.sgd(lr=0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       has_model_state=True)
    state = init_fn(params, batch_stats)
    step = jax.jit(step_fn, donate_argnums=(0,))
    return step, state, (x, y)


# -- BERT-base FusedAdam (BASELINE config 4; Pallas layernorm + xentropy) -----

def _bert_flops_per_step(n_params, batch, seq, hidden, layers):
    dense = 6 * n_params * batch * seq            # fwd+bwd matmul-dominated
    attn = 3 * layers * 4 * seq * seq * hidden * batch
    return dense + attn


def _make_bert_step(batch=16, seq=128):
    from apex_tpu import training
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models import bert_base
    from apex_tpu.training import make_train_step

    # attention_impl="flash": the Pallas flash-attention kernel on TPU
    # (falls back to the jnp blockwise path off-TPU).
    model = bert_base(dtype=jnp.bfloat16, num_classes=None,
                      attention_impl="flash")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 30522, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, 30522, (batch, seq)))
    variables = model.init(jax.random.PRNGKey(0), ids)
    params = variables["params"]
    n_params = sum(np.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(params))

    emb_kernel = params["word_embeddings"]["embedding"]

    def loss_fn(p, b):
        ids_b, labels_b = b
        feats = model.apply({"params": p}, ids_b)          # [b, s, h] fp32
        logits = feats @ p["word_embeddings"]["embedding"].T  # tied head
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]),
            labels_b.reshape(-1), smoothing=0.1, padding_idx=-1)
        return jnp.mean(losses)

    tx = training.adam(lr=1e-4)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2")
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    hidden = emb_kernel.shape[1]
    return step, state, (ids, labels), int(n_params), hidden


# -- FusedAdam whole-model step vs eager per-tensor loop ----------------------

def _adam_fused_vs_eager(iters):
    """BASELINE metric 'FusedAdam step time vs eager': one jitted
    whole-model update (the multi-tensor capability) vs a per-tensor
    dispatch loop (the analog of an unfused eager optimizer)."""
    from apex_tpu.models import bert_base
    from apex_tpu.optimizers import functional as F

    model = bert_base(dtype=jnp.bfloat16, num_classes=None)
    ids = jnp.asarray(np.zeros((1, 16), np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-4, p.dtype), params)

    # fused: whole pytree in ONE program
    state = F.adam_init(params)
    fused = jax.jit(functools.partial(F.adam_update, lr=1e-3))

    def run_fused(params, state):
        return fused(grads, state, params)

    p, s = run_fused(params, state)
    _force(p)
    t0 = time.perf_counter()
    p, s = params, state
    for _ in range(iters):
        p, s = run_fused(p, s)
    _force(p)
    t_fused = (time.perf_counter() - t0) / iters

    # eager: one dispatch per tensor (same math), jit per shape
    @jax.jit
    def one(g, p, m, v, t):
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3
        gf = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * gf
        v = b2 * v + (1 - b2) * gf * gf
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        return (p - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype), m, v

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_g = jax.tree_util.tree_leaves(grads)
    ms = [jnp.zeros(l.shape, jnp.float32) for l in leaves_p]
    vs = [jnp.zeros(l.shape, jnp.float32) for l in leaves_p]

    def run_eager(ps, ms, vs, t):
        out_p, out_m, out_v = [], [], []
        for g, pp, m, v in zip(leaves_g, ps, ms, vs):
            npp, nm, nv = one(g, pp, m, v, t)
            out_p.append(npp); out_m.append(nm); out_v.append(nv)
        return out_p, out_m, out_v

    ps2, ms2, vs2 = run_eager(leaves_p, ms, vs, 1.0)   # compile all shapes
    _force(ps2)
    t0 = time.perf_counter()
    ps2, ms2, vs2 = leaves_p, ms, vs
    for i in range(iters):
        ps2, ms2, vs2 = run_eager(ps2, ms2, vs2, float(i + 1))
    _force(ps2)
    t_eager = (time.perf_counter() - t0) / iters

    return t_fused, t_eager, len(leaves_p)


# -- long-context flash attention (beyond-parity, SURVEY §5) ------------------

def _bench_flash_attention(seq, batch=1, heads=12, head_dim=64, iters=10):
    """Causal fwd+bwd of the Pallas flash kernel vs the jnp blockwise
    oracle at long context — the long-sequence story on one chip."""
    from apex_tpu.ops.attention import blockwise_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, seq, heads, head_dim),
                           jnp.bfloat16) for _ in range(3))

    def timed(fn):
        loss = lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = g(q, k, v)
        _force(out[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, k, v)
        _force(out[0])
        return (time.perf_counter() - t0) / iters

    t_flash = timed(lambda q, k, v: flash_attention(q, k, v, causal=True))
    t_block = timed(lambda q, k, v: blockwise_attention(q, k, v, causal=True))
    return t_flash, t_block


# -- DCGAN multi-loss O1 (BASELINE config 5) ----------------------------------

def _make_dcgan_step(batch=64):
    from apex_tpu import training
    from apex_tpu.models import Discriminator, Generator
    from apex_tpu.training import make_train_step

    gen = Generator(dtype=jnp.bfloat16)
    disc = Discriminator(dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    z = jax.random.normal(rng, (batch, 100), jnp.float32)
    real = jnp.asarray(np.random.RandomState(0).rand(
        batch, 64, 64, 3), jnp.float32)
    gv = gen.init(rng, z, train=False)
    gp, g_bs = gv["params"], gv["batch_stats"]
    fake0 = gen.apply(gv, z, train=False)
    dv = disc.init(rng, fake0, train=False)
    dp, d_bs = dv["params"], dv["batch_stats"]

    from apex_tpu.ops.losses import binary_cross_entropy_with_logits

    def bce(logits, target):
        return binary_cross_entropy_with_logits(
            logits, jnp.full(logits.shape, target), reduction="mean")

    def loss_fn(params, b):
        z_b, real_b = b
        g = {"params": params["gen"], "batch_stats": g_bs}
        d = {"params": params["disc"], "batch_stats": d_bs}
        fake = gen.apply(g, z_b, train=False)
        d_loss = (bce(disc.apply(d, real_b, train=False), 1.0)
                  + bce(disc.apply(d, jax.lax.stop_gradient(fake),
                                   train=False), 0.0))
        g_loss = bce(disc.apply(d, fake, train=False), 1.0)
        return d_loss + g_loss       # two losses, one multi-model step

    tx = training.adam(lr=2e-4, beta1=0.5)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                      loss_scale="dynamic")
    state = init_fn({"gen": gp, "disc": dp})
    return jax.jit(step_fn, donate_argnums=(0,)), state, (z, real)


def main():
    on_tpu = jax.default_backend() == "tpu"
    peak = _chip_peak_flops()
    device_kind = jax.devices()[0].device_kind

    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 32
    iters = 20 if on_tpu else 3

    step2, state2, data2 = _make_resnet_step("O2", batch, size)
    t_o2 = _time_steps(step2, state2, data2, iters)
    del step2, state2, data2
    step0, state0, data0 = _make_resnet_step("O0", batch, size)
    t_o0 = _time_steps(step0, state0, data0, iters)
    del step0, state0, data0

    ips_o2, ips_o0 = batch / t_o2, batch / t_o0
    flops = _resnet_flops_per_step(batch, size)
    implied_o2, implied_o0 = flops / t_o2, flops / t_o0
    if on_tpu:
        for name, implied in [("O2", implied_o2), ("O0", implied_o0)]:
            if implied >= peak:
                raise SystemExit(
                    f"BENCH SELF-CHECK FAILED: ResNet-50 {name} implies "
                    f"{implied/1e12:.1f} TFLOP/s > chip peak "
                    f"{peak/1e12:.0f} TFLOP/s ({device_kind}) — the timing "
                    f"loop did not force execution; refusing to report.")

    measured_peak = _calibrate_peak() if on_tpu else None

    extra = {
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "peak_bf16_tflops": round(peak / 1e12, 1),
        # Achievable bf16 matmul rate measured on THIS chip (see
        # _calibrate_peak): the honest MFU denominator on a tunneled chip.
        "measured_matmul_tflops": (round(measured_peak / 1e12, 1)
                                   if measured_peak else None),
        "resnet50": {
            "batch": batch, "image_size": size, "iters": iters,
            "ms_per_step_o2": round(t_o2 * 1e3, 2),
            "ms_per_step_o0": round(t_o0 * 1e3, 2),
            "images_per_sec_o0": round(ips_o0, 2),
            "mfu_o2_pct": round(100 * implied_o2 / peak, 1),
            "mfu_o0_pct": round(100 * implied_o0 / peak, 1),
            "mfu_o2_vs_measured_pct": (
                round(100 * implied_o2 / measured_peak, 1)
                if measured_peak else None),
        },
    }

    # BERT-base FusedAdam O2 — Pallas FusedLayerNorm + xentropy on chip.
    b_batch, b_seq = (16, 128) if on_tpu else (2, 32)
    bstep, bstate, bdata, n_params, hidden = _make_bert_step(b_batch, b_seq)
    t_bert = _time_steps(bstep, bstate, bdata, max(iters // 2, 2))
    del bstep, bstate, bdata
    bert_flops = _bert_flops_per_step(n_params, b_batch, b_seq, hidden, 12)
    bert_implied = bert_flops / t_bert
    if on_tpu and bert_implied >= peak:
        raise SystemExit(
            f"BENCH SELF-CHECK FAILED: BERT implies "
            f"{bert_implied/1e12:.1f} TFLOP/s > peak {peak/1e12:.0f}.")
    extra["bert_base_fusedadam"] = {
        "batch": b_batch, "seq": b_seq, "n_params": n_params,
        "ms_per_step": round(t_bert * 1e3, 2),
        "mfu_pct": round(100 * bert_implied / peak, 1),
        "pallas_kernels": (["fused_layer_norm", "xentropy", "flash_attention"]
                           if on_tpu else []),
    }

    # Long-context flash attention (beyond-parity): causal fwd+bwd at 8k.
    fa_seq = 8192 if on_tpu else 512
    t_flash, t_block = _bench_flash_attention(fa_seq)
    extra["flash_attention_causal"] = {
        "seq": fa_seq, "heads": 12, "head_dim": 64,
        "flash_ms": round(t_flash * 1e3, 2),
        "blockwise_jnp_ms": round(t_block * 1e3, 2),
        "speedup": round(t_block / t_flash, 2),
    }

    # FusedAdam whole-model step vs eager per-tensor loop.
    t_fused, t_eager, n_tensors = _adam_fused_vs_eager(max(iters // 2, 2))
    extra["fused_adam_step"] = {
        "n_tensors": n_tensors,
        "fused_ms": round(t_fused * 1e3, 3),
        "eager_per_tensor_ms": round(t_eager * 1e3, 3),
        "speedup_vs_eager": round(t_eager / t_fused, 2),
    }

    # DCGAN multi-model multi-loss (config 5).
    dstep, dstate, ddata = _make_dcgan_step(batch=64 if on_tpu else 4)
    t_dcgan = _time_steps(dstep, dstate, ddata, max(iters // 2, 2))
    del dstep, dstate, ddata
    extra["dcgan_two_loss"] = {"ms_per_step": round(t_dcgan * 1e3, 2)}

    print(json.dumps({
        "metric": "resnet50_amp_o2_images_per_sec_per_chip",
        "value": round(ips_o2, 2),
        "unit": "images/sec",
        "vs_baseline": round(t_o0 / t_o2, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
