"""Flash-vs-jnp attention crossover sweep (VERDICT r4 next #2).

The Pallas flash kernel wins at long context (~3x at seq 8k) and LOSES at
short context: at BERT's seq 128 the per-kernel-launch overhead and the
1024^2-tuned block machinery cannot beat one fused XLA softmax over a
[B,H,128,128] score tensor that fits VMEM outright.  This sweep measures
fwd+bwd wall time of the three implementations over (seq, heads*batch,
head_dim, causal) on the real chip and prints a JSON table; the measured
crossover is baked into ``apex_tpu.ops.flash_attention`` as the default
dispatch rule (and documented in ``docs/attention.md``).

Run on the TPU host::

    python tools/attention_sweep.py --out ATTENTION_SWEEP.json

Timing policy: min-of-3 passes of ``iters`` fwd+bwd calls, execution
forced by a scalar fetch (block_until_ready is a no-op through the axon
tunnel — see bench.py's honesty contract).
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), _os.pardir)))

import jax
import jax.numpy as jnp

# One timing policy, one implementation: reuse bench.py's execution-forcing
# fetch (block_until_ready is a no-op through the tunnel) so the sweep's
# numbers stay comparable to the bench numbers the README cites.
from bench import _force  # noqa: E402


def time_grad(fn, q, k, v, iters=10, reps=3):
    """Min-of-reps seconds per fwd+bwd call — bench.py's `timed` policy
    (see _bench_flash_attention) applied to a 3-arg grad."""
    loss = lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    out = g(q, k, v)
    _force(out[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, k, v)
        _force(out[0])
        # jaxlint: disable=J009 -- fenced by bench._force(out[0]) on the line above; the linter's sync-def resolution is module-local and cannot see through the import
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def sweep(configs, iters=10):
    from apex_tpu.ops.attention import blockwise_attention
    from apex_tpu.ops.attention import dot_product_attention
    from apex_tpu.ops.flash_attention import flash_attention

    rows = []
    rng = np.random.RandomState(0)
    for cfg in configs:
        b, s, h, d, causal = (cfg["batch"], cfg["seq"], cfg["heads"],
                              cfg["head_dim"], cfg["causal"])
        q, k, v = (jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
                   for _ in range(3))
        row = dict(cfg)
        # full (materialized scores) — skip where the [B,H,T,S] tensor
        # would blow HBM (fp32 scores + softmax residents, fwd AND bwd)
        score_gb = 4 * b * h * s * s / 1e9
        if score_gb < 4.0:
            row["full_ms"] = round(time_grad(
                lambda q, k, v: dot_product_attention(q, k, v,
                                                      causal=causal),
                q, k, v, iters) * 1e3, 3)
        row["blockwise_ms"] = round(time_grad(
            lambda q, k, v: blockwise_attention(q, k, v, causal=causal),
            q, k, v, iters) * 1e3, 3)
        # flash kernel at candidate block sizes (block <= seq only)
        best_flash, best_blk = None, None
        for blk in cfg.get("blocks", [128, 256, 512, 1024]):
            if blk > s:
                continue
            t = time_grad(
                lambda q, k, v, blk=blk: flash_attention(
                    q, k, v, causal=causal, block_q=blk, block_k=blk),
                q, k, v, iters) * 1e3
            row[f"flash_{blk}_ms"] = round(t, 3)
            if best_flash is None or t < best_flash:
                best_flash, best_blk = t, blk
        if best_flash is None:         # no candidate block tiles this seq
            row["flash_best_ms"] = None
            row["kernel_wins"] = False
        else:
            row["flash_best_ms"] = round(best_flash, 3)
            row["flash_best_block"] = best_blk
            # jaxlint: disable=J001 -- best_flash is time_grad's host float (min-of-reps seconds), not a device value
            row["kernel_wins"] = bool(
                best_flash < min(row.get("full_ms", float("inf")),
                                 row["blockwise_ms"]))
        row["jnp_best_ms"] = round(min(row.get("full_ms", float("inf")),
                                       row["blockwise_ms"]), 3)
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--quick", action="store_true",
                    help="CPU smoke: tiny shapes, interpret-free jnp only")
    args = ap.parse_args()

    if args.quick:
        configs = [dict(batch=2, seq=128, heads=2, head_dim=64,
                        causal=False, blocks=[128])]
    else:
        configs = []
        # BERT-shaped batch (b16 h12 d64) at fine-grained short seqs —
        # where the crossover lives; non-causal (encoder) AND causal.
        for causal in (False, True):
            for s in (128, 256, 512, 1024, 2048):
                configs.append(dict(batch=16, seq=s, heads=12, head_dim=64,
                                    causal=causal))
        # long-context single-batch (the flash headline shape), causal.
        for s in (4096, 8192):
            configs.append(dict(batch=1, seq=s, heads=12, head_dim=64,
                                causal=True))
        # head_dim=128 spot checks (GPT-ish) at the crossover region.
        for s in (256, 512, 1024):
            configs.append(dict(batch=8, seq=s, heads=16, head_dim=128,
                                causal=True))

    rows = sweep(configs, iters=args.iters)
    out = {"device_kind": jax.devices()[0].device_kind,
           "backend": jax.default_backend(),
           "timing_policy": "min_of_3_passes",
           "iters": args.iters,
           "rows": rows}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    print(json.dumps({"n_rows": len(rows),
                      "kernel_wins_from_seq": min(
                          [r["seq"] for r in rows if r["kernel_wins"]],
                          default=None)}))


if __name__ == "__main__":
    main()
