"""Deep trajectory gates for SHARDED topologies (VERDICT r4 next #7).

``tools/convergence.py`` proves 8-way DP tracks single-process at 150-step
depth; the five other topologies in ``__graft_entry__.dryrun_multichip``
run one step each.  This tool trains two of them — dp × tp
(Megatron-style tensor parallelism, ``apex_tpu/parallel/
tensor_parallel.py``) and ZeRO-1 (optimizer-state sharding,
``apex_tpu/parallel/zero.py``) — for 100+ steps on the virtual CPU mesh
and gates the loss trajectory against the SAME shard_map program on a
1-device mesh (the honest single-process oracle: identical code path,
only the mesh factorization differs, so the comparison isolates
sharding/reduction order exactly like the DP gate).

Two-tier structure (same rationale as ``convergence.gate_dp``):

* O0 / fp32: per-step head gate at near-reduction-order tolerance.
* O2 / bf16: statistical tail gate only (bf16 amplifies epsilon-level
  reduction-order differences chaotically; see the r5 DP controls).

Run::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/convergence_sharded.py --steps 120 \
      --out CONVERGENCE_SHARDED_r05.json

Reference anchor: the L1 cross-product-distributed suite
(``/root/reference/tests/L1/cross_product_distributed/run.sh``) trains
real epochs under DDP; these gates are its analog for the beyond-parity
topologies.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), _os.pardir)))

try:
    from tools.convergence import gate_dp  # imported as a package module
except ImportError:
    from convergence import gate_dp        # run as a script from tools/


def _cpu_devices(n):
    import jax
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise SystemExit(
            f"need {n} CPU devices, found {len(devs)} — run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    return devs[:n]


def run_dp_tp(opt_level, steps, *, dp, tp, batch=32, seq=16, log_every=50):
    """One loss curve of the toy transformer under dp × tp sharding.
    ``dp=tp=1`` is the single-process oracle (same program, 1-device
    mesh)."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import training
    from apex_tpu.parallel import tp_mlp, tp_self_attention
    from apex_tpu.training import make_train_step

    V, D, H, E, C = 256, 64, 4, 16, 10
    rng = np.random.RandomState(0)
    params = {
        "emb": jnp.asarray(rng.randn(V, D) * 0.05, jnp.float32),
        "wqkv": jnp.asarray(rng.randn(D, 3, H, E) * 0.05, jnp.float32),
        "wo": jnp.asarray(rng.randn(H * E, D) * 0.05, jnp.float32),
        "w1": jnp.asarray(rng.randn(D, 4 * D) * 0.05, jnp.float32),
        "b1": jnp.zeros((4 * D,), jnp.float32),
        "w2": jnp.asarray(rng.randn(4 * D, D) * 0.05, jnp.float32),
        "b2": jnp.zeros((D,), jnp.float32),
        "head": jnp.asarray(rng.randn(D, C) * 0.05, jnp.float32),
    }
    pspec = {
        "emb": P(), "wqkv": P(None, None, "tp"), "wo": P("tp", None),
        "w1": P(None, "tp"), "b1": P("tp"), "w2": P("tp", None),
        "b2": P(), "head": P(),
    }
    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32

    def loss_fn(p, batch_):
        ids, y = batch_
        x = p["emb"][ids].astype(dtype)
        x = x + tp_self_attention(x, p["wqkv"], p["wo"],
                                  H // tp, "tp", causal=True)
        x = x + tp_mlp(x, p["w1"], p["b1"], p["w2"], p["b2"], "tp")
        # first-token (CLS-style) pooling: the label is a function of
        # ids[:, 0], so it is linearly decodable from x[:, 0] and the
        # curve actually falls at gate depth (mean pooling diluted the
        # signal 1/seq and the loss sat at ~ln C for 120 steps)
        logits = x[:, 0].astype(jnp.float32) @ p["head"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    tx = training.sgd(lr=0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=opt_level,
        loss_scale="dynamic" if opt_level == "O2" else None,
        axis_name=("data",))
    state = init_fn(params)

    devices = _cpu_devices(dp * tp)
    mesh = Mesh(np.array(devices).reshape(dp, tp), ("data", "tp"))
    # TrainState spec: params (and every optimizer-state subtree that
    # mirrors them — masters, momentum) carry the tp sharding; scalars
    # stay replicated.  Same scaffold as __graft_entry__._run_step_on_mesh.
    from apex_tpu.training import TrainState
    params_struct = jax.tree_util.tree_structure(state.params)

    def spec_of(node):
        if jax.tree_util.tree_structure(node) == params_struct:
            return pspec
        if hasattr(node, "_fields"):
            return type(node)(*[spec_of(getattr(node, f))
                                for f in node._fields])
        return P()

    state_spec = TrainState(params=pspec, opt_state=spec_of(state.opt_state),
                            scaler=P(), model_state=P())

    step = jax.jit(shard_map(
        step_fn, mesh=mesh,
        in_specs=(state_spec, (P("data"), P("data"))),
        out_specs=(state_spec, P())), donate_argnums=(0,))

    n_batches = 8
    xs = [jnp.asarray(rng.randint(0, V, (batch, seq))) for _ in
          range(n_batches)]
    # Labels derived FROM the sequence (first token id mod C): a learnable
    # structured task — random labels on random sequences were not
    # memorizable by the 1-layer model at gate depth, leaving the
    # "learned" criterion vacuously red.
    ys = [x[:, 0] % C for x in xs]
    losses = []
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, (xs[i % n_batches], ys[i % n_batches]))
        losses.append(jnp.ravel(metrics["loss"])[0])
        if log_every and i % log_every == 0:
            print(f"  [dp{dp}xtp{tp}/{opt_level}] step {i} "
                  f"loss {float(losses[-1]):.4f}", flush=True)
    return ([float(v) for v in np.asarray(jnp.stack(losses))],
            time.perf_counter() - t0)


def run_zero1(opt_level, steps, *, shards, batch=64, log_every=50):
    """One loss curve of a 3-layer MLP under ZeRO-1 optimizer-state
    sharding over ``shards`` devices; ``shards=1`` is the oracle."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import training
    from apex_tpu.parallel.zero import zero1, zero1_partition_spec
    from apex_tpu.training import make_train_step

    Din, Dh, C = 64, 128, 10
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(Din, Dh) * 0.1, jnp.float32),
        "b1": jnp.zeros((Dh,), jnp.float32),
        "w2": jnp.asarray(rng.randn(Dh, Dh) * 0.1, jnp.float32),
        "b2": jnp.zeros((Dh,), jnp.float32),
        "w3": jnp.asarray(rng.randn(Dh, C) * 0.1, jnp.float32),
        "b3": jnp.zeros((C,), jnp.float32),
    }
    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32

    def loss_fn(p, batch_):
        x, y = batch_
        h = jax.nn.relu(x.astype(dtype) @ p["w1"].astype(dtype)
                        + p["b1"].astype(dtype))
        h = jax.nn.relu(h @ p["w2"].astype(dtype) + p["b2"].astype(dtype))
        logits = (h @ p["w3"].astype(dtype)).astype(jnp.float32) + p["b3"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    tx = zero1(training.adam(1e-2), "data", num_shards=shards)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=opt_level,
        loss_scale="dynamic" if opt_level == "O2" else None,
        axis_name="data")
    state = init_fn(params)

    devices = _cpu_devices(shards)
    mesh = Mesh(np.array(devices), ("data",))
    from apex_tpu.training import TrainState
    zspec = zero1_partition_spec(state.opt_state, "data")
    state_spec = TrainState(params=P(), opt_state=zspec,
                            scaler=P(), model_state=P())

    step = jax.jit(shard_map(
        step_fn, mesh=mesh,
        in_specs=(state_spec, (P("data"), P("data"))),
        out_specs=(state_spec, P())), donate_argnums=(0,))

    n_batches = 8
    xs = [jnp.asarray(rng.randn(batch, Din), jnp.float32) for _ in
          range(n_batches)]
    ys = [jnp.asarray(rng.randint(0, C, (batch,))) for _ in
          range(n_batches)]
    losses = []
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, (xs[i % n_batches], ys[i % n_batches]))
        losses.append(jnp.ravel(metrics["loss"])[0])
        if log_every and i % log_every == 0:
            print(f"  [zero1x{shards}/{opt_level}] step {i} "
                  f"loss {float(losses[-1]):.4f}", flush=True)
    return ([float(v) for v in np.asarray(jnp.stack(losses))],
            time.perf_counter() - t0)


def run_gates(steps, *, dp=4, tp=2, zero_shards=8, head=6, tail=30,
              log_every=50):
    """All four curve pairs + two-tier verdicts; returns the artifact."""
    import jax
    cpu0 = _cpu_devices(1)[0]
    out = {"config": {"steps": steps, "dp": dp, "tp": tp,
                      "zero_shards": zero_shards,
                      "backend": "cpu (virtual mesh)"}}
    verdicts = {}
    with jax.default_device(cpu0):
        for topo in ("dp_tp", "zero1"):
            curves = {}
            for lvl in ("O0", "O2"):
                if topo == "dp_tp":
                    curves[f"{lvl}_single"], _ = run_dp_tp(
                        lvl, steps, dp=1, tp=1, log_every=log_every)
                    curves[f"{lvl}_sharded"], _ = run_dp_tp(
                        lvl, steps, dp=dp, tp=tp, log_every=log_every)
                else:
                    curves[f"{lvl}_single"], _ = run_zero1(
                        lvl, steps, shards=1, log_every=log_every)
                    curves[f"{lvl}_sharded"], _ = run_zero1(
                        lvl, steps, shards=zero_shards,
                        log_every=log_every)
            v = {
                "o0": gate_dp(curves["O0_single"], curves["O0_sharded"],
                              head=head, tail=tail, head_gate=True),
                "o2": gate_dp(curves["O2_single"], curves["O2_sharded"],
                              head=head, tail=tail, head_gate=False),
            }
            v["ok"] = v["o0"]["ok"] and v["o2"]["ok"]
            verdicts[topo] = v
            out[f"losses_{topo}"] = {k: [round(x, 5) for x in c]
                                     for k, c in curves.items()}
    out["verdicts"] = verdicts
    out["ok"] = all(v["ok"] for v in verdicts.values())
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    art = run_gates(args.steps)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(art, f)
    print(json.dumps({"sharded_convergence_ok": art["ok"],
                      **{k: v["ok"] for k, v in art["verdicts"].items()}}))
    if not art["ok"]:
        raise SystemExit("SHARDED CONVERGENCE GATE FAILED")


if __name__ == "__main__":
    main()
