"""Real 2-process CPU multi-host fixture (ISSUE 12 gate).

Spawns ``--nproc`` worker processes with distinct process ids, each of
which joins the distributed runtime through
``apex_tpu.parallel.multiproc.initialize`` (env autodetect, gloo CPU
collectives), builds the SAME global :class:`MeshPlan` per process, and
trains a small ZeRO-sharded model with REAL cross-process collectives.
The parent then validates the whole multi-host story end to end:

* **mesh parity** — every worker's loss trajectory is bitwise identical
  (the replicated metrics of one SPMD program), and matches a
  single-process run of the same global mesh within float tolerance;
* **per-host checkpoint shards** — ``CheckpointManager`` (process
  identity from ``multiproc``, not ad-hoc ``jax.process_index``) wrote
  one shard + manifest part per host, and the merged checkpoint
  validates;
* **fleet merge** — ``prof.fleet`` merges the two REAL telemetry
  streams (not the synthetic fixture) and attributes both hosts.

Run directly (CI lane in ``docker/run_matrix.sh``)::

    python tools/multihost_smoke.py --nproc 2

Exit 0 + a JSON verdict on stdout; ``bench.py`` invokes it as the
multi-process self-validation gate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEVS_PER_PROC = 2
STEPS = 6


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_code() -> str:
    # One source file for worker AND single-process reference: the
    # reference simply skips initialize() and sees all devices locally.
    return WORKER


WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["APEX_SMOKE_REPO"])
import jax
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
import jax.numpy as jnp

from apex_tpu import telemetry, training
from apex_tpu.checkpoint import CheckpointManager
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel import multiproc

out_dir = os.environ["APEX_SMOKE_OUT"]
role = os.environ["APEX_SMOKE_ROLE"]          # "worker" | "reference"
steps = int(os.environ["APEX_SMOKE_STEPS"])

if role == "worker":
    pid, nproc = multiproc.initialize()       # env autodetect
    env_rank = int(os.environ["JAX_PROCESS_ID"])
    assert pid == env_rank, (pid, env_rank)
    assert multiproc.process_identity() == (pid, nproc)
else:
    pid, nproc = 0, 1

world = jax.device_count()
plan = M.MeshPlan(dp=1, fsdp=world)

rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(6, 4) * 0.3, jnp.float32),
          "b": jnp.zeros((4,), jnp.float32)}
x_global = rng.randn(8 * world, 6).astype(np.float32)
y_global = (rng.randn(8 * world, 4) * 0.1).astype(np.float32)


def loss_fn(p, batch):
    xb, yb = batch
    pred = xb @ p["w"].astype(jnp.float32) + p["b"].astype(jnp.float32)
    return jnp.mean((pred - yb) ** 2)


rec = None
if role == "worker":
    rec = telemetry.start(os.path.join(out_dir, f"host{pid}.jsonl"),
                          meta={"fixture": "multihost_smoke"})

ms = M.make_mesh_train_step(loss_fn, training.adam(1e-2), plan,
                            zero=3, opt_level="O2", loss_scale="dynamic")
state = ms.init(params)
step = ms.jit_step(state, donate=False)

# each process feeds its host-local slice; device_put_batch globalizes
per = x_global.shape[0] // nproc
sl = slice(pid * per, (pid + 1) * per)
batch = plan.device_put_batch((jnp.asarray(x_global[sl]),
                               jnp.asarray(y_global[sl])))

losses = []
for _ in range(steps):
    state, metrics = step(state, batch)
    losses.append(float(np.ravel(jax.device_get(metrics["loss"]))[0]))  # jaxlint: disable=J001 -- fixture verdict: the replicated loss is the cross-process parity evidence

# replicated parameter checksum: psum of local squared chunks
from jax import lax
from jax.sharding import PartitionSpec as P
spec = ms.state_spec(state)


def sqsum(pk):
    acc = jnp.float64(0.0) if jax.config.read("jax_enable_x64") \
        else jnp.float32(0.0)
    for b in pk.data:
        acc = acc + lax.psum(jnp.sum(jnp.square(b)), plan.fsdp_axis)
    return acc


check = jax.jit(plan.shard_map(sqsum, in_specs=(spec.params,),
                               out_specs=P()))(state.params)
param_sqsum = float(np.ravel(jax.device_get(check))[0])  # jaxlint: disable=J001 -- fixture verdict read

ck_ok = None
if role == "worker":
    mgr = CheckpointManager(os.path.join(out_dir, "ckpt"), keep=1)
    assert mgr.procs == (pid, nproc), (mgr.procs, pid, nproc)
    store = ms.store()
    mgr.save(steps, state, block=True,
             bucket_layout=plan.bucket_layout(store))
    mgr.close()
    ck_ok = True
    rec.close()

with open(os.path.join(out_dir, f"result_{role}_{pid}.json"), "w") as f:
    json.dump({"role": role, "pid": pid, "nproc": nproc, "world": world,
               "losses": losses, "param_sqsum": param_sqsum,
               "is_coordinator": multiproc.is_coordinator(),
               "checkpoint": ck_ok}, f)
print(f"{role} {pid}/{nproc} done", flush=True)
"""


def run(nproc: int = 2, out_dir: str = None, verbose: bool = True) -> dict:
    import shutil
    import tempfile

    own_dir = out_dir is None
    if own_dir:
        out_dir = tempfile.mkdtemp(prefix="apex_tpu_multihost_")
    os.makedirs(out_dir, exist_ok=True)
    worker_py = os.path.join(out_dir, "_worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER)

    sys.path.insert(0, REPO)
    from apex_tpu.parallel.multiproc import worker_env

    base = dict(os.environ)
    base.pop("XLA_FLAGS", None)
    base.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={DEVS_PER_PROC}",
        APEX_SMOKE_REPO=REPO, APEX_SMOKE_OUT=out_dir,
        APEX_SMOKE_STEPS=str(STEPS))

    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(nproc):
        env = worker_env(rank, nproc, coordinator, base=base)
        env["APEX_SMOKE_ROLE"] = "worker"
        log = open(os.path.join(out_dir, f"worker_{rank}.log"), "w")
        procs.append((rank, subprocess.Popen(
            [sys.executable, worker_py], env=env,
            stdout=log, stderr=subprocess.STDOUT), log))
    # single-process reference over the SAME global device count
    ref_env = dict(base)
    ref_env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{DEVS_PER_PROC * nproc}")
    ref_env["APEX_SMOKE_ROLE"] = "reference"
    ref_log = open(os.path.join(out_dir, "reference.log"), "w")
    ref = subprocess.Popen([sys.executable, worker_py], env=ref_env,
                           stdout=ref_log, stderr=subprocess.STDOUT)

    failures = []
    for rank, p, log in procs:
        rc = p.wait(timeout=600)
        log.close()
        if rc != 0:
            failures.append(f"worker {rank} exited {rc}")
    rc = ref.wait(timeout=600)
    ref_log.close()
    if rc != 0:
        failures.append(f"reference exited {rc}")
    if failures and verbose:
        for rank in range(nproc):
            lp = os.path.join(out_dir, f"worker_{rank}.log")
            if os.path.exists(lp):
                print(f"--- worker {rank} log ---", file=sys.stderr)
                sys.stderr.write(open(lp).read()[-4000:])
        lp = os.path.join(out_dir, "reference.log")
        if os.path.exists(lp):
            print("--- reference log ---", file=sys.stderr)
            sys.stderr.write(open(lp).read()[-4000:])

    verdict = {"nproc": nproc, "devs_per_proc": DEVS_PER_PROC,
               "steps": STEPS, "spawn_failures": failures}
    if not failures:
        results = {}
        for path in glob.glob(os.path.join(out_dir, "result_*.json")):
            with open(path) as f:
                r = json.load(f)
            results[(r["role"], r["pid"])] = r
        workers = [results[("worker", i)] for i in range(nproc)]
        reference = results[("reference", 0)]
        # 1) bitwise across hosts: one SPMD program's replicated metrics
        verdict["parity_bitwise_across_hosts"] = all(
            w["losses"] == workers[0]["losses"]
            and w["param_sqsum"] == workers[0]["param_sqsum"]
            for w in workers[1:])
        # 2) vs single-process same-mesh reference (collective impls
        # differ: gloo ring vs local — tolerance, not bitwise)
        ref_l = reference["losses"]
        w_l = workers[0]["losses"]
        verdict["max_rel_loss_diff_vs_single"] = max(
            abs(a - b) / max(abs(a), 1e-12) for a, b in zip(ref_l, w_l))
        verdict["parity_vs_single_process"] = (
            verdict["max_rel_loss_diff_vs_single"] < 1e-5)
        verdict["coordinator_elected_once"] = (
            sum(1 for w in workers if w["is_coordinator"]) == 1
            and workers[0]["is_coordinator"])
        # 3) per-host checkpoint shards
        from apex_tpu.checkpoint import latest_checkpoint
        step_dir = latest_checkpoint(os.path.join(out_dir, "ckpt"))
        shards = (sorted(glob.glob(os.path.join(step_dir, "shard_*.npz")))
                  if step_dir else [])
        verdict["checkpoint_valid"] = step_dir is not None
        verdict["checkpoint_shards"] = len(shards)
        # 4) fleet merge over the two REAL streams
        try:
            from apex_tpu.prof import fleet
            streams = fleet.load_fleet(
                [os.path.join(out_dir, "host*.jsonl")])
            merged = fleet.analyze_fleet(streams)
            verdict["fleet_n_hosts"] = merged.get("n_hosts")
            verdict["fleet_hosts_attributed"] = (
                len(merged.get("hosts") or []) == nproc)
            by_axis = ((merged.get("collectives") or {})
                       .get("by_axis") or {})
            verdict["fleet_axes_attributed"] = sorted(by_axis)
        except Exception as e:                       # pragma: no cover
            verdict["fleet_error"] = f"{type(e).__name__}: {e}"
        verdict["ok"] = bool(
            verdict["parity_bitwise_across_hosts"]
            and verdict["parity_vs_single_process"]
            and verdict["coordinator_elected_once"]
            and verdict["checkpoint_valid"]
            and verdict["checkpoint_shards"] == nproc
            and verdict.get("fleet_n_hosts") == nproc)
    else:
        verdict["ok"] = False
    if own_dir and verdict["ok"]:
        shutil.rmtree(out_dir, ignore_errors=True)
    elif not verdict["ok"]:
        verdict["out_dir"] = out_dir
    return verdict


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--nproc", type=int, default=2)
    parser.add_argument("--out-dir", default=None)
    args = parser.parse_args(argv)
    verdict = run(args.nproc, args.out_dir)
    print(json.dumps(verdict, indent=1))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
