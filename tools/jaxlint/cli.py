"""jaxlint CLI — ``python -m tools.jaxlint <paths...>``.

Exit status: 0 when every file is clean (or every finding is waived
with a reason), 1 when there are findings, 2 on usage errors.  This is
the contract ``tests/test_lint.py`` gates tier-1 on.
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from .linter import RULES, lint_paths


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="Tracing-safety & dtype-discipline static analyzer "
                    "for the apex_tpu stack (rules J001-J007; see "
                    "docs/jaxlint.md).")
    ap.add_argument("paths", nargs="*",
                    help="files or directory trees to lint "
                         "(e.g. apex_tpu examples tools bench.py)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--select", metavar="CODES", default=None,
                    help="comma-separated rule codes to report "
                         "(default: all)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    if not args.paths:
        ap.print_usage()
        print("error: no paths given (and --list-rules not requested)")
        return 2

    try:
        findings = lint_paths(args.paths)
    except FileNotFoundError as e:
        print(f"error: {e}")
        return 2
    if args.select:
        keep = {c.strip() for c in args.select.split(",")}
        findings = [f for f in findings if f.rule in keep]
    for f in findings:
        print(f.render())
    if findings:
        by_rule: dict = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items()))
        n_adv = sum(1 for f in findings if f.advisory)
        print(f"jaxlint: {len(findings)} finding(s) ({summary})")
        if n_adv == len(findings):
            # Advisory-only (e.g. J011 fusion advice): reported but not
            # a failure — the code is correct, just slower than the
            # fused path the message names.
            print(f"jaxlint: all {n_adv} advisory — not failing")
            return 0
        return 1
    print("jaxlint: clean")
    return 0
