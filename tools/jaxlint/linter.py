"""jaxlint core — AST rules, waiver handling, and the lint engine.

Rules J001–J015 tuned to this codebase's failure modes (the ones that are
invisible to pytest and surface as 10x dispatch-floor regressions in
``bench.py``):

* **J001** host sync in device code: ``jax.device_get`` / ``.item()`` /
  ``.block_until_ready()`` / ``float()/int()/bool()/np.asarray()`` on
  array values.  In library code (``apex_tpu/``) every occurrence is a
  finding unless the enclosing function is on the host-boundary
  allowlist (``state_dict``/``load_state_dict`` — serialization is
  host-side by contract); in driver scripts (``examples/``, ``tools/``,
  ``bench.py``, ``tests/``) only syncs inside loop bodies are findings
  (a driver legitimately syncs once at the end, but a per-iteration
  sync is the hot-loop stall the ROADMAP's dispatch floors measure).
* **J002** ``jax.jit`` of a function taking non-array Python args
  (bool/str-typed or bool/str-defaulted params) without covering them
  with ``static_argnums``/``static_argnames``.
* **J003** fp32 dtype leaks inside bf16/amp-cast paths: a function that
  touches ``bfloat16`` and casts to ``float32`` without any
  compensating downcast keeps the wide dtype alive downstream; also
  ``jnp.float32(...)`` literal promotion inside arithmetic.
* **J004** retracing hazards: a jitted callable invoked with the loop
  induction variable (a fresh Python scalar per iteration → one
  retrace per iteration), or ``jax.jit`` itself called inside a loop.
* **J005** use-after-donate: a buffer passed at a ``donate_argnums``
  position of a jitted callable and read again afterwards (donated
  buffers are invalidated by XLA aliasing).
* **J006** Python control flow (``if``/``while``) branching on traced
  values inside a jitted function — trace-time concretization errors,
  or worse, silent trace-time specialization.
* **J007** per-step host staging: ``jax.device_put`` / ``np.asarray`` /
  ``jnp.asarray`` applied to batch data (a loop target drawn from a
  host iterable — a loader/stream) inside a loop body.  Host->device
  staging belongs in the input engine
  (:class:`apex_tpu.data.PrefetchLoader` / ``stage_windows``), where it
  overlaps compute, not on the hot loop where it serializes with it
  (ISSUE 3: the input-side twin of the J001 sync stalls).
* **J008** per-leaf host syncs in loops over pytree leaves: a J001-class
  sync (``float()``/``.item()``/``np.asarray``/``device_get``) inside a
  loop whose iterable comes from ``jax.tree_util.tree_leaves`` /
  ``tree_flatten`` — e.g. ``float(leaf_norm)`` per grad leaf.  One sync
  per step caps throughput at a round-trip; one per LEAF multiplies that
  by the model depth (O(leaves) drains per sweep).  Compute the
  reduction on device (``tree_finite`` / ``multi_tensor_l2norm``, one
  reduce per bucket with a ``BucketStore``) and fetch ONE value, or
  stack the per-leaf values into a single transfer (ISSUE 4: the
  tree-sweep twin of the J001 stalls).
* **J009** async-dispatch timing lies: ``time.time()`` /
  ``time.perf_counter()`` read before AND after a call to a jitted
  callable with **no sync in the timed span** — jax dispatch is
  asynchronous, so the elapsed time measures how fast the host can
  *enqueue* the program, not how long the device takes to run it
  (bench round 1 reported 6x chip peak exactly this way).  Fence the
  measurement with ``jax.block_until_ready(out)`` or a value fetch
  (``device_get`` / ``float()`` on an output) before reading the
  second clock; calls to local helpers that sync internally count
  (ISSUE 5: the static twin of the telemetry stream's measured-window
  contract).
* **J010** cost harvesting inside step loops: ``.cost_analysis()`` /
  ``.memory_analysis()``, or ``.lower()``/``.compile()`` of a jitted
  computation, called inside a loop body.  Each ``lower`` re-traces and
  each ``compile`` re-runs the backend — seconds per call on a real
  chip, and none of it is cached across loop iterations.  Costs are
  static per (shapes, dtypes): harvest ONCE before the loop
  (``apex_tpu.prof.roofline.harvest_costs``) and reuse the result
  (ISSUE 6: the static twin of the roofline engine's harvest-at-trace-
  time contract).
* **J012** per-request host syncs in serving contexts: a J001-class
  sync (``device_get``/``.item()``/``block_until_ready``/``float()`` on
  an array) inside a ``while`` loop or inside a request-handler
  function (``handle*``/``serve*``/``on_*``/``*_handler``/
  ``*request*``).  A training loop pays one sync per K-step window; a
  serving loop that syncs PER REQUEST (or per decode step) caps
  throughput at a host round-trip per token — defer the fetch one step
  behind (the ``DeferredMetrics`` pattern) or batch it, and waive ONLY
  the sanctioned response boundary, where sampled tokens must reach the
  host to drive termination/eviction (ISSUE 11: the serving twin of
  the J001/J008 stalls).  Reported INSTEAD of J001 in those contexts.
* **J011** (advisory) unfused BN/GN + ReLU chains in model bodies:
  ``nn.BatchNorm``/``nn.GroupNorm`` applied and immediately followed by
  ``nn.relu`` — nested (``nn.relu(nn.BatchNorm(...)(x))``) or as
  consecutive statements — inside a module ``__call__``.  apex_tpu
  ships a fused epilogue for exactly this chain
  (``normalization.bn_relu_residual``, reachable through
  ``SyncBatchNorm(fuse_relu=True)`` / ``contrib.groupbn.
  BatchNorm2d_NHWC`` / the ResNet norm-factory hook), which collapses
  the two elementwise sweeps into one pass (ISSUE 7).  Advisory
  severity: reported, waivable, and never fails the CLI on its own —
  the chain is correct, just slower than it needs to be.
* **J013** (advisory) unsharded parameter staging in multi-device
  entry points: ``jax.device_put`` with no sharding argument, or
  ``jnp.asarray``, of a parameter-sized array (name matches
  ``param*``/``state``/``weight*``/``master*``/``moment*``/
  ``opt_state``/``grad*``) inside a function that constructs or maps
  a mesh (``Mesh``/``MeshPlan``/``shard_map``/``NamedSharding``/
  ``make_mesh_train_step``).  The bare put lands the array uncommitted
  on one device: the partitioner reshuffles it per sharded call and
  AOT warmup cannot pin the placement — derive it from the plan
  (``plan.named(...)``/``plan.batch_sharding()``) instead (ISSUE 12).
* **J014** (advisory) per-step recalibration at quantized-matmul call
  sites: a ``quantized_matmul``/``quant_matmul`` call whose ``x_scale``
  /``scale`` argument is a freshly computed ``abs().max()`` (inline or
  via a same-function local).  The activation scale is supposed to be a
  FROZEN calibration constant (``apex_tpu.quant.Calibrator`` observe →
  freeze); re-deriving it in the step pays a full extra reduction per
  dispatch and silently changes the numerics the CONVERGENCE_QUANT
  gate certified.  ``w_scale`` is exempt — weights are exact at trace
  time, per-step channel scales are the correct recipe (ISSUE 13).
* **J015** (advisory) literal block-size overrides at Pallas-kernel
  call sites: a tunable kernel exposing block params
  (``flash_attention`` / ``bn_relu_residual`` / ``fused_layer_norm`` /
  ``quantized_matmul``) invoked with an integer LITERAL for
  ``block_q``/``block_k``/``block_m``/``block_n``/``row_block``.  The
  literal freezes one sweep's winner for every device kind and shape,
  bypassing the per-device config cache the tune registry dispatches
  through (``python -m apex_tpu.tune``, ISSUE 14) — leave the blocks
  at their defaults (cache-consulted) or pass a measured variable.
  Waive where the literal IS the documented reference path (a sweep
  tool enumerating configs, an A/B probe pinning one side).

Waivers: ``# jaxlint: disable=J001 -- reason`` on the offending line
suppresses the named rule(s) there; ``# jaxlint: disable-file=J004 --
reason`` suppresses for the whole file.  A waiver **must** carry a
``-- reason``; a bare waiver is itself a finding (J000) so sanctioned
violations stay documented rather than silenced.

All analysis is purely syntactic (``ast``) — no imports of the linted
code, so the linter runs in milliseconds under ``JAX_PLATFORMS=cpu``
with no accelerator present.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "RULES", "lint_source", "lint_file", "lint_paths"]


RULES: Dict[str, str] = {
    "J000": "malformed waiver (missing '-- reason' or unknown rule code)",
    "J001": "host sync in device code (device_get/.item()/float() on arrays)",
    "J002": "jax.jit with non-array Python args not marked static",
    "J003": "fp32 dtype leak inside a bf16/amp-cast path",
    "J004": "retracing hazard (jitted callable fed varying Python scalars)",
    "J005": "use-after-donate of a donate_argnums buffer",
    "J006": "Python control flow branching on a traced value under jit",
    "J007": "per-step host staging (device_put/asarray on batch data in a "
            "loop; stage in the loader)",
    "J008": "per-leaf host sync in a loop over tree_leaves/tree_flatten "
            "(O(leaves) round-trips; reduce on device or batch into one "
            "transfer)",
    "J009": "wall-clock timing around a jitted call with no sync in the "
            "timed span (async dispatch: the clock measures enqueue, not "
            "compute)",
    "J010": "cost_analysis()/lower()/compile() of a jitted computation "
            "inside a loop (re-traces and recompiles per call; harvest "
            "once before the loop)",
    "J011": "nn.BatchNorm/nn.GroupNorm immediately followed by nn.relu "
            "in a model __call__ (a fused apex_tpu epilogue exists; "
            "advisory)",
    "J012": "per-request host sync in a serving context (device_get/"
            ".item()/block_until_ready in a while-serving loop or a "
            "request-handler function; defer or batch the fetch — waive "
            "only the sanctioned response boundary)",
    "J013": "device_put/jnp.asarray of a parameter-sized array without "
            "an explicit NamedSharding inside a multi-device entry "
            "point (the array lands replicated/on one device and the "
            "partitioner reshuffles it per call; derive the placement "
            "from the MeshPlan; advisory)",
    "J014": "quantized-matmul call site whose scale argument is a "
            "freshly computed abs().max() (recalibration-per-step: the "
            "per-tensor activation scale should come from a FROZEN "
            "apex_tpu.quant calibration, not be re-derived inside the "
            "step; advisory)",
    "J015": "Pallas kernel invoked with a literal block-size override "
            "(block_q/block_k/block_m/block_n/row_block) instead of "
            "dispatching through the tune registry/config cache — the "
            "literal freezes one device's sweep winner for every "
            "device kind (python -m apex_tpu.tune; advisory)",
    "J016": "NCHW convolution layout: lax.conv_general_dilated with "
            "missing or NC*-leading dimension_numbers, or the "
            "always-NCHW lax.conv/lax.conv_with_general_padding "
            "wrappers — TPU-hostile (the feature axis belongs on the "
            "128 lanes; use ('NHWC','HWIO','NHWC'); advisory)",
}

#: Rules reported as advice, not errors: the CLI exits 0 when only
#: advisory findings remain, and ``Finding.advisory`` marks them.
ADVISORY_RULES: Set[str] = {"J011", "J013", "J014", "J015", "J016"}

# Functions whose *contract* is the host boundary: serialization must
# materialize host values, so J001 does not fire inside them.  Everything
# else documents its sanctioned syncs with an inline waiver.
_J001_HOST_BOUNDARY_FUNCS = {"state_dict", "load_state_dict"}

# Path components that mark a file as a host-side driver script (J001
# then only fires inside loop bodies).
_DRIVER_PARTS = {"examples", "tools", "tests", "docker"}
_DRIVER_BASENAMES = {"bench.py", "setup.py", "conftest.py"}

# Function names that mark per-request serving code for J012: a sync
# anywhere in such a function is a per-request round-trip.  Exactly the
# documented contract — ``handle*``/``serve*`` as underscore-delimited
# segments, ``on_*`` as a PREFIX only (``train_on_batch`` must stay
# J001 territory or existing J001 waivers would silently stop
# covering it), plus ``handler``/``request`` substrings.
_HANDLER_NAME_RE = re.compile(
    r"(^|_)(handle|serve|serving)(_|$)|^_?on_|handler|request")


class Finding(NamedTuple):
    path: str
    line: int
    col: int
    rule: str
    message: str

    @property
    def advisory(self) -> bool:
        return self.rule in ADVISORY_RULES

    def render(self) -> str:
        sev = " [advisory]" if self.advisory else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}{sev} {self.message}")


# -- waivers ------------------------------------------------------------------

_WAIVER_RE = re.compile(
    r"#\s*jaxlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Z][0-9]{3}(?:\s*,\s*[A-Z][0-9]{3})*)"
    r"\s*(?:--\s*(\S.*))?")


def _comments(src: str) -> List[Tuple[int, int, str]]:
    """(line, col, text) of every real comment token — waiver directives
    in docstrings or string literals (e.g. this linter's own docs) must
    not parse as waivers."""
    import io
    import tokenize
    out: List[Tuple[int, int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                   # ast.parse already reported the real error
    return out


class _Waivers:
    """Parsed waiver directives for one file."""

    def __init__(self, src: str, path: str):
        self.line_waivers: Dict[int, Set[str]] = {}
        self.file_waivers: Set[str] = set()
        self.errors: List[Finding] = []
        lines = src.splitlines()
        for lineno, col, text in _comments(src):
            m = _WAIVER_RE.search(text)
            if m is None:
                if re.search(r"jaxlint:\s*disable", text):
                    self.errors.append(Finding(
                        path, lineno, col, "J000",
                        "unparseable jaxlint directive"))
                continue
            kind, codes_s, reason = m.groups()
            codes = {c.strip() for c in codes_s.split(",")}
            bad = codes - set(RULES)
            if bad:
                self.errors.append(Finding(
                    path, lineno, col, "J000",
                    f"unknown rule code(s) {sorted(bad)} in waiver"))
                codes -= bad
            if not reason:
                self.errors.append(Finding(
                    path, lineno, col, "J000",
                    "waiver without a '-- reason' (document why the "
                    "violation is sanctioned)"))
                continue        # an undocumented waiver waives nothing
            if kind == "disable-file":
                self.file_waivers |= codes
                continue
            self.line_waivers.setdefault(lineno, set()).update(codes)
            # A comment-ONLY waiver line also covers the line below it —
            # multi-line statements (backslash/paren continuations)
            # cannot carry a trailing comment on their first physical
            # line.  A trailing waiver stays scoped to its own line, so
            # it cannot silently cover an unrelated violation added on
            # the next line (review: the old unconditional line-1 lookup
            # let exactly that slip through the tier-1 gate).
            standalone = lineno <= len(lines) \
                and not lines[lineno - 1][:col].strip()
            if standalone:
                self.line_waivers.setdefault(lineno + 1, set()).update(codes)

    def waived(self, f: Finding) -> bool:
        if f.rule in self.file_waivers:
            return True
        return f.rule in self.line_waivers.get(f.line, set())


# -- small AST helpers --------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); None for anything
    not a pure dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _rooted_at(node: ast.AST, roots: Sequence[str]) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    return d.split(".", 1)[0] in roots


# Trace-time metadata: shape/dtype/aval queries are resolved during
# tracing and never touch the device, so float()/int()/bool() of them
# is NOT a sync even when the operand is an array.
_STATIC_METADATA_CALLS = {
    "jnp.size", "jnp.shape", "jnp.ndim", "jnp.result_type", "jnp.dtype",
    "jnp.issubdtype", "np.prod", "numpy.prod", "math.prod", "len",
    "jax.typeof", "jax.eval_shape", "jax.tree_util.tree_structure",
}
_STATIC_METADATA_ATTRS = {"shape", "ndim", "dtype", "itemsize", "weak_type",
                          "vma", "aval"}


def _is_static_metadata(node: ast.AST) -> bool:
    """True when the expression is built ONLY from trace-time metadata
    (shapes, dtypes, avals) — device-free by construction.  Structural,
    not a substring scan: ``float(jnp.sum(y) / y.shape[0])`` is a real
    device round-trip even though ``.shape`` appears inside it (review:
    the old any-subexpression test exempted exactly that idiom)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_METADATA_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_static_metadata(node.value)     # x.shape[0]
    if isinstance(node, ast.Call):
        # metadata queries return host ints/dtypes whatever their args
        return _dotted(node.func) in _STATIC_METADATA_CALLS
    if isinstance(node, ast.BinOp):
        return _is_static_metadata(node.left) \
            and _is_static_metadata(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_metadata(node.operand)
    if isinstance(node, ast.Compare):
        return _is_static_metadata(node.left) \
            and all(_is_static_metadata(c) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return all(_is_static_metadata(v) for v in node.values)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_static_metadata(e) for e in node.elts)
    return False


def _is_arrayish(node: ast.AST, local_arrayish: Set[str]) -> bool:
    """Heuristic: does this expression hold a (possibly traced) array?
    True when any subexpression is rooted at jnp/jax/lax, calls
    ``.astype``, or names a local previously bound from such a value.
    Lambda bodies are NOT part of the expression's value (they run
    later, with their own scope) — descending into them mistakes a
    timing harness fed ``lambda q: flash(q)`` for an array value."""
    stack = [node]
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.Lambda):
            continue
        if isinstance(sub, ast.Name) and sub.id in local_arrayish:
            return True
        if isinstance(sub, ast.Call):
            if _rooted_at(sub.func, ("jnp", "jax", "lax")):
                return True
            if isinstance(sub.func, ast.Attribute) and sub.func.attr in (
                    "astype", "block_until_ready"):
                return True
        if isinstance(sub, ast.Attribute) and _rooted_at(sub, ("jnp", "lax")):
            return True
        stack.extend(ast.iter_child_nodes(sub))
    return False


def _const_ints(node: ast.AST) -> Optional[Set[int]]:
    """Literal int or tuple/list of ints -> set; None when not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in node.elts:
            s = _const_ints(e)
            if s is None:
                return None
            out |= s
        return out
    return None


def _const_strs(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            s = _const_strs(e)
            if s is None:
                return None
            out |= s
        return out
    return None


class _JitSite(NamedTuple):
    """One ``jax.jit`` application found in the module."""
    node: ast.Call                  # the jax.jit(...) call (or decorator)
    target: Optional[str]           # name of the function being jitted
    bound_name: Optional[str]       # name the jitted callable is bound to
    static_argnums: Optional[Set[int]]   # None = non-literal (unknown)
    static_argnames: Optional[Set[str]]
    donate_argnums: Optional[Set[int]]


def _parse_jit_call(call: ast.Call) -> Tuple[Optional[Set[int]],
                                             Optional[Set[str]],
                                             Optional[Set[int]]]:
    nums: Optional[Set[int]] = set()
    names: Optional[Set[str]] = set()
    donate: Optional[Set[int]] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _const_ints(kw.value)
        elif kw.arg == "static_argnames":
            names = _const_strs(kw.value)
        elif kw.arg == "donate_argnums":
            donate = _const_ints(kw.value)
        elif kw.arg is None:         # **kwargs: give up on precision
            nums = names = donate = None
    return nums, names, donate


def _is_jax_jit(func: ast.AST) -> bool:
    return _dotted(func) in ("jax.jit", "jit", "pjit", "jax.pjit")


# Calls that fence async dispatch for J009: a device round-trip or an
# explicit block.  ``float()/int()/bool()`` and ``.fetch()``/``.item()``
# are counted generously (regardless of arg arrayishness) — precision
# over recall on the TIMING rule means missing a pathological
# ``float(python_scalar)`` fence, not flagging a correctly fenced loop.
_J009_SYNC_DOTTED = {"jax.device_get", "jax.block_until_ready",
                     "np.asarray", "numpy.asarray", "np.array",
                     "numpy.array"}


def _is_sync_call(call: ast.Call) -> bool:
    if _dotted(call.func) in _J009_SYNC_DOTTED:
        return True
    if isinstance(call.func, ast.Attribute) and call.func.attr in (
            "item", "block_until_ready", "fetch", "last"):
        return True
    if isinstance(call.func, ast.Name) \
            and call.func.id in ("float", "int", "bool") and call.args:
        return True
    return False


# -- module-level scan: jit sites, donated names, function defs ---------------

class _ModuleIndex:
    """Everything the per-scope rules need to know about the module.

    Name bindings (``step = jax.jit(...)``) are tracked per enclosing
    function: two unrelated locals that happen to share a name in
    different functions must not cross-contaminate J004/J005 (``scope``
    below is the enclosing FunctionDef node, or None at module level —
    module-level bindings are visible from every scope)."""

    def __init__(self, tree: ast.Module):
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.jit_sites: List[_JitSite] = []
        # (scope, name) keys; scope None = module level
        self.jitted_names: Set[Tuple[Optional[ast.AST], str]] = set()
        self.jitted_defs: Set[str] = set()            # def names that get traced
        self.donated: Dict[Tuple[Optional[ast.AST], str], Set[int]] = {}
        self._seen_calls: Set[int] = set()
        self._scan_body(tree.body, None)

    def jitted_name(self, scope, name: str) -> bool:
        return (scope, name) in self.jitted_names \
            or (None, name) in self.jitted_names

    def sync_defs(self) -> Set[str]:
        """Names of module-level defs whose body directly syncs — calling
        one (e.g. a local ``_force``/``drain`` helper) fences an
        async-dispatch timing exactly like an inline ``device_get``, so
        J009 treats it as a sync point (one-level interprocedural)."""
        cached = getattr(self, "_sync_defs", None)
        if cached is None:
            cached = set()
            for name, fn in self.defs.items():
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and _is_sync_call(sub):
                        cached.add(name)
                        break
            self._sync_defs = cached
        return cached

    def donated_argnums(self, scope, name: str) -> Optional[Set[int]]:
        got = self.donated.get((scope, name))
        if got is None:
            got = self.donated.get((None, name))
        return got

    def _scan_body(self, body: Sequence[ast.stmt], scope) -> None:
        for stmt in body:
            self._scan_stmt(stmt, scope)

    def _scan_stmt(self, stmt: ast.stmt, scope) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.defs.setdefault(stmt.name, stmt)
            self._scan_decorators(stmt, scope)
            self._scan_body(stmt.body, stmt)
            return
        if isinstance(stmt, ast.ClassDef):
            self._scan_body(stmt.body, scope)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                and stmt.value is not None \
                and isinstance(stmt.value, ast.Call) \
                and _is_jax_jit(stmt.value.func):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            bound = None
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                bound = targets[0].id
            self._add_call_site(stmt.value, bound, scope)
        # bare jax.jit(...) calls in this statement's own expressions
        # (J002 only); skip subtrees owned by nested defs / child
        # statements — they are visited with their own scope.
        skip: Set[int] = set()
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, (ast.stmt, ast.excepthandler)):
                for n in ast.walk(sub):
                    skip.add(id(n))
        for sub in ast.walk(stmt):
            if sub is stmt or id(sub) in skip:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                for n in ast.walk(sub):
                    skip.add(id(n))
                continue
            if isinstance(sub, ast.Call) and _is_jax_jit(sub.func) \
                    and id(sub) not in self._seen_calls:
                self._add_call_site(sub, None, scope)
        # recurse into child statements (compound stmt bodies)
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.stmt):
                self._scan_stmt(sub, scope)
            elif isinstance(sub, ast.excepthandler):
                self._scan_body(sub.body, scope)

    def _add_call_site(self, call: ast.Call, bound: Optional[str],
                       scope) -> None:
        self._seen_calls.add(id(call))
        target = None
        if call.args:
            a0 = call.args[0]
            if isinstance(a0, ast.Name):
                target = a0.id
            elif isinstance(a0, ast.Call):
                # jax.jit(functools.partial(fn, ...)) — resolve through
                # the partial to the underlying def for J002/J006.
                if _dotted(a0.func) in ("functools.partial", "partial") \
                        and a0.args and isinstance(a0.args[0], ast.Name):
                    target = a0.args[0].id
        nums, names, donate = _parse_jit_call(call)
        self.jit_sites.append(_JitSite(call, target, bound, nums, names,
                                       donate))
        if target:
            self.jitted_defs.add(target)
        if bound:
            self.jitted_names.add((scope, bound))
            if donate:
                self.donated[(scope, bound)] = donate

    def _scan_decorators(self, fn: ast.FunctionDef, scope) -> None:
        for dec in fn.decorator_list:
            site = None
            if _is_jax_jit(dec):                       # @jax.jit
                site = _JitSite(ast.Call(func=dec, args=[], keywords=[]),
                                fn.name, fn.name, set(), set(), set())
            elif isinstance(dec, ast.Call):
                if _is_jax_jit(dec.func):              # @jax.jit(...) (rare)
                    nums, names, donate = _parse_jit_call(dec)
                    site = _JitSite(dec, fn.name, fn.name, nums, names,
                                    donate)
                elif _dotted(dec.func) in ("functools.partial", "partial") \
                        and dec.args and _is_jax_jit(dec.args[0]):
                    # @functools.partial(jax.jit, static_argnums=...)
                    nums, names, donate = _parse_jit_call(dec)
                    site = _JitSite(dec, fn.name, fn.name, nums, names,
                                    donate)
            if site is None:
                continue
            self.jit_sites.append(site)
            self.jitted_defs.add(fn.name)
            self.jitted_names.add((scope, fn.name))
            if site.donate_argnums:
                self.donated[(scope, fn.name)] = site.donate_argnums


# -- J002: jit of non-array Python args ---------------------------------------

_PYTHONISH_ANNOTATIONS = {"bool", "str"}


def _check_j002(idx: _ModuleIndex, path: str) -> List[Finding]:
    out: List[Finding] = []
    for site in idx.jit_sites:
        if site.target is None or site.target not in idx.defs:
            continue
        if site.static_argnums is None or site.static_argnames is None:
            continue                      # non-literal statics: can't verify
        fn = idx.defs[site.target]
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = list(fn.args.defaults)
        # align defaults with trailing positional args
        dstart = len(args) - len(defaults)
        for i, a in enumerate(args):
            if a.arg in ("self", "cls"):
                continue
            pythonish = None
            if isinstance(a.annotation, ast.Name) \
                    and a.annotation.id in _PYTHONISH_ANNOTATIONS:
                pythonish = a.annotation.id
            d = defaults[i - dstart] if i >= dstart else None
            if d is not None and isinstance(d, ast.Constant) \
                    and type(d.value) in (bool, str):
                pythonish = type(d.value).__name__
            if pythonish is None:
                continue
            if i in site.static_argnums or a.arg in site.static_argnames:
                continue
            out.append(Finding(
                path, site.node.func.lineno, site.node.func.col_offset,
                "J002",
                f"jax.jit of '{site.target}' passes Python {pythonish} "
                f"arg '{a.arg}' (index {i}) without static_argnums/"
                f"static_argnames — it will trace as an array (bool) or "
                f"fail (str); mark it static"))
    return out


# -- J003: fp32 leaks in bf16 paths -------------------------------------------

def _fn_has_bf16(fn: ast.FunctionDef) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute) and sub.attr == "bfloat16":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "bfloat16":
            return True
    return False


def _is_f32_dtype(node: ast.AST) -> bool:
    d = _dotted(node)
    if d in ("jnp.float32", "np.float32", "numpy.float32", "jax.numpy.float32"):
        return True
    return isinstance(node, ast.Constant) and node.value == "float32"


# fp32 casts whose consumer keeps them fp32 *by design* are exempt:
# softmax/log-softmax/losses/norm statistics belong in fp32 under amp
# (the reference's O1 fp32 function list), and a cast feeding a host
# fetch (float()/device_get) dies at the device boundary anyway.
_J003_FP32_SINK_RE = re.compile(
    r"softmax|loss|xent|entropy|logsumexp|norm|mean|var|sum", re.IGNORECASE)
_J003_HOST_SINKS = {"float", "int", "bool", "print"}


def _j003_exempt_nodes(fn: ast.FunctionDef) -> Set[int]:
    """ids of all nodes living under an fp32-sink call."""
    out: Set[int] = set()
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        d = _dotted(sub.func) or ""
        attr = sub.func.attr if isinstance(sub.func, ast.Attribute) else ""
        name = sub.func.id if isinstance(sub.func, ast.Name) else ""
        sink = (_J003_FP32_SINK_RE.search(d or attr or name)
                or name in _J003_HOST_SINKS
                or d in ("jax.device_get", "np.asarray", "numpy.asarray"))
        if sink:
            for n in ast.walk(sub):
                out.add(id(n))
    return out


def _check_j003(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        if not _fn_has_bf16(fn):
            continue
        exempt = _j003_exempt_nodes(fn)
        upcasts: List[ast.Call] = []
        has_downcast = False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or id(sub) in exempt:
                continue
            if isinstance(sub.func, ast.Attribute) and sub.func.attr == "astype":
                dt = sub.args[0] if sub.args else None
                for kw in sub.keywords:
                    if kw.arg == "dtype":
                        dt = kw.value
                if dt is not None and _is_f32_dtype(dt):
                    upcasts.append(sub)
                elif dt is not None:
                    has_downcast = True
            elif _dotted(sub.func) in ("jnp.asarray", "jnp.array"):
                if not sub.args or not _is_arrayish(sub.args[0], set()):
                    continue    # creation from host data, not a cast
                dt = sub.args[1] if len(sub.args) > 1 else None
                for kw in sub.keywords:
                    if kw.arg == "dtype":
                        dt = kw.value
                if dt is not None and _is_f32_dtype(dt):
                    upcasts.append(sub)
                elif dt is not None:
                    has_downcast = True
        if upcasts and not has_downcast:
            for c in upcasts:
                out.append(Finding(
                    path, c.lineno, c.col_offset, "J003",
                    f"fp32 cast in bf16 function '{fn.name}' with no "
                    f"compensating downcast anywhere in the function — "
                    f"the widened dtype leaks to every consumer"))
        # weak-type / literal promotion: jnp.float32(lit) inside arithmetic
        for sub in ast.walk(fn):
            if isinstance(sub, ast.BinOp):
                for side in (sub.left, sub.right):
                    if isinstance(side, ast.Call) \
                            and _dotted(side.func) == "jnp.float32" \
                            and side.args \
                            and isinstance(side.args[0], ast.Constant):
                        out.append(Finding(
                            path, side.lineno, side.col_offset, "J003",
                            f"jnp.float32(literal) inside arithmetic in "
                            f"bf16 function '{fn.name}' promotes the whole "
                            f"expression to fp32 (non-weak dtype); use a "
                            f"plain Python literal (weak type) or cast the "
                            f"result back"))
    return out


# -- J011: unfused BN/GN + ReLU chains in model __call__ bodies ---------------

_J011_NORMS = {"nn.BatchNorm", "nn.GroupNorm", "linen.BatchNorm",
               "linen.GroupNorm", "flax.linen.BatchNorm",
               "flax.linen.GroupNorm"}
_J011_RELUS = {"nn.relu", "jax.nn.relu", "flax.linen.relu"}


def _j011_norm_aliases(fn: ast.FunctionDef) -> Set[str]:
    """Local names bound to a BN/GN factory: ``norm = functools.partial(
    nn.BatchNorm, ...)`` or ``norm = lambda ...: nn.BatchNorm(...)`` —
    the idiom model bodies use to parameterize their norm layers."""
    out: Set[str] = set()
    for stmt in ast.walk(fn):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        v = stmt.value
        name = stmt.targets[0].id
        if isinstance(v, ast.Call) \
                and _dotted(v.func) in ("functools.partial", "partial") \
                and v.args and _dotted(v.args[0]) in _J011_NORMS:
            out.add(name)
        elif isinstance(v, ast.Lambda) and isinstance(v.body, ast.Call):
            f = v.body.func
            if _dotted(f) in _J011_NORMS:
                out.add(name)
            elif isinstance(f, ast.Call) and _dotted(f.func) in _J011_NORMS:
                out.add(name)
    return out


def _j011_is_norm_apply(node: ast.AST, aliases: Set[str]) -> bool:
    """``nn.BatchNorm(...)(x)`` / ``norm_alias(...)(x)`` /
    ``norm_alias(x)`` — a BN/GN module applied to activations."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Call):             # ctor-then-apply
        if _dotted(f.func) in _J011_NORMS:
            return True
        if isinstance(f.func, ast.Name) and f.func.id in aliases:
            return True
    if isinstance(f, ast.Name) and f.id in aliases:
        return True
    return False


def _check_j011(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []

    def _report(node: ast.AST, how: str) -> None:
        findings.append(Finding(
            path, node.lineno, node.col_offset, "J011",
            f"BatchNorm/GroupNorm {how} nn.relu in a model __call__ — "
            f"apex_tpu ships a fused epilogue for this exact chain "
            f"(normalization.bn_relu_residual via SyncBatchNorm("
            f"fuse_relu=True) / contrib.groupbn.BatchNorm2d_NHWC / the "
            f"ResNet norm-factory hook): one elementwise pass instead "
            f"of two"))

    for fn in ast.walk(tree):
        if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name == "__call__"):
            continue
        aliases = _j011_norm_aliases(fn)
        # nested form: nn.relu(<bn apply>)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and _dotted(node.func) in _J011_RELUS \
                    and node.args \
                    and _j011_is_norm_apply(node.args[0], aliases):
                _report(node, "wrapped directly in")
        # consecutive-statement form: v = <bn apply>; v = nn.relu(v) —
        # across EVERY statement list (if/else arms, loop bodies, try/
        # except/finally), not just .body: an else-branch chain is the
        # same two sweeps.
        stmt_lists = []
        for holder in ast.walk(fn):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(holder, field, None)
                if isinstance(body, list) and body \
                        and isinstance(body[0], ast.stmt):
                    stmt_lists.append(body)
        for body in stmt_lists:
            for prev, nxt in zip(body, body[1:]):
                if not (isinstance(prev, ast.Assign)
                        and len(prev.targets) == 1
                        and isinstance(prev.targets[0], ast.Name)
                        and _j011_is_norm_apply(prev.value, aliases)):
                    continue
                tgt = prev.targets[0].id
                if not (isinstance(nxt, ast.Assign)
                        and isinstance(nxt.value, ast.Call)
                        and _dotted(nxt.value.func) in _J011_RELUS
                        and nxt.value.args
                        and isinstance(nxt.value.args[0], ast.Name)
                        and nxt.value.args[0].id == tgt):
                    continue
                _report(nxt.value, "immediately followed by")
    return findings


# -- J013: unsharded parameter staging in multi-device entry points -----------

#: a function that touches any of these is a "multi-device entry
#: point": it constructs or maps over a mesh, so every array it stages
#: has a RIGHT placement the partitioner cannot infer from a bare put.
_J013_MESH_MARKERS = {"Mesh", "MeshPlan", "shard_map", "NamedSharding",
                      "make_mesh_train_step", "make_mesh"}

#: names that look parameter-sized (the arrays whose silent
#: replication costs HBM and a reshuffle; a scalar metric staged
#: without a sharding is noise, not a finding)
_J013_PARAMISH_RE = re.compile(
    r"(^|_)(params?|state|weights?|masters?|moments?|opt_state|grads?)"
    r"(_|$|\d)", re.IGNORECASE)

_J013_ASARRAY = {"jnp.asarray", "jax.numpy.asarray",
                 "jnp.array", "jax.numpy.array"}


def _j013_is_mesh_fn(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        name = _dotted(node) if isinstance(node, (ast.Name,
                                                  ast.Attribute)) else None
        if name and name.split(".")[-1] in _J013_MESH_MARKERS:
            return True
    return False


def _j013_paramish(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return bool(_J013_PARAMISH_RE.search(node.id))
    if isinstance(node, ast.Attribute):
        return bool(_J013_PARAMISH_RE.search(node.attr)) \
            or _j013_paramish(node.value)
    return False


def _check_j013(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _j013_is_mesh_fn(fn):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name and name.split(".")[-1] == "device_put":
                # an explicit second arg / device= / sharding kwarg IS
                # the placement — only the bare single-arg put flags
                explicit = (len(node.args) >= 2
                            or any(k.arg in ("device", "sharding", "dst")
                                   for k in node.keywords))
                if not explicit and node.args \
                        and _j013_paramish(node.args[0]):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset, "J013",
                        "device_put of a parameter-sized array with no "
                        "sharding inside a multi-device entry point — "
                        "it lands on one device (or replicated) and "
                        "every sharded call reshuffles it; pass the "
                        "NamedSharding the mesh plan derives "
                        "(plan.named(...)/plan.batch_sharding())"))
            elif name in _J013_ASARRAY:
                if node.args and _j013_paramish(node.args[0]):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset, "J013",
                        "jnp.asarray of a parameter-sized array inside "
                        "a multi-device entry point stages it "
                        "uncommitted on the default device — "
                        "device_put with the plan-derived NamedSharding "
                        "instead, so warmup and restore pin the "
                        "placement"))
    return findings


# -- J014: per-step recalibration at quantized-matmul call sites --------------

#: call names that take a calibrated scale (the apex_tpu.quant surface
#: plus the obvious user spellings)
_J014_QUANT_CALLS = {"quantized_matmul", "quant_matmul",
                     "quantized_matmul_ref"}

#: keyword arguments that carry an ACTIVATION scale.  ``w_scale`` is
#: deliberately absent: weights are exact at trace time, so deriving
#: their per-channel scale in-step is the correct recipe.
_J014_SCALE_KWARGS = {"x_scale", "scale"}

_J014_ABS_NAMES = {"abs", "absolute"}
_J014_MAX_NAMES = {"max", "amax", "nanmax"}


def _j014_call_leaf(call: ast.Call) -> Optional[str]:
    """The trailing name of a call: ``jnp.abs`` -> ``abs``, and the
    method form ``expr.max()`` -> ``max`` (an Attribute on a non-name
    value has no dotted spelling but its attr still identifies it)."""
    name = _dotted(call.func)
    if name:
        return name.split(".")[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _j014_contains_abs(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and _j014_call_leaf(sub) in _J014_ABS_NAMES:
            return True
    return False


def _j014_is_fresh_absmax(node: ast.AST) -> bool:
    """True when ``node`` computes an absmax inline: ``jnp.max(jnp.abs(
    x))`` / ``jnp.abs(x).max()`` / ``abs(x).max()`` — the per-step
    recalibration shape.  A frozen float, an attribute read
    (``calib.scales[...]``) or a plain name resolves False here; names
    assigned from an absmax in the SAME function are resolved by the
    caller."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if _j014_call_leaf(sub) in _J014_MAX_NAMES \
                and _j014_contains_abs(sub):
            return True
    return False


def _j014_scope_walk(fn):
    """``ast.walk`` limited to ``fn``'s OWN scope: nested function defs
    are their own J014 scopes, so a helper's local ``s = abs(x).max()``
    must not mark the enclosing function's ``s`` (a frozen calibration
    constant) as fresh.  Lambdas cannot contain assignments, so their
    bodies stay included (call-site coverage, no name pollution)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _check_j014(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # one-level local resolution (the J009 pattern): a name assigned
        # from a fresh absmax in this function is as fresh as the
        # expression itself.  Binding-order aware: what matters is the
        # LAST assignment to the name before the call site, so
        # ``s = abs(x).max()/127; s = calib.scales[k]`` resolves frozen
        bindings: Dict[str, List[Tuple[int, bool]]] = {}
        for node in _j014_scope_walk(fn):
            if isinstance(node, ast.Assign):
                fresh_val = _j014_is_fresh_absmax(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        bindings.setdefault(tgt.id, []).append(
                            (node.lineno, fresh_val))

        def _name_fresh_at(name: str, lineno: int) -> bool:
            prior = [b for b in bindings.get(name, ())
                     if b[0] <= lineno]
            return bool(prior) and max(prior)[1]

        for node in _j014_scope_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if not name or name.split(".")[-1] not in _J014_QUANT_CALLS:
                continue
            for kw in node.keywords:
                if kw.arg not in _J014_SCALE_KWARGS:
                    continue
                fresh = _j014_is_fresh_absmax(kw.value) or (
                    isinstance(kw.value, ast.Name)
                    and _name_fresh_at(kw.value.id, node.lineno))
                if fresh:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset, "J014",
                        f"{kw.arg}= is a freshly computed abs().max() — "
                        f"per-step recalibration re-derives the int8 "
                        f"range every dispatch (an extra full reduction "
                        f"over the activations) and unpins the "
                        f"numerics the convergence gate certified; "
                        f"freeze scales once via apex_tpu.quant."
                        f"Calibrator and pass the calibrated constant"))
    return findings


# -- J015: literal block-size overrides at tunable-kernel call sites ----------

#: call-name leaves of the registered tunable kernels that EXPOSE a
#: block override (xentropy is cache-tuned too but its public function
#: takes no block kwarg, so no literal can appear at a working call
#: site — listing it would document a parameter that does not exist)
_J015_KERNEL_CALLS = {"flash_attention", "bn_relu_residual",
                      "fused_layer_norm", "fused_layer_norm_affine",
                      "quantized_matmul", "conv2d"}
#: the tuned block-size parameters across the kernel family
_J015_BLOCK_KWARGS = {"block_q", "block_k", "block_m", "block_n",
                      "row_block"}


def _check_j015(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not name or name.split(".")[-1] not in _J015_KERNEL_CALLS:
            continue
        for kw in node.keywords:
            if kw.arg not in _J015_BLOCK_KWARGS:
                continue
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int) \
                    and not isinstance(kw.value.value, bool):
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "J015",
                    f"{kw.arg}={kw.value.value} is a literal block-size "
                    f"override — it freezes one sweep's winner for every "
                    f"device kind and shape; leave the blocks at their "
                    f"defaults so the tune config cache decides per "
                    f"device (python -m apex_tpu.tune), or pass a "
                    f"measured variable"))
    return findings


# -- J016: NCHW convolution layouts -------------------------------------------

#: always-NCHW lax convenience wrappers (no dimension_numbers knob);
#: matched by the FULL dotted suffix ``lax.<name>`` — the bare leaf
#: ``conv`` is far too common (``self.conv(...)`` factories) to match
_J016_LAX_NCHW_CALLS = {"conv", "conv_with_general_padding"}


def _j016_spec_is_nchw(value: ast.expr) -> Optional[bool]:
    """True/False when ``dimension_numbers=`` is a literal spec we can
    read (tuple/list of strings: NC* -> True, else False); None when it
    is a variable / ConvDimensionNumbers expression (not inspected)."""
    if isinstance(value, (ast.Tuple, ast.List)) and value.elts:
        first = value.elts[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value.upper().startswith("NC")
    return None


def _check_j016(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not name:
            continue
        parts = name.split(".")
        leaf = parts[-1]
        if leaf == "conv_general_dilated":
            dims = None
            for kw in node.keywords:
                if kw.arg == "dimension_numbers":
                    dims = kw.value
            if dims is None and len(node.args) >= 6:
                dims = node.args[5]
            if dims is None:
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "J016",
                    "conv_general_dilated without dimension_numbers= — "
                    "the lax default IS NCHW ('NCHW','OIHW','NCHW'), a "
                    "TPU-hostile layout that transposes around every "
                    "conv; spell ('NHWC','HWIO','NHWC') explicitly"))
            elif _j016_spec_is_nchw(dims):
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "J016",
                    "NCHW dimension_numbers at a conv call site — TPUs "
                    "tile the feature axis onto the 128 lanes, so NCHW "
                    "pays a transpose either side of every conv and "
                    "walls off the NHWC Pallas conv path; use "
                    "('NHWC','HWIO','NHWC')"))
        elif (leaf in _J016_LAX_NCHW_CALLS and len(parts) >= 2
              and parts[-2] == "lax"):
            findings.append(Finding(
                path, node.lineno, node.col_offset, "J016",
                f"lax.{leaf} is the always-NCHW convenience wrapper — "
                f"it has no layout knob and lands the TPU-hostile "
                f"('NCHW','OIHW','NCHW') spec; call "
                f"conv_general_dilated with ('NHWC','HWIO','NHWC') or "
                f"use flax.linen.Conv / apex_tpu.ops.PallasConv"))
    return findings


# -- per-scope walker: J001, J004, J005, J006 ---------------------------------

class _ScopeWalker:
    """Walks one scope (module body or one function body, excluding
    nested defs which become their own scopes) tracking loop depth and
    which locals hold arrays."""

    def __init__(self, idx: _ModuleIndex, path: str, driver: bool,
                 findings: List[Finding]):
        self.idx = idx
        self.path = path
        self.driver = driver
        self.findings = findings

    def lint_module(self, tree: ast.Module) -> None:
        self._scope(tree.body, fn=None)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scope(node.body, fn=node)

    # .. scope machinery ......................................................

    def _scope(self, body: List[ast.stmt], fn) -> None:
        self.fn = fn
        self.body = body
        self.fn_name = fn.name if fn is not None else "<module>"
        # Locals known to hold arrays.  Parameters are deliberately NOT
        # assumed arrayish: ``float(eps)`` on a Python-scalar parameter is
        # the dominant idiom and would drown real syncs in false
        # positives; precision over recall.
        self.arrayish: Set[str] = set()
        # Loop targets drawn from NON-array host iterables (a loader /
        # batch stream): per-step device_put/asarray on these is the
        # J007 host-staging-in-the-hot-loop finding.
        self.batch_vars: Set[str] = set()
        # Locals bound from tree_leaves/tree_flatten results: loops over
        # them are PER-LEAF sweeps, where a sync is J008 (O(leaves)
        # round-trips), not a garden-variety J001.
        self.leafish: Set[str] = set()
        self.jit_scoped = (fn is not None
                           and fn.name in self.idx.jitted_defs)
        # Request-handler scope for J012: syncs anywhere in a function
        # whose NAME marks it as per-request serving code are
        # per-request round-trips, loop or not.
        self.handler_fn = bool(_HANDLER_NAME_RE.search(self.fn_name))
        # J009 collection: clock reads, jitted-call sites, and sync
        # points seen in this scope (line-ordered pairing happens in
        # _finish_j009 once the whole scope is walked).
        self._j009_clocks: List[Tuple[int, int]] = []
        self._j009_jits: List[Tuple[int, str]] = []
        self._j009_syncs: List[int] = []
        self._stmts(body, loop_depth=0, loop_vars=frozenset(),
                    leaf_loop=False)
        self._finish_j009()

    def _stmts(self, body: List[ast.stmt], loop_depth: int,
               loop_vars: frozenset, leaf_loop: bool,
               in_while: bool = False) -> None:
        for stmt in body:
            self._stmt(stmt, loop_depth, loop_vars, leaf_loop, in_while)

    def _stmt(self, stmt: ast.stmt, loop_depth: int,
              loop_vars: frozenset, leaf_loop: bool,
              in_while: bool = False) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # nested defs are separate scopes
        if isinstance(stmt, ast.Assign):
            self._track_arrayish(stmt)
            self._track_leafish(stmt)
            self._check_j005_stmt(stmt, loop_depth)
        elif isinstance(stmt, ast.Expr):
            self._check_j005_stmt(stmt, loop_depth)
        # expression-level checks on this statement's own expressions
        self._exprs(stmt, loop_depth, loop_vars, leaf_loop, in_while)
        # recurse into compound statements
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            new_vars = loop_vars | self._scalar_loop_vars(stmt)
            # Loop targets drawn from an arrayish iterable hold arrays:
            # ``for loss in losses: float(loss)`` is a per-iteration
            # device round-trip exactly like ``float(losses[i])`` — the
            # J001 extension of ISSUE 2 (the old tracking only followed
            # Assign bindings, so iteration syncs in for/while bodies
            # passed the sweep).  Scalar counters (range/enumerate) are
            # excluded; zip over mixed iterables over-approximates, per
            # the waiver contract.
            in_leaf_loop = leaf_loop or self._is_leaves_expr(stmt.iter)
            if in_leaf_loop or _is_arrayish(stmt.iter, self.arrayish):
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name) and n.id not in new_vars:
                        self.arrayish.add(n.id)
            else:
                # Non-array iterable (a loader / host batch stream):
                # its non-scalar targets are host BATCH data — J007
                # territory when device_put/asarray'd per step.
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name) and n.id not in new_vars:
                        self.batch_vars.add(n.id)
            self._stmts(stmt.body, loop_depth + 1, new_vars, in_leaf_loop,
                        in_while)
            self._stmts(stmt.orelse, loop_depth, loop_vars, leaf_loop,
                        in_while)
        elif isinstance(stmt, ast.While):
            self._stmts(stmt.body, loop_depth + 1, loop_vars, leaf_loop,
                        True)
            self._stmts(stmt.orelse, loop_depth, loop_vars, leaf_loop,
                        in_while)
        elif isinstance(stmt, ast.If):
            self._check_j006(stmt)
            self._stmts(stmt.body, loop_depth, loop_vars, leaf_loop,
                        in_while)
            self._stmts(stmt.orelse, loop_depth, loop_vars, leaf_loop,
                        in_while)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._stmts(stmt.body, loop_depth, loop_vars, leaf_loop,
                        in_while)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, loop_depth, loop_vars, leaf_loop,
                        in_while)
            for h in stmt.handlers:
                self._stmts(h.body, loop_depth, loop_vars, leaf_loop,
                            in_while)
            self._stmts(stmt.orelse, loop_depth, loop_vars, leaf_loop,
                        in_while)
            self._stmts(stmt.finalbody, loop_depth, loop_vars, leaf_loop,
                        in_while)

    @staticmethod
    def _scalar_loop_vars(stmt) -> frozenset:
        """Loop targets that are definitely fresh Python scalars per
        iteration: ``for i in range(...)`` (all targets) and the counter
        of ``for i, x in enumerate(...)``.  Iterating arrays/leaves binds
        traced values, which retrace nothing — only scalar counters feed
        J004."""
        it = stmt.iter
        if not isinstance(it, ast.Call):
            return frozenset()
        d = _dotted(it.func)
        if d == "range":
            return frozenset(n.id for n in ast.walk(stmt.target)
                             if isinstance(n, ast.Name))
        if d == "enumerate" and isinstance(stmt.target, ast.Tuple) \
                and stmt.target.elts \
                and isinstance(stmt.target.elts[0], ast.Name):
            return frozenset({stmt.target.elts[0].id})
        return frozenset()

    # tree-leaves iterables feeding J008 (per-leaf sync sweeps)
    _TREE_LEAVES_CALLS = ("jax.tree_util.tree_leaves", "jax.tree_leaves",
                          "tree_leaves", "jax.tree.leaves",
                          "tree_util.tree_leaves")
    _TREE_FLATTEN_CALLS = ("jax.tree_util.tree_flatten", "jax.tree_flatten",
                           "tree_flatten", "jax.tree.flatten",
                           "tree_util.tree_flatten")

    def _is_leaves_expr(self, node: ast.AST) -> bool:
        """Does this expression yield the leaf list of a pytree?
        ``tree_leaves(...)``, ``tree_flatten(...)[0]``, or a local bound
        from either."""
        if isinstance(node, ast.Call) \
                and _dotted(node.func) in self._TREE_LEAVES_CALLS:
            return True
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Call) \
                and _dotted(node.value.func) in self._TREE_FLATTEN_CALLS:
            sl = node.slice
            return isinstance(sl, ast.Constant) and sl.value == 0
        if isinstance(node, ast.Name) and node.id in self.leafish:
            return True
        # zip(leaves_a, leaves_b, ...): per-leaf lockstep sweep
        if isinstance(node, ast.Call) and _dotted(node.func) == "zip":
            return any(self._is_leaves_expr(a) for a in node.args)
        return False

    def _track_leafish(self, stmt: ast.Assign) -> None:
        if len(stmt.targets) != 1:
            return
        t, v = stmt.targets[0], stmt.value
        if isinstance(t, ast.Name):
            if self._is_leaves_expr(v):
                self.leafish.add(t.id)
            else:
                self.leafish.discard(t.id)
            return
        # ``leaves, treedef = tree_flatten(tree)``
        if isinstance(t, ast.Tuple) and t.elts \
                and isinstance(t.elts[0], ast.Name) \
                and isinstance(v, ast.Call) \
                and _dotted(v.func) in self._TREE_FLATTEN_CALLS:
            self.leafish.add(t.elts[0].id)
            return
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                self.leafish.discard(n.id)

    def _track_arrayish(self, stmt: ast.Assign) -> None:
        # Results of a known-jitted callable are device arrays too —
        # ``state, metrics = step(state, b)`` then ``float(metrics[...])``
        # is the per-step sync this PR scrubbed from examples/lm (review:
        # the old tracking missed both the jitted call and tuple targets).
        v = stmt.value
        value_arrayish = _is_arrayish(v, self.arrayish) or (
            isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
            and self.idx.jitted_name(self.fn, v.func.id))
        # A host fetch PRODUCES a host value: after
        # ``vals = jax.device_get(...)`` every later bool(vals)/float()
        # is plain host arithmetic, not another sync (review: the fetch
        # itself is the one finding; post-fetch consumers are noise).
        if isinstance(v, ast.Call) and (
                _dotted(v.func) in ("jax.device_get", "np.asarray",
                                    "numpy.asarray", "np.array",
                                    "numpy.array")
                or (isinstance(v.func, ast.Name)
                    and v.func.id in ("float", "int", "bool"))):
            value_arrayish = False
        if len(stmt.targets) != 1:
            return
        target = stmt.targets[0]
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [n.id for e in target.elts for n in ast.walk(e)
                     if isinstance(n, ast.Name)]
        else:
            return
        for name in names:
            if value_arrayish:
                self.arrayish.add(name)
            else:
                self.arrayish.discard(name)

    def _exprs(self, stmt: ast.stmt, loop_depth: int,
               loop_vars: frozenset, leaf_loop: bool,
               in_while: bool = False) -> None:
        # own expressions only (not nested statements/defs)
        for expr in ast.iter_child_nodes(stmt):
            if isinstance(expr, (ast.stmt, ast.FunctionDef)):
                continue
            if isinstance(expr, ast.expr):
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Call):
                        self._check_j001_call(sub, loop_depth, leaf_loop,
                                              in_while)
                        self._check_j004_call(sub, loop_depth, loop_vars)
                        self._check_j007_call(sub, loop_depth)
                        self._check_j010_call(sub, loop_depth)
                        self._collect_j009(sub)
        # While tests live on the stmt itself
        if isinstance(stmt, ast.While):
            self._check_j006(stmt)

    # .. J001 / J008 / J012 ...................................................

    def _check_j001_call(self, call: ast.Call, loop_depth: int,
                         leaf_loop: bool = False,
                         in_while: bool = False) -> None:
        sync: Optional[str] = None
        d = _dotted(call.func)
        if d in ("jax.device_get", "jax.block_until_ready"):
            sync = d
        elif isinstance(call.func, ast.Attribute) and call.func.attr in (
                "item", "block_until_ready") and not call.args:
            sync = f".{call.func.attr}()"
        elif isinstance(call.func, ast.Name) \
                and call.func.id in ("float", "int", "bool") \
                and len(call.args) == 1 \
                and _is_arrayish(call.args[0], self.arrayish) \
                and not _is_static_metadata(call.args[0]):
            sync = f"{call.func.id}()"
        elif d in ("np.asarray", "numpy.asarray", "np.array", "numpy.array") \
                and call.args and _is_arrayish(call.args[0], self.arrayish) \
                and not _is_static_metadata(call.args[0]):
            sync = d
        if sync is None:
            return
        if self.fn_name in _J001_HOST_BOUNDARY_FUNCS:
            return
        if leaf_loop:
            # The per-LEAF sweep variant (ISSUE 4): O(leaves) round-trips
            # per sweep, the multiplied form of the J001 stall.  More
            # specific rule, reported INSTEAD of J001.
            self.findings.append(Finding(
                self.path, call.lineno, call.col_offset, "J008",
                f"per-leaf host sync {sync} in a loop over pytree leaves "
                f"— O(leaves) device round-trips per sweep; reduce on "
                f"device (tree_finite / multi_tensor_l2norm, one reduce "
                f"per bucket with a BucketStore) and fetch ONE value, or "
                f"stack the per-leaf values into a single transfer"))
            return
        if self.driver and loop_depth == 0:
            return
        if in_while or self.handler_fn:
            # The serving variant (ISSUE 11): a while-serving loop or a
            # request-handler function syncs PER REQUEST / per decode
            # step — reported INSTEAD of J001 (more specific rule, same
            # replacement contract as J008).
            where = ("in a while-serving loop" if in_while else
                     f"in request-handler '{self.fn_name}'")
            self.findings.append(Finding(
                self.path, call.lineno, call.col_offset, "J012",
                f"per-request host sync {sync} {where} — every request "
                f"(or decode step) pays a device round-trip; defer the "
                f"fetch one step behind or batch it, and waive only the "
                f"sanctioned response boundary"))
            return
        where = ("inside a loop" if loop_depth else
                 f"in library function '{self.fn_name}'")
        self.findings.append(Finding(
            self.path, call.lineno, call.col_offset, "J001",
            f"host sync {sync} {where} — blocks dispatch until the device "
            f"round-trip completes; keep the value on device or waive with "
            f"a reason"))

    # .. J007 .................................................................

    _J007_STAGING_CALLS = ("jax.device_put", "np.asarray", "numpy.asarray",
                           "jnp.asarray", "np.array", "numpy.array")

    def _check_j007_call(self, call: ast.Call, loop_depth: int) -> None:
        if loop_depth == 0 or not call.args:
            return
        d = _dotted(call.func)
        if d not in self._J007_STAGING_CALLS:
            return
        if d != "jax.device_put" and not self.driver:
            # The asarray-family half targets TRAINING loops (driver
            # scripts): library code legitimately asarray's inside
            # serialization / per-leaf metadata loops, and its real
            # sync hazards are J001's (arrayish) business.
            return
        arg = call.args[0]
        names = {n.id for n in ast.walk(arg) if isinstance(n, ast.Name)}
        hit = bool(names & self.batch_vars)
        if d == "jax.device_put" and not hit:
            # Re-staging values that are already device arrays is the
            # same per-step stall, whatever name they travel under.
            hit = _is_arrayish(arg, self.arrayish)
        if not hit:
            return
        self.findings.append(Finding(
            self.path, call.lineno, call.col_offset, "J007",
            f"per-step host staging {d} on batch data inside a loop — "
            f"host->device staging belongs in the input engine "
            f"(PrefetchLoader / stage_windows device=...), where it "
            f"overlaps compute instead of serializing with each step"))

    # .. J010 .................................................................

    # Compile-triggering analysis entry points.  The bare attr names fire
    # anywhere in a loop; ``lower``/``compile`` only when the receiver is
    # demonstrably a jitted computation (``jax.jit(f).lower(...)``, a
    # known-jitted name, or a ``.lower(...)`` chain) — ``s.lower()`` on a
    # string and ``re.compile`` must not flag.
    _J010_HARVEST_ATTRS = ("cost_analysis", "memory_analysis")

    def _check_j010_call(self, call: ast.Call, loop_depth: int) -> None:
        if loop_depth == 0:
            return
        f = call.func
        if not isinstance(f, ast.Attribute):
            return
        if f.attr in self._J010_HARVEST_ATTRS:
            what = f".{f.attr}()"
        elif f.attr in ("lower", "compile"):
            recv = f.value
            jitted_recv = (
                (isinstance(recv, ast.Call)
                 and (_is_jax_jit(recv.func)
                      or (isinstance(recv.func, ast.Attribute)
                          and recv.func.attr == "lower")))
                or (isinstance(recv, ast.Name)
                    and self.idx.jitted_name(self.fn, recv.id)))
            if not jitted_recv:
                return
            what = f".{f.attr}()"
        else:
            return
        self.findings.append(Finding(
            self.path, call.lineno, call.col_offset, "J010",
            f"{what} inside a loop — every call re-traces (and "
            f"`.compile()` re-runs the backend, seconds per call on a "
            f"real chip); costs are static per (shapes, dtypes), so "
            f"harvest ONCE before the loop "
            f"(apex_tpu.prof.roofline.harvest_costs) and reuse the "
            f"result"))

    # .. J009 .................................................................

    _J009_CLOCK_CALLS = ("time.time", "time.perf_counter",
                         "time.monotonic", "perf_counter", "monotonic",
                         "timeit.default_timer", "default_timer")

    def _collect_j009(self, call: ast.Call) -> None:
        """Classify one call for the scope-level timing analysis: a
        clock read, a sync point (inline or via a local helper that
        syncs), or a call to a known-jitted callable."""
        if _dotted(call.func) in self._J009_CLOCK_CALLS:
            self._j009_clocks.append((call.lineno, call.col_offset))
            return
        if _is_sync_call(call) or (
                isinstance(call.func, ast.Name)
                and call.func.id in self.idx.sync_defs()):
            self._j009_syncs.append(call.lineno)
            return
        if isinstance(call.func, ast.Name) \
                and self.idx.jitted_name(self.fn, call.func.id):
            self._j009_jits.append((call.lineno, call.func.id))

    def _finish_j009(self) -> None:
        """Pair clock reads around jitted calls: a jitted call between
        two clock reads with no sync inside the span means the elapsed
        time measures ENQUEUE, not compute (async dispatch).  Reported
        at the closing clock read; one finding per scope."""
        if len(self._j009_clocks) < 2 or not self._j009_jits:
            return
        clocks = sorted(self._j009_clocks)
        syncs = sorted(self._j009_syncs)
        for j_line, j_name in sorted(self._j009_jits):
            before = [c for c in clocks if c[0] < j_line]
            after = [c for c in clocks if c[0] > j_line]
            if not before or not after:
                continue
            t_open, t_close = before[-1], after[0]
            if any(t_open[0] < s <= t_close[0] for s in syncs):
                continue
            self.findings.append(Finding(
                self.path, t_close[0], t_close[1], "J009",
                f"wall-clock timing around jitted '{j_name}' with no "
                f"block_until_ready/device_get/value fetch in the timed "
                f"span — jax dispatch is async, so this elapsed time "
                f"measures how fast the host ENQUEUED the program, not "
                f"how long the device ran it; fence with "
                f"jax.block_until_ready(out) or fetch a value before "
                f"reading the second clock"))
            return

    # .. J004 .................................................................

    def _check_j004_call(self, call: ast.Call, loop_depth: int,
                         loop_vars: frozenset) -> None:
        if loop_depth == 0:
            return
        if _is_jax_jit(call.func):
            self.findings.append(Finding(
                self.path, call.lineno, call.col_offset, "J004",
                "jax.jit called inside a loop — a fresh jitted callable "
                "per iteration retraces (and re-compiles) every time; "
                "hoist the jit out of the loop"))
            return
        if not (isinstance(call.func, ast.Name)
                and self.idx.jitted_name(self.fn, call.func.id)):
            return
        # keyword args retrace exactly like positional ones (review:
        # ``step(x, s=i)`` was invisible to the positional-only scan)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in loop_vars:
                bad = arg.id
            elif isinstance(arg, (ast.BinOp, ast.UnaryOp)) \
                    and not any(isinstance(s, (ast.Call, ast.Subscript))
                                for s in ast.walk(arg)) \
                    and any(isinstance(s, ast.Name) and s.id in loop_vars
                            for s in ast.walk(arg)):
                bad = ast.unparse(arg)
            else:
                continue
            self.findings.append(Finding(
                self.path, call.lineno, call.col_offset, "J004",
                f"jitted '{call.func.id}' called with loop-varying Python "
                f"scalar '{bad}' — every new value retraces; pass it as a "
                f"traced array (jnp.asarray) or mark it static if it takes "
                f"few values"))

    # .. J005 .................................................................

    def _check_j005_stmt(self, stmt: ast.stmt, loop_depth: int) -> None:
        if isinstance(stmt, ast.Assign):
            call = stmt.value if isinstance(stmt.value, ast.Call) else None
            targets: Set[str] = set()
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        targets.add(n.id)
        elif isinstance(stmt, ast.Expr):
            call = stmt.value if isinstance(stmt.value, ast.Call) else None
            targets = set()
        else:
            return
        if call is None or not isinstance(call.func, ast.Name):
            return
        donate = self.idx.donated_argnums(self.fn, call.func.id)
        if not donate:
            return
        for i in donate:
            if i >= len(call.args) or not isinstance(call.args[i], ast.Name):
                continue
            name = call.args[i].id
            if name in targets:
                continue                      # rebound by this statement: ok
            if loop_depth > 0:
                self.findings.append(Finding(
                    self.path, call.lineno, call.col_offset, "J005",
                    f"'{name}' is donated to '{call.func.id}' "
                    f"(donate_argnums={i}) inside a loop without being "
                    f"rebound — the next iteration re-donates a "
                    f"deleted buffer"))
                continue
            if self._read_later(name, call.lineno):
                self.findings.append(Finding(
                    self.path, call.lineno, call.col_offset, "J005",
                    f"'{name}' is donated to '{call.func.id}' "
                    f"(donate_argnums={i}) but read again later in "
                    f"'{self.fn_name}' — donated buffers are invalidated"))

    def _read_later(self, name: str, after_line: int) -> bool:
        # self.body covers module scope too — drivers donate-and-read at
        # the top level under no function at all (review: the old
        # fn-only lookup made J005 a no-op exactly there).
        occurrences: List[Tuple[int, int, bool]] = []
        for stmt in self.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Name) and sub.id == name \
                        and sub.lineno > after_line:
                    occurrences.append((sub.lineno, sub.col_offset,
                                        isinstance(sub.ctx, ast.Load)))
        if not occurrences:
            return False
        occurrences.sort()
        # ANY Load on the earliest later line is a read: in
        # ``state = f(state)`` the RHS Load evaluates before the Store
        # even though the Store tokenizes first (review: sorting by
        # column let the col-0 Store mask the same-line read).
        first_line = occurrences[0][0]
        return any(is_load for line, _c, is_load in occurrences
                   if line == first_line)

    # .. J006 .................................................................

    def _check_j006(self, stmt) -> None:
        if not self.jit_scoped:
            return
        test = stmt.test
        traced = None
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                if _rooted_at(sub.func, ("jnp", "lax")):
                    traced = ast.unparse(sub.func)
                    break
                if isinstance(sub.func, ast.Attribute) and sub.func.attr in (
                        "any", "all", "item"):
                    traced = f".{sub.func.attr}()"
                    break
        if traced is None:
            return
        kw = "while" if isinstance(stmt, ast.While) else "if"
        self.findings.append(Finding(
            self.path, stmt.lineno, stmt.col_offset, "J006",
            f"Python '{kw}' branches on traced value ({traced}) inside "
            f"jitted '{self.fn_name}' — use jnp.where/lax.cond; Python "
            f"control flow executes at trace time, not per step"))


# -- engine -------------------------------------------------------------------

def _is_driver_path(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return bool(set(parts) & _DRIVER_PARTS) \
        or os.path.basename(path) in _DRIVER_BASENAMES


def lint_source(src: str, path: str = "<string>",
                driver: Optional[bool] = None) -> List[Finding]:
    """Lint one source string; returns unwaived findings (plus J000 for
    malformed waivers).  ``driver`` overrides path-based classification."""
    if driver is None:
        driver = _is_driver_path(path)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, e.offset or 0, "J000",
                        f"syntax error: {e.msg}")]
    waivers = _Waivers(src, path)
    findings: List[Finding] = []
    idx = _ModuleIndex(tree)
    findings += _check_j002(idx, path)
    findings += _check_j003(tree, path)
    findings += _check_j011(tree, path)
    findings += _check_j013(tree, path)
    findings += _check_j014(tree, path)
    findings += _check_j015(tree, path)
    findings += _check_j016(tree, path)
    _ScopeWalker(idx, path, driver, findings).lint_module(tree)
    kept = [f for f in findings if not waivers.waived(f)]
    kept += waivers.errors
    # Dedup: nested defs are walked by their enclosing function too
    # (J003), and one expression can contain several sync calls
    # (``float(jax.device_get(x))``) — since waivers are line-scoped,
    # one J001 report per line is enough.
    seen: Set[tuple] = set()
    unique = []
    for f in sorted(kept, key=lambda f: (f.line, f.col, f.rule)):
        k = ((f.line, f.rule) if f.rule in ("J001", "J008")
             else (f.line, f.col, f.rule))
        if k in seen:
            continue
        seen.add(k)
        unique.append(f)
    return unique


def lint_file(path: str, driver: Optional[bool] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path, driver=driver)


_SKIP_DIRS = {"__pycache__", ".git", "build", "csrc", "node_modules",
              ".claude"}


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    """Lint files and directory trees; returns all findings sorted by
    (path, line)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                files += [os.path.join(dirpath, f) for f in sorted(filenames)
                          if f.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
        else:
            raise FileNotFoundError(f"not a directory or .py file: {p!r}")
    out: List[Finding] = []
    for f in files:
        out += lint_file(f)
    out.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return out
