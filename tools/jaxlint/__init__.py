"""jaxlint — tracing-safety & dtype-discipline static analyzer for the
apex_tpu stack.

Rules (see ``docs/jaxlint.md`` for the failure each one prevents):

====  =========================================================
J001  host sync in device code (device_get / .item() / float())
J002  jax.jit with non-array Python args not marked static
J003  fp32 dtype leak inside a bf16/amp-cast path
J004  retracing hazard (jit fed varying Python scalars)
J005  use-after-donate of a donate_argnums buffer
J006  Python control flow branching on a traced value under jit
====  =========================================================

Usage::

    python -m tools.jaxlint apex_tpu examples tools bench.py

Inline waiver (MUST carry a reason)::

    x = float(jax.device_get(v))  # jaxlint: disable=J001 -- checkpoint read

The runtime complement — catching the retraces J004 can only guess at
— is ``apex_tpu.prof.assert_trace_count``.
"""

from .linter import Finding, RULES, lint_file, lint_paths, lint_source  # noqa: F401
from .cli import main                                                   # noqa: F401

__all__ = ["Finding", "RULES", "lint_file", "lint_paths", "lint_source",
           "main"]
