"""ZeRO-3 memory-scaling probe (ISSUE 12 acceptance evidence).

Runs in its own process on a forced N-device CPU mesh (the parent sets
``XLA_FLAGS``/``JAX_PLATFORMS``) and prints a JSON ledger comparing
per-device param+optimizer-state bytes under ZeRO-3 (fsdp=N) against
the replicated ZeRO-2 params baseline:

* ``zero3.ratio`` — bytes one device holds / global bytes, from the
  committed shardings (``MeshPlan.state_bytes``, exact);
* ``zero3.xla`` — the compiled sharded step's ``memory_analysis``
  argument/output/temp bytes via ``prof.memory.stats_from_analysis``
  where the backend exposes it (recorded; null on backends that
  don't).

``bench.py`` gates ``zero3.ratio`` at ~1/shard_count and records the
whole ledger in BENCH_EXTRA.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.environ.get(
    "APEX_PROBE_REPO",
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import training
    from apex_tpu.parallel import mesh as M
    from apex_tpu.prof import memory as prof_memory

    devs = jax.devices()
    n = len(devs)
    rng = np.random.RandomState(0)
    # ~1.05M fp32 params -> ~4.2 MB params, ~12.6 MB more as O2
    # masters'+moments' flat buckets
    params = {"w": jnp.asarray(rng.randn(1024, 1024) * 0.02, jnp.float32),
              "b": jnp.zeros((1024,), jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    out = {"devices": n}
    for zero, key in ((3, "zero3"), (2, "zero2")):
        plan = M.MeshPlan(dp=1, fsdp=n, devices=devs)
        ms = M.make_mesh_train_step(loss_fn, training.adam(1e-3), plan,
                                    zero=zero, opt_level="O2")
        state = ms.init(params)
        led = plan.state_bytes((state.params, state.opt_state))
        entry = dict(led, shard_count=n,
                     params_bytes=plan.state_bytes(state.params))
        step = ms.jit_step(state, donate=False)
        x = jnp.asarray(rng.randn(8 * n, 1024), jnp.float32)
        y = jnp.asarray(rng.randn(8 * n, 1024), jnp.float32)
        batch = plan.device_put_batch((x, y))
        try:
            compiled = step.lower(state, batch).compile()  # jaxlint: disable=J010 -- one AOT compile per probed zero level (2 total), the probe's whole purpose
            entry["xla"] = prof_memory.stats_from_analysis(
                compiled.memory_analysis())  # jaxlint: disable=J010 -- single read of the probe executable's ledger
        except Exception as e:
            entry["xla"] = None
            entry["xla_error"] = f"{type(e).__name__}: {e}"
        out[key] = entry
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
