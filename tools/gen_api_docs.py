"""Generate the per-symbol API reference (VERDICT r4 missing #3).

The reference ships sphinx autodoc pages (``/root/reference/docs/source/
*.rst`` for amp / parallel / optimizers / layernorm); this is the
equivalent without the sphinx build dependency: walk the public modules,
emit one markdown page per package under ``docs/api/`` with every public
symbol's signature and docstring.  Regenerate with::

    python tools/gen_api_docs.py

A fast-gate test (tests/test_api_docs.py) fails when the committed pages
drift from the code, the same contract as the README perf table.
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, ROOT)

# module -> page; mirrors the reference's docs/source/*.rst set plus the
# beyond-parity packages.
PAGES = {
    "amp": ["apex_tpu.amp", "apex_tpu.amp.loss_scaler",
            "apex_tpu.amp.properties", "apex_tpu.amp.autocast"],
    "optimizers": ["apex_tpu.optimizers", "apex_tpu.optimizers.functional"],
    "parallel": ["apex_tpu.parallel", "apex_tpu.parallel.distributed",
                 "apex_tpu.parallel.sync_batchnorm",
                 "apex_tpu.parallel.ring_attention",
                 "apex_tpu.parallel.tensor_parallel",
                 "apex_tpu.parallel.pipeline",
                 "apex_tpu.parallel.expert_parallel",
                 "apex_tpu.parallel.zero",
                 "apex_tpu.parallel.mesh",
                 "apex_tpu.parallel.multiproc"],
    "normalization": ["apex_tpu.normalization",
                      "apex_tpu.normalization.fused_bn_act"],
    "ops": ["apex_tpu.ops.flash_attention", "apex_tpu.ops.conv",
            "apex_tpu.ops.attention", "apex_tpu.ops.losses"],
    "multi_tensor": ["apex_tpu.multi_tensor"],
    "bf16_utils": ["apex_tpu.bf16_utils"],
    "training": ["apex_tpu.training"],
    "runtime": ["apex_tpu.runtime"],
    "cache": ["apex_tpu.cache"],
    "prof": ["apex_tpu.prof.capture", "apex_tpu.prof.parse",
             "apex_tpu.prof.analysis", "apex_tpu.prof.ledger",
             "apex_tpu.prof.trace_count", "apex_tpu.prof.timeline",
             "apex_tpu.prof.roofline", "apex_tpu.prof.regress",
             "apex_tpu.prof.fleet", "apex_tpu.prof.memory",
             "apex_tpu.prof.requests"],
    "telemetry": ["apex_tpu.telemetry", "apex_tpu.telemetry.events",
                  "apex_tpu.telemetry.metrics",
                  "apex_tpu.telemetry.watchdog",
                  "apex_tpu.telemetry.export",
                  "apex_tpu.telemetry.tracing",
                  "apex_tpu.telemetry.slo"],
    "rnn_reparam": ["apex_tpu.RNN", "apex_tpu.reparameterization"],
    "contrib": ["apex_tpu.contrib.xentropy", "apex_tpu.contrib.groupbn"],
    "models": ["apex_tpu.models"],
    "checkpoint_data": ["apex_tpu.checkpoint", "apex_tpu.data"],
    "serving": ["apex_tpu.serving", "apex_tpu.serving.engine",
                "apex_tpu.serving.kv_cache", "apex_tpu.serving.hotswap"],
    "quant": ["apex_tpu.quant", "apex_tpu.quant.kernels",
              "apex_tpu.quant.calibrate", "apex_tpu.quant.layers"],
    "tune": ["apex_tpu.tune", "apex_tpu.tune.registry",
             "apex_tpu.tune.measure", "apex_tpu.tune.store",
             "apex_tpu.tune.dispatch", "apex_tpu.tune.space"],
}


def _sig(obj) -> str:
    try:
        sig = inspect.signature(obj)
    except (ValueError, TypeError):
        return "(...)"
    # Stable rendering: elide defaults whose repr embeds a memory address
    # (flax's parent=<_Sentinel object at 0x...>) — they change per
    # interpreter and would keep the drift gate permanently red.
    params = []
    for p in sig.parameters.values():
        if p.default is not inspect.Parameter.empty \
                and " at 0x" in repr(p.default):
            p = p.replace(default=Ellipsis)
        params.append(p)
    return str(sig.replace(parameters=params)).replace(
        "=Ellipsis", "=...")


def _doc(obj) -> str:
    import re
    d = inspect.getdoc(obj) or ""
    # flax dataclass auto-docstrings embed object reprs with memory
    # addresses (the parent _Sentinel); normalize them or every fresh
    # interpreter would "drift" the generated pages.
    return re.sub(r" at 0x[0-9a-f]+", " at 0x...", d.strip())


def _public_symbols(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod) if not n.startswith("_")]
    out = []
    for n in sorted(names):
        o = getattr(mod, n, None)
        if o is None or inspect.ismodule(o):
            continue
        # keep symbols defined in (or re-exported by) apex_tpu only
        mod_name = getattr(o, "__module__", "") or ""
        if not mod_name.startswith("apex_tpu"):
            continue
        out.append((n, o))
    return out


def render_module(mod_name: str) -> str:
    mod = importlib.import_module(mod_name)
    lines = [f"## `{mod_name}`", ""]
    mdoc = _doc(mod)
    if mdoc:
        first = mdoc.split("\n\n")[0]
        lines += [first, ""]
    for name, obj in _public_symbols(mod):
        if inspect.isclass(obj):
            lines.append(f"### class `{name}{_sig(obj)}`")
            lines.append("")
            d = _doc(obj)
            if d:
                lines += [d, ""]
            for mname, m in sorted(vars(obj).items()):
                if mname.startswith("_") or not callable(m):
                    continue
                md = _doc(m)
                lines.append(f"- **`{mname}{_sig(m)}`** — "
                             f"{md.splitlines()[0] if md else ''}")
            lines.append("")
        elif callable(obj):
            lines.append(f"### `{name}{_sig(obj)}`")
            lines.append("")
            d = _doc(obj)
            if d:
                lines += [d, ""]
    return "\n".join(lines)


def generate() -> dict:
    pages = {}
    for page, mods in PAGES.items():
        parts = [f"# API reference — {page}",
                 "",
                 "(generated by `tools/gen_api_docs.py`; do not edit "
                 "by hand)", ""]
        for m in mods:
            try:
                parts.append(render_module(m))
            except Exception as e:
                parts.append(f"## `{m}`\n\n*(import failed: "
                             f"{type(e).__name__}: {e})*\n")
        pages[page] = "\n".join(parts) + "\n"
    return pages


def main(check: bool = False) -> bool:
    outdir = os.path.join(ROOT, "docs", "api")
    os.makedirs(outdir, exist_ok=True)
    ok = True
    for page, text in generate().items():
        path = os.path.join(outdir, f"{page}.md")
        old = None
        if os.path.exists(path):
            with open(path) as f:
                old = f.read()
        if check:
            if old != text:
                print(f"DRIFT: docs/api/{page}.md", file=sys.stderr)
                ok = False
            continue
        if old != text:
            with open(path, "w") as f:
                f.write(text)
            print(f"wrote docs/api/{page}.md")
    return ok


if __name__ == "__main__":
    if not main(check="--check" in sys.argv):
        raise SystemExit(1)
