"""Multi-hundred-step convergence artifact — the L1 gate at real depth.

The reference's L1 suite trains real epochs and compares full loss curves
across opt levels (``/root/reference/tests/L1/common/run_test.sh:21-120``,
``compare.py:36-64``); the repo's ``tests/test_l1_cross_product.py`` is a
6-step trajectory-parity gate.  This tool closes the gap (VERDICT r2
next #2): it trains ResNet-18 for hundreds of steps on a FIXED synthetic
dataset (8 batches cycled, so the loss is actually minimizable) at amp O0
(pure fp32) and O2 (bf16 compute + fp32 masters + dynamic scaling),
records both full loss curves, and asserts

* both runs LEARN: tail-mean loss < 60% of the head-mean loss;
* O2 TRACKS O0: |tail_mean_o2 - tail_mean_o0| / tail_mean_o0 < 15%.

Run on a TPU host (the driver artifact)::

    python tools/convergence.py --steps 300 --out CONVERGENCE_r03.json

The emitted JSON holds the config, both curves, and the gate verdicts;
``tests/test_convergence.py`` runs the same harness at CPU scale inside
the suite.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), _os.pardir)))


def make_fixed_dataset(n_batches, batch, image_size, num_classes, seed=0):
    """A fixed, cycled dataset: unlike per-step random labels (which keep
    the loss pinned near log(C)), a finite sample is memorizable, so the
    loss curve actually falls — what a convergence gate needs."""
    rng = np.random.RandomState(seed)
    xs = [rng.rand(batch, image_size, image_size, 3).astype(np.float32)
          for _ in range(n_batches)]
    ys = [rng.randint(0, num_classes, batch).astype(np.int32)
          for _ in range(n_batches)]
    return xs, ys


def run_curve(opt_level, steps, *, batch, image_size, num_classes,
              arch="resnet18", lr=0.02, loss_scale=None, log_every=50,
              dp=0, force_cpu=False, use_sync_bn=None,
              allreduce_always_fp32=False, perturb_eps=0.0):
    """One loss curve.  ``dp=N`` trains the SAME function 8-way-style
    data-parallel instead: shard_map over an N-device mesh with SyncBN
    (whole-batch statistics) and DDP gradient averaging, the reference's
    distributed L1 configuration (``tests/L1/cross_product_distributed/
    run.sh``) at trajectory depth.

    ``force_cpu`` pins the run to the CPU backend — required for the DP
    gate on a single-chip host (the virtual multi-device mesh is CPU-only,
    and the single-process oracle must share the DP run's backend or
    bf16 numeric differences would drown the reduction-order signal).
    Note ``JAX_PLATFORMS=cpu`` alone does NOT demote the TPU plugin's
    default-backend claim on some setups; explicit device pinning does."""
    import jax
    import jax.numpy as jnp

    kw = dict(batch=batch, image_size=image_size, num_classes=num_classes,
              arch=arch, lr=lr, loss_scale=loss_scale, log_every=log_every,
              dp=dp, use_sync_bn=use_sync_bn,
              allreduce_always_fp32=allreduce_always_fp32,
              perturb_eps=perturb_eps)
    if force_cpu:
        cpu0 = jax.devices("cpu")[0]
        with jax.default_device(cpu0):
            return _run_curve_inner(opt_level, steps, **kw)
    return _run_curve_inner(opt_level, steps, **kw)


def _run_curve_inner(opt_level, steps, *, batch, image_size, num_classes,
                     arch, lr, loss_scale, log_every, dp, use_sync_bn=None,
                     allreduce_always_fp32=False, perturb_eps=0.0):
    import jax
    import jax.numpy as jnp

    from apex_tpu import training
    from apex_tpu.models import ResNet18, ResNet50
    from apex_tpu.training import make_train_step

    model_cls = {"resnet18": ResNet18, "resnet50": ResNet50}[arch]
    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    axis_name = "data" if dp else None
    # SyncBN in the DP run so per-shard batches still produce whole-batch
    # statistics; init without the axis (outside shard_map).  The
    # single-process ORACLE for a DP comparison must also use SyncBN
    # (axis_name=None == whole-batch stats via the same Welford-parallel
    # arithmetic): plain flax BatchNorm computes the same statistics by a
    # DIFFERENT summation algorithm, and under bf16 that ~1e-5 head
    # difference amplifies chaotically (measured 3e-5 at step 0 -> 0.03
    # by step 10 when the oracle used plain BN).
    sync_bn = bool(dp) if use_sync_bn is None else use_sync_bn
    model = model_cls(num_classes=num_classes, dtype=dtype,
                      sync_bn=sync_bn, axis_name=axis_name)
    init_model = model_cls(num_classes=num_classes, dtype=dtype)

    xs, ys = make_fixed_dataset(8, batch, image_size, num_classes)
    variables = init_model.init(jax.random.PRNGKey(0), jnp.asarray(xs[0]),
                                train=True)

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    tx = training.sgd(lr=lr, momentum=0.9)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=opt_level, loss_scale=loss_scale,
        axis_name=axis_name, has_model_state=True,
        allreduce_always_fp32=allreduce_always_fp32)
    if perturb_eps:
        # Chaos-envelope control (VERDICT r4 weak #5): scale the INPUTS by
        # (1 + eps) with eps at fp32-reduction-order magnitude.  A weight
        # perturbation at 1e-7 is ERASED by the bf16 compute cast (measured:
        # zero loss difference over 8 steps) — but reduction-order noise in
        # DP enters through fp32 intermediates (SyncBN statistics) whose
        # bf16-cast downstream values flip quantization boundaries.  An
        # fp32-epsilon input scale injects a difference by the same
        # mechanism: most elements round to the same bf16, a boundary
        # fraction flips, and the flips amplify step over step.  Comparing
        # this curve to the unperturbed one yields the honest chaos
        # envelope for the O2 DP head gap.
        xs = [x * (1.0 + np.float32(perturb_eps)) for x in xs]
    state = init_fn(variables["params"], variables["batch_stats"])
    if dp:
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        # Prefer the (virtual) CPU mesh for the gate; the default backend
        # may be a single chip.
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            devs = jax.devices()
        if len(devs) < dp:
            raise SystemExit(
                f"--dp {dp} needs {dp} devices, found {len(devs)} "
                f"— a shrunken mesh would record a vacuously-green 'DP' "
                f"verdict (run with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={dp} "
                f"for the virtual-mesh gate)")
        mesh = Mesh(np.array(devs[:dp]), ("data",))
        step = jax.jit(shard_map(
            step_fn, mesh=mesh,
            in_specs=(P(), (P("data"), P("data"))), out_specs=(P(), P())),
            donate_argnums=(0,))
    else:
        step = jax.jit(step_fn, donate_argnums=(0,))

    # Batches pre-uploaded once; per-step losses stay ON DEVICE and are
    # fetched in ONE stacked transfer at the end — a per-step float()
    # costs a full round-trip through a tunneled chip (~0.1-0.5 s), which
    # made a 2x300-step run exceed 10 minutes while the compute itself is
    # seconds.
    dev_batches = [(jnp.asarray(x), jnp.asarray(y)) for x, y in zip(xs, ys)]
    loss_refs = []
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, dev_batches[i % len(dev_batches)])
        loss_refs.append(jnp.ravel(metrics["loss"])[0])
        if log_every and i % log_every == 0:
            print(f"  [{opt_level}{'/dp' + str(dp) if dp else ''}] "
                  f"step {i}  loss {float(loss_refs[-1]):.4f}", flush=True)
    losses = [float(v) for v in np.asarray(jnp.stack(loss_refs))]
    return losses, time.perf_counter() - t0


def gate_dp(losses_single, losses_dp, *, head=6, tail=30,
            head_tol=2e-3, tail_tol=0.10, head_gate=True):
    """Deep DP-vs-single agreement gate (VERDICT r3 next #7), two-tier:

    * ``head_gate=True`` (the fp32 / O0 tier): the first ``head`` steps
      must agree to near-reduction-order tolerance.  In fp32 the runs
      compute the same function and only summation order differs;
      measured on this harness the trajectories are EXACT for 4 steps,
      then the difference grows ~10x/step through BN's variance
      divisions (3.9e-6 at step 4, 6e-5 at step 5) — 6 steps @ 2e-3
      leaves a ~30x margin while still catching any real reduction bug
      (a wrong mean shows up at step 0).
    * ``head_gate=False`` (the bf16 / O2 tier): a per-step head gate is
      NOT honest under bf16 — a 1e-7 stat difference flips bf16
      quantization boundaries in the activations (measured 2.6e-5 loss
      difference at step 0, 0.03 by step 10 on this harness), so only
      the statistical criterion applies.  PROVEN by the r5 controls
      (``--o2-controls``, ``CONVERGENCE_DP_r05.json``): (a) the
      ``allreduce_always_fp32`` run is bit-identical to the plain DP run
      (grads are fp32 masters pre-summed by shard_map's implicit psum —
      allreduce dtype ruled out); (b) the step-0 single-vs-DP gap, where
      no optimizer or allreduce has executed, is 1.0e-7 in fp32 vs
      2.5e-5 in bf16 — pure forward reduction order, amplified ~250x by
      bf16 quantization; (c) a 1e-7 relative INPUT epsilon produces a
      head divergence of 0.0198 — 2.6x LARGER than the observed DP gap
      (0.0075), so the gap sits well inside the chaos envelope of any
      epsilon-level difference.

    Both tiers require tail-mean agreement within ``tail_tol`` and the
    DP run actually learning."""
    ls, ld = np.asarray(losses_single), np.asarray(losses_dp)
    head_rel = float(np.max(np.abs(ls[:head] - ld[:head])
                            / np.maximum(np.abs(ls[:head]), 1e-6)))
    tail_s = float(np.mean(ls[-tail:]))
    tail_d = float(np.mean(ld[-tail:]))
    tail_rel = abs(tail_d - tail_s) / max(tail_s, 1e-6)
    learned = ld[-tail:].mean() < 0.6 * ld[:head].mean()
    ok = tail_rel < tail_tol and bool(learned)
    if head_gate:
        ok = ok and head_rel < head_tol
    return {
        "head_max_rel": head_rel, "head_tol": head_tol,
        "head_gate": bool(head_gate),
        "tail_mean_single": tail_s, "tail_mean_dp": tail_d,
        "tail_rel_gap": tail_rel, "tail_tol": tail_tol,
        "dp_learned": bool(learned),
        "ok": ok,
    }


def gate(losses_o0, losses_o2, *, tail=50, head=10,
         learn_factor=0.6, track_tol=0.15):
    head_o0 = float(np.mean(losses_o0[:head]))
    head_o2 = float(np.mean(losses_o2[:head]))
    tail_o0 = float(np.mean(losses_o0[-tail:]))
    tail_o2 = float(np.mean(losses_o2[-tail:]))
    learned_o0 = tail_o0 < learn_factor * head_o0
    learned_o2 = tail_o2 < learn_factor * head_o2
    rel = abs(tail_o2 - tail_o0) / tail_o0
    return {
        "head_mean_o0": head_o0, "head_mean_o2": head_o2,
        "tail_mean_o0": tail_o0, "tail_mean_o2": tail_o2,
        "o0_learned": learned_o0, "o2_learned": learned_o2,
        "rel_tail_gap": rel, "track_tol": track_tol,
        "o2_tracks_o0": rel < track_tol,
        "ok": learned_o0 and learned_o2 and rel < track_tol,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--arch", default="resnet18",
                    choices=["resnet18", "resnet50"])
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--dp", type=int, default=0,
                    help="also run an N-way DP O2 curve (shard_map + "
                    "SyncBN) and gate it against the single-process one")
    ap.add_argument("--o2-controls", action="store_true",
                    help="with --dp: run the two O2 divergence controls "
                    "(allreduce_always_fp32 + epsilon-perturbation chaos "
                    "envelope, VERDICT r4 next #5)")
    ap.add_argument("--out", default=None, help="write full JSON artifact")
    args = ap.parse_args()

    import jax
    cfg = dict(steps=args.steps, batch=args.batch,
               image_size=args.image_size, num_classes=args.num_classes,
               arch=args.arch, lr=args.lr,
               backend=jax.default_backend(),
               device_kind=jax.devices()[0].device_kind)

    # With --dp everything (including the single-process oracle curves)
    # runs on the CPU backend: the DP mesh is CPU-virtual, and comparing
    # a TPU O2 curve against a CPU DP curve would measure backend
    # numerics, not reduction order.
    force_cpu = bool(args.dp)
    if force_cpu:
        cfg["backend"] = "cpu (forced for --dp virtual mesh)"
    losses_o0, dt0 = run_curve("O0", args.steps, batch=args.batch,
                               image_size=args.image_size,
                               num_classes=args.num_classes, arch=args.arch,
                               lr=args.lr, force_cpu=force_cpu)
    losses_o2, dt2 = run_curve("O2", args.steps, batch=args.batch,
                               image_size=args.image_size,
                               num_classes=args.num_classes, arch=args.arch,
                               lr=args.lr, loss_scale="dynamic",
                               force_cpu=force_cpu)
    verdict = gate(losses_o0, losses_o2)
    artifact = {"config": cfg, "verdict": verdict,
                "wall_s_o0": round(dt0, 1), "wall_s_o2": round(dt2, 1),
                "losses_o0": [round(l, 5) for l in losses_o0],
                "losses_o2": [round(l, 5) for l in losses_o2]}
    dp_verdict = None
    if args.dp:
        # Two-tier DP gate (see gate_dp): O0/fp32 with the tight head
        # gate, O2/bf16 statistical.  Oracles are single-process with
        # SyncBN (axis=None) — the same statistics arithmetic as the DP
        # runs, so the fp32 comparison isolates reduction order.
        kw = dict(batch=args.batch, image_size=args.image_size,
                  num_classes=args.num_classes, arch=args.arch, lr=args.lr,
                  use_sync_bn=True, force_cpu=True)
        curves = {}
        t_dp = 0.0
        rows = [
            ("o0_single", "O0", None, 0, {}),
            ("o0_dp", "O0", None, args.dp, {}),
            ("o2_single", "O2", "dynamic", 0, {}),
            ("o2_dp", "O2", "dynamic", args.dp, {}),
        ]
        if args.o2_controls:
            rows += [
                # Control 1 (VERDICT r4 next #5 as written): same O2 DP run
                # with allreduce_always_fp32=True.  PREDICTION, recorded
                # here so the artifact is falsifiable: a NO-OP on this
                # harness — O2 grads are w.r.t. the fp32 masters (already
                # fp32) and arrive pre-summed by shard_map's implicit
                # broadcast-transpose psum, so the flag's upcast never
                # executes.  An unchanged curve PROVES the divergence does
                # not come from allreduce dtype.
                ("o2_dp_fp32allreduce", "O2", "dynamic", args.dp,
                 {"allreduce_always_fp32": True}),
                # Control 2: the chaos envelope.  Scales ALL inputs by
                # (1 + 1e-7) — an fp32-epsilon-class difference entering
                # through the same door as reduction-order noise (values
                # near bf16 quantization midpoints flip; see run_curve's
                # perturb_eps comment — a single-weight nudge is erased
                # outright by the bf16 cast).  If by the head window it
                # produces a loss gap of the same order as the observed DP
                # gap, the gap is bf16-forward amplification of
                # reduction order, bounded.
                ("o2_single_perturbed", "O2", "dynamic", 0,
                 {"perturb_eps": 1e-7}),
            ]
        for name, lvl, scale, dp_n, extra_kw in rows:
            curves[name], dt = run_curve(lvl, args.steps, loss_scale=scale,
                                         dp=dp_n, **kw, **extra_kw)
            if dp_n:
                t_dp += dt
        dp_verdict = {
            "o0": gate_dp(curves["o0_single"], curves["o0_dp"],
                          head_gate=True),
            "o2": gate_dp(curves["o2_single"], curves["o2_dp"],
                          head_gate=False),
        }
        if args.o2_controls:
            ls = np.asarray(curves["o2_single"])
            head = 6
            env = np.asarray(curves["o2_single_perturbed"])
            ctrl = gate_dp(curves["o2_single"],
                           curves["o2_dp_fp32allreduce"], head_gate=False)
            identical = curves["o2_dp_fp32allreduce"] == curves["o2_dp"]
            observed = dp_verdict["o2"]["head_max_rel"]
            envelope = float(np.max(np.abs(ls[:head] - env[:head])
                                    / np.maximum(np.abs(ls[:head]), 1e-6)))
            # Step-0 gaps: BEFORE any optimizer update or gradient
            # allreduce has run, the single and DP losses already differ —
            # the difference can only be forward-pass reduction order
            # (SyncBN psum vs single-device summation).  The O0 (fp32)
            # step-0 gap is the raw reduction-order magnitude; the O2
            # (bf16) step-0 gap shows its amplification through bf16
            # quantization.  No DDP machinery is even reachable at step 0.
            s0_o0 = abs(curves["o0_dp"][0] - curves["o0_single"][0]) / max(
                abs(curves["o0_single"][0]), 1e-6)
            s0_o2 = abs(curves["o2_dp"][0] - curves["o2_single"][0]) / max(
                abs(curves["o2_single"][0]), 1e-6)
            dp_verdict["o2_controls"] = {
                "fp32_allreduce": ctrl,
                # bit-identical curves = the flag is a no-op here (grads
                # already fp32 + pre-summed), ruling OUT allreduce dtype:
                "fp32_allreduce_identical_to_dp": bool(identical),
                "step0_rel_gap_o0_fp32": float(s0_o0),
                "step0_rel_gap_o2_bf16": float(s0_o2),
                "perturb_eps": 1e-7,
                "perturbation_head_max_rel": envelope,
                "observed_dp_head_max_rel": observed,
                # the claim under test: the DP head gap is within ~the
                # chaos envelope of an epsilon-level input difference
                "dp_gap_within_chaos_envelope": bool(
                    observed <= 10.0 * max(envelope, 1e-12)),
            }
        dp_verdict["ok"] = dp_verdict["o0"]["ok"] and dp_verdict["o2"]["ok"]
        artifact["dp_verdict"] = dp_verdict
        artifact["wall_s_dp"] = round(t_dp, 1)
        for name, losses in curves.items():
            artifact[f"losses_{name}_syncbn"] = [round(l, 5)
                                                 for l in losses]
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f)
    ok = verdict["ok"] and (dp_verdict is None or dp_verdict["ok"])
    print(json.dumps({"convergence_ok": ok, **verdict,
                      **({"dp": dp_verdict} if dp_verdict else {}),
                      "steps": args.steps, "backend": cfg["backend"]}))
    if not ok:
        raise SystemExit("CONVERGENCE GATE FAILED")


if __name__ == "__main__":
    main()
