"""Multi-hundred-step convergence artifact — the L1 gate at real depth.

The reference's L1 suite trains real epochs and compares full loss curves
across opt levels (``/root/reference/tests/L1/common/run_test.sh:21-120``,
``compare.py:36-64``); the repo's ``tests/test_l1_cross_product.py`` is a
6-step trajectory-parity gate.  This tool closes the gap (VERDICT r2
next #2): it trains ResNet-18 for hundreds of steps on a FIXED synthetic
dataset (8 batches cycled, so the loss is actually minimizable) at amp O0
(pure fp32) and O2 (bf16 compute + fp32 masters + dynamic scaling),
records both full loss curves, and asserts

* both runs LEARN: tail-mean loss < 60% of the head-mean loss;
* O2 TRACKS O0: |tail_mean_o2 - tail_mean_o0| / tail_mean_o0 < 15%.

Run on a TPU host (the driver artifact)::

    python tools/convergence.py --steps 300 --out CONVERGENCE_r03.json

The emitted JSON holds the config, both curves, and the gate verdicts;
``tests/test_convergence.py`` runs the same harness at CPU scale inside
the suite.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), _os.pardir)))


def make_fixed_dataset(n_batches, batch, image_size, num_classes, seed=0):
    """A fixed, cycled dataset: unlike per-step random labels (which keep
    the loss pinned near log(C)), a finite sample is memorizable, so the
    loss curve actually falls — what a convergence gate needs."""
    rng = np.random.RandomState(seed)
    xs = [rng.rand(batch, image_size, image_size, 3).astype(np.float32)
          for _ in range(n_batches)]
    ys = [rng.randint(0, num_classes, batch).astype(np.int32)
          for _ in range(n_batches)]
    return xs, ys


def run_curve(opt_level, steps, *, batch, image_size, num_classes,
              arch="resnet18", lr=0.02, loss_scale=None, log_every=50):
    import jax
    import jax.numpy as jnp

    from apex_tpu import training
    from apex_tpu.models import ResNet18, ResNet50
    from apex_tpu.training import make_train_step

    model_cls = {"resnet18": ResNet18, "resnet50": ResNet50}[arch]
    dtype = jnp.bfloat16 if opt_level in ("O2", "O3") else jnp.float32
    model = model_cls(num_classes=num_classes, dtype=dtype)

    xs, ys = make_fixed_dataset(8, batch, image_size, num_classes)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(xs[0]),
                           train=True)

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, updated = model.apply(
            {"params": p, "batch_stats": ms}, xb, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        return loss, updated["batch_stats"]

    tx = training.sgd(lr=lr, momentum=0.9)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level=opt_level, loss_scale=loss_scale,
        has_model_state=True)
    state = init_fn(variables["params"], variables["batch_stats"])
    step = jax.jit(step_fn, donate_argnums=(0,))

    losses = []
    t0 = time.perf_counter()
    for i in range(steps):
        b = (jnp.asarray(xs[i % len(xs)]), jnp.asarray(ys[i % len(ys)]))
        state, metrics = step(state, b)
        losses.append(float(metrics["loss"]))   # host sync per step
        if log_every and i % log_every == 0:
            print(f"  [{opt_level}] step {i}  loss {losses[-1]:.4f}",
                  flush=True)
    return losses, time.perf_counter() - t0


def gate(losses_o0, losses_o2, *, tail=50, head=10,
         learn_factor=0.6, track_tol=0.15):
    head_o0 = float(np.mean(losses_o0[:head]))
    head_o2 = float(np.mean(losses_o2[:head]))
    tail_o0 = float(np.mean(losses_o0[-tail:]))
    tail_o2 = float(np.mean(losses_o2[-tail:]))
    learned_o0 = tail_o0 < learn_factor * head_o0
    learned_o2 = tail_o2 < learn_factor * head_o2
    rel = abs(tail_o2 - tail_o0) / tail_o0
    return {
        "head_mean_o0": head_o0, "head_mean_o2": head_o2,
        "tail_mean_o0": tail_o0, "tail_mean_o2": tail_o2,
        "o0_learned": learned_o0, "o2_learned": learned_o2,
        "rel_tail_gap": rel, "track_tol": track_tol,
        "o2_tracks_o0": rel < track_tol,
        "ok": learned_o0 and learned_o2 and rel < track_tol,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--arch", default="resnet18",
                    choices=["resnet18", "resnet50"])
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--out", default=None, help="write full JSON artifact")
    args = ap.parse_args()

    import jax
    cfg = dict(steps=args.steps, batch=args.batch,
               image_size=args.image_size, num_classes=args.num_classes,
               arch=args.arch, lr=args.lr,
               backend=jax.default_backend(),
               device_kind=jax.devices()[0].device_kind)

    losses_o0, dt0 = run_curve("O0", args.steps, batch=args.batch,
                               image_size=args.image_size,
                               num_classes=args.num_classes, arch=args.arch,
                               lr=args.lr)
    losses_o2, dt2 = run_curve("O2", args.steps, batch=args.batch,
                               image_size=args.image_size,
                               num_classes=args.num_classes, arch=args.arch,
                               lr=args.lr, loss_scale="dynamic")
    verdict = gate(losses_o0, losses_o2)
    artifact = {"config": cfg, "verdict": verdict,
                "wall_s_o0": round(dt0, 1), "wall_s_o2": round(dt2, 1),
                "losses_o0": [round(l, 5) for l in losses_o0],
                "losses_o2": [round(l, 5) for l in losses_o2]}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f)
    print(json.dumps({"convergence_ok": verdict["ok"], **verdict,
                      "steps": args.steps, "backend": cfg["backend"]}))
    if not verdict["ok"]:
        raise SystemExit("CONVERGENCE GATE FAILED")


if __name__ == "__main__":
    main()
