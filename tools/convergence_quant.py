"""O4-vs-O2 convergence artifact on the small LM (ISSUE 13 gate).

The int8 engine's acceptance is a TRAJECTORY property, not a one-matmul
tolerance: with every GPT projection quantized (per-tensor calibrated
activations, per-channel weights, bf16 straight-through backward), the
O4 loss curve must TRACK the O2 curve over hundreds of optimization
steps on a memorizable LM dataset — the same harness shape as
``tools/convergence.py`` (O2-vs-O0) and the CONVERGENCE_*.json artifact
family.

Recipe under test is exactly docs/quant.md's: observe a few batches
through the ``mode="observe"`` model, freeze the delayed-amax-history
calibration, rebuild with ``QuantConfig.frozen`` and train at
``opt_level="O4"`` (storage semantics identical to O2 — the quantized
sites are the ONLY difference between the two curves).

Run (CPU works; the artifact records the backend)::

    python tools/convergence_quant.py --steps 240 --out CONVERGENCE_QUANT.json

``tests/test_quant.py`` runs the same harness at reduced depth in CI,
and ``tests/test_convergence.py`` re-validates any committed artifact.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

try:
    import apex_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout
    _sys.path.insert(0, _os.path.abspath(_os.path.join(
        _os.path.dirname(__file__), _os.pardir)))

from convergence import gate  # noqa: E402  (same gate definition: learn + track)


def make_lm_dataset(n_batches, batch, seq, vocab, seed=0, noise=0.1):
    """Fixed noisy-bigram next-token batches.

    A memorize-to-zero dataset (convergence.py's fixed random batches)
    is the WRONG gate for quantization: O2 drives the loss toward 0
    while int8 forward noise sets a small irreducible floor, so the
    relative tail gap diverges on a vanishing denominator.  A noisy
    bigram process has a nonzero entropy floor BOTH levels converge to
    (next token = a fixed random successor with prob ``1 - noise``,
    uniform otherwise; enough distinct batches that memorizing the
    noise is out of capacity) — the honest scale for "O4 tracks O2"."""
    rng = np.random.RandomState(seed)
    succ = rng.randint(0, vocab, vocab)           # the bigram table
    out = []
    for _ in range(n_batches):
        b = np.empty((batch, seq + 1), np.int64)
        b[:, 0] = rng.randint(0, vocab, batch)
        for t in range(seq):
            flip = rng.rand(batch) < noise
            b[:, t + 1] = np.where(flip, rng.randint(0, vocab, batch),
                                   succ[b[:, t]])
        out.append(b.astype(np.int32))
    return out


def build_model(quant_cfg=None, *, vocab=256, hidden=64, layers=2,
                heads=4, seq=32):
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPT

    return GPT(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
               num_heads=heads, mlp_dim=hidden * 4, max_len=seq,
               dtype=jnp.bfloat16, attention_impl="blockwise",
               quant=quant_cfg)


def calibrate(params, batches, *, n_observe=4, history=16, mode="max",
              **model_kw):
    """The observation phase: run ``n_observe`` batches through the
    observe-mode model, harvest the quant_stats collection per batch,
    freeze the delayed-amax-history calibration."""
    import jax

    from apex_tpu import quant

    obs = build_model(quant.QuantConfig.observe(), **model_kw)
    cal = quant.Calibrator(history=history)
    for b in batches[:n_observe]:
        _, st = obs.apply({"params": params}, b[:, :-1],
                          mutable=["quant_stats"])
        cal.harvest(jax.device_get(st["quant_stats"]))  # jaxlint: disable=J001 -- the calibration observation boundary: absmax stats must reach the host to freeze scales; a handful of batches, not the training loop
    return cal.freeze(mode)


def run_lm_curve(opt_level, steps, *, batch=8, seq=32, vocab=64,
                 hidden=64, layers=2, heads=4, lr=3e-3, n_batches=64,
                 seed=0, log_every=0, interpret=False,
                 calibration=None):
    """One LM loss curve at ``opt_level``.  For O4 a calibration is
    harvested from the initial params (or passed in); every other knob
    is shared with the O2 run, so the curves differ ONLY by the
    quantized sites."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import quant, training
    from apex_tpu.training import make_train_step

    model_kw = dict(vocab=vocab, hidden=hidden, layers=layers,
                    heads=heads, seq=seq)
    batches = make_lm_dataset(n_batches, batch, seq, vocab, seed=seed)
    plain = build_model(None, **model_kw)
    params = plain.init(jax.random.PRNGKey(seed),
                        jnp.asarray(batches[0][:, :-1]))["params"]

    if opt_level == "O4":
        if calibration is None:
            calibration = calibrate(params, batches, **model_kw)
        model = build_model(
            quant.QuantConfig.frozen(calibration, interpret=interpret),
            **model_kw)
    else:
        model = plain

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b[:, :-1])
        logp = jax.nn.log_softmax(
            logits.reshape(-1, vocab).astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(
            logp, b[:, 1:].reshape(-1)[:, None], axis=1))

    tx = training.adam(lr=lr)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level=opt_level,
                                       loss_scale="dynamic")
    state = init_fn(params)
    step = jax.jit(step_fn, donate_argnums=(0,))
    dev = [jnp.asarray(b) for b in batches]
    refs = []
    t0 = time.perf_counter()
    for i in range(steps):
        state, m = step(state, dev[i % len(dev)])
        refs.append(jnp.ravel(m["loss"])[0])
        if log_every and i % log_every == 0:
            print(f"  [{opt_level}] step {i} "
                  f"loss {float(refs[-1]):.4f}", flush=True)
    losses = [float(v) for v in np.asarray(jnp.stack(refs))]
    return losses, time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=240)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--track-tol", type=float, default=0.15)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    cfg = dict(steps=args.steps, batch=args.batch, seq=args.seq,
               vocab=args.vocab, hidden=args.hidden, layers=args.layers,
               lr=args.lr, backend=jax.default_backend(),
               device_kind=jax.devices()[0].device_kind)
    kw = dict(batch=args.batch, seq=args.seq, vocab=args.vocab,
              hidden=args.hidden, layers=args.layers, lr=args.lr,
              log_every=50)
    losses_o2, dt2 = run_lm_curve("O2", args.steps, **kw)
    losses_o4, dt4 = run_lm_curve("O4", args.steps, **kw)
    verdict = gate(losses_o2, losses_o4, track_tol=args.track_tol)
    # gate() names its operands o0/o2; restate them as o2/o4 so a
    # reader never mistakes which levels were compared
    ren = {"head_mean_o0": "head_mean_o2", "head_mean_o2": "head_mean_o4",
           "tail_mean_o0": "tail_mean_o2", "tail_mean_o2": "tail_mean_o4",
           "o0_learned": "o2_learned", "o2_learned": "o4_learned",
           "o2_tracks_o0": "o4_tracks_o2"}
    verdict = {ren.get(k, k): v for k, v in verdict.items()}
    artifact = {"kind": "quant", "config": cfg,
                "verdict": {**verdict, "compared": "O4 vs O2"},
                "wall_s_o2": round(dt2, 1), "wall_s_o4": round(dt4, 1),
                "losses_o2": [round(l, 5) for l in losses_o2],
                "losses_o4": [round(l, 5) for l in losses_o4]}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f)
    print(json.dumps({"convergence_quant_ok": verdict["ok"],
                      **verdict, "steps": args.steps,
                      "backend": cfg["backend"]}))
    if not verdict["ok"]:
        raise SystemExit("CONVERGENCE_QUANT GATE FAILED")


if __name__ == "__main__":
    main()
