#!/usr/bin/env bash
# Install/tier matrix — the runnable analog of the reference's
# tests/docker_extension_builds/run.sh:16-40 (build apex across ~7 images
# and assert each tier works).  The TPU build's matrix is degradation
# tiers rather than CUDA/toolchain images:
#
#   tier 1: full        — native C++ runtime + Pallas kernels
#   tier 2: no-native   — Python flatten/decode fallbacks
#   tier 3: no-pallas   — jnp kernels (APEX_TPU_DISABLE_PALLAS=1)
#   tier 4: bare        — both fallbacks at once
#
# Each tier runs the install-matrix gate (tier-equivalence tests) plus an
# import smoke.  Run from the repo root; ~5 min on an 8-core box.
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# test_multi_tensor.py rides along for the flat-bucket matrix (ISSUE 4):
# the bucket engine is pure XLA, so every degradation tier must keep its
# numerics bit-identical.  test_telemetry.py rides along for the
# run-telemetry matrix (ISSUE 5): the event stream is pure host Python,
# so every tier must emit identical event shapes and keep the disabled
# path a bitwise no-op.  test_roofline.py + test_watchdog.py ride along
# for the attribution/health engines (ISSUE 6): cost harvesting is a
# static jaxpr walk and the watchdog a pure host fold, so every tier
# must produce identical ledgers/alerts.
FAST="python -m pytest tests/test_install_matrix.py tests/test_multi_tensor.py tests/test_telemetry.py tests/test_roofline.py tests/test_watchdog.py -q"

echo "=== tier 1: full (native + pallas) ==="
python setup.py build_native
$FAST

echo "=== tier 2: no-native (python flatten/decode) ==="
# APEX_TPU_DISABLE_NATIVE short-circuits the lazy builder (which would
# otherwise just rebuild the .so with the g++ tier 1 proved present)
APEX_TPU_DISABLE_NATIVE=1 $FAST

echo "=== tier 3: no-pallas (jnp kernels) ==="
APEX_TPU_DISABLE_PALLAS=1 $FAST

echo "=== tier 4: bare (both fallbacks) ==="
APEX_TPU_DISABLE_NATIVE=1 APEX_TPU_DISABLE_PALLAS=1 $FAST

echo "=== import smoke from outside the tree ==="
(cd /tmp && PYTHONPATH="$OLDPWD" python -c "
import apex_tpu
from apex_tpu import amp, optimizers, parallel, normalization
print('import surface ok:', apex_tpu.__name__)")

echo "ALL TIERS GREEN"
