#!/usr/bin/env bash
# Install/tier matrix — the runnable analog of the reference's
# tests/docker_extension_builds/run.sh:16-40 (build apex across ~7 images
# and assert each tier works).  The TPU build's matrix is degradation
# tiers rather than CUDA/toolchain images:
#
#   tier 1: full        — native C++ runtime + Pallas kernels
#   tier 2: no-native   — Python flatten/decode fallbacks
#   tier 3: no-pallas   — jnp kernels (APEX_TPU_DISABLE_PALLAS=1)
#   tier 4: bare        — both fallbacks at once
#
# Each tier runs the install-matrix gate (tier-equivalence tests) plus an
# import smoke.  Run from the repo root; ~5 min on an 8-core box.
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# test_multi_tensor.py rides along for the flat-bucket matrix (ISSUE 4):
# the bucket engine is pure XLA, so every degradation tier must keep its
# numerics bit-identical.  test_telemetry.py rides along for the
# run-telemetry matrix (ISSUE 5): the event stream is pure host Python,
# so every tier must emit identical event shapes and keep the disabled
# path a bitwise no-op.  test_roofline.py + test_watchdog.py ride along
# for the attribution/health engines (ISSUE 6): cost harvesting is a
# static jaxpr walk and the watchdog a pure host fold, so every tier
# must produce identical ledgers/alerts.
# test_contrib.py + test_fused_bn_act.py ride along for the conv-path
# fusion engine (ISSUE 7): the tier-parity tests run the REAL pallas
# kernels in interpret mode against the jnp references, so the
# no-pallas tiers must stay numerically identical.  test_cache.py rides
# for the warm-start engine (AOT warmup is pure host machinery — every
# tier must keep zero-compile-after-step-0 and bitwise parity).
# test_checkpoint.py + test_faultinject.py ride for the elastic
# fault-tolerant runtime (ISSUE 9): serialization, manifest validation,
# and kill-and-resume bit-parity are pure host + XLA machinery, so
# every degradation tier must recover identically (the faultinject
# children inherit the tier env vars through the harness).
# test_fleet.py + test_export.py + test_memory.py ride for the fleet
# observability layer (ISSUE 10): the merge/aligner and the Prometheus
# renderer are pure host JSON/text, and the memory walk a static jaxpr
# replay — every tier must produce identical attributions and
# expositions.  test_serving.py rides for the inference engine (ISSUE
# 11): the paged cache, AOT bucket table, scheduler, and hot-swap are
# host machinery over plain XLA programs, so every degradation tier
# must serve bitwise-identical greedy tokens.  test_mesh.py rides for
# the mesh frontend (ISSUE 12): the ZeRO-2/3 sharding engine is pure
# XLA collectives over the flat-bucket store, so every tier must hold
# the bitwise zero1-parity and 1/N state-sharding contracts.
# test_quant.py rides for the int8 engine (ISSUE 13): the pallas tiers
# run the REAL quantized-matmul kernel via interpret=True, the
# no-pallas tiers the jnp reference — every tier must hold the
# kernel-parity, O4-fallback-bitwise-O2, and int8-KV decode contracts.
# test_conv.py rides for the Pallas implicit-GEMM conv (ISSUE 18): the
# interpret kernels, the fused conv+bn_relu_residual epilogue, and the
# conv_cls resnet hook must match the XLA oracle on every tier.
# test_tune.py rides for the kernel autotuner (ISSUE 14): the config
# cache is pure host JSON and the tuner's interpret-mode probes run the
# REAL kernels, so every tier must hold the roundtrip/invalidation/
# corrupt-fallback contracts and the bitwise tuned-vs-default dispatch
# parity.  test_tracing.py + test_requests.py ride for the request
# tracing/SLO subsystem (ISSUE 20): span emission, the SLO fold, and
# the offline analyzer are pure host machinery over the event stream,
# so every tier must produce identical span trees, goodput verdicts,
# and bitwise-unchanged traced tokens.
FAST="python -m pytest tests/test_install_matrix.py tests/test_multi_tensor.py tests/test_telemetry.py tests/test_roofline.py tests/test_watchdog.py tests/test_contrib.py tests/test_fused_bn_act.py tests/test_cache.py tests/test_checkpoint.py tests/test_faultinject.py tests/test_fleet.py tests/test_export.py tests/test_memory.py tests/test_serving.py tests/test_tracing.py tests/test_requests.py tests/test_mesh.py tests/test_quant.py tests/test_tune.py tests/test_conv.py -q -m 'not slow'"

echo "=== tier 1: full (native + pallas) ==="
python setup.py build_native
$FAST

echo "=== tier 2: no-native (python flatten/decode) ==="
# APEX_TPU_DISABLE_NATIVE short-circuits the lazy builder (which would
# otherwise just rebuild the .so with the g++ tier 1 proved present)
APEX_TPU_DISABLE_NATIVE=1 $FAST

echo "=== tier 3: no-pallas (jnp kernels) ==="
APEX_TPU_DISABLE_PALLAS=1 $FAST

echo "=== tier 4: bare (both fallbacks) ==="
APEX_TPU_DISABLE_NATIVE=1 APEX_TPU_DISABLE_PALLAS=1 $FAST

echo "=== multi-host lane: 2 REAL processes (ISSUE 12) ==="
# Spawns 2 subprocesses with distinct process ids joined through
# jax.distributed (gloo CPU collectives): mesh parity must hold
# bitwise ACROSS hosts, CheckpointManager must land one shard per
# host, and prof.fleet must merge the two real telemetry streams.
# The script manages its own per-child XLA_FLAGS.
python tools/multihost_smoke.py --nproc 2

echo "=== cross-run regression gate (prof.regress, ISSUE 7) ==="
# Diff the freshest bench headline against the checked-in r05 baseline:
# throughput/MFU regressions FAIL the matrix here instead of hiding
# inside BENCH_EXTRA.  bench.py writes BENCH_SUMMARY.json on every full
# run; a box that never ran the bench (CPU-only CI) skips the gate
# loudly.  --tol-default 25: the tunneled chip swings ~±18% pass to
# pass even under min-of-reps — this gate exists for the 2x class, the
# bench's own self-validation holds the tight floors.  vs_prev ratios
# compare different round pairs and are excluded outright.
if [ -f BENCH_SUMMARY.json ]; then
  # Freshness: a summary older than any source file gates the WRONG
  # commit — the silent-regression case this step exists to catch.
  STALE=$( (find apex_tpu bench.py -name '*.py' -newer BENCH_SUMMARY.json
            || true) | head -1)
  if [ -n "$STALE" ]; then
    echo "BENCH_SUMMARY.json predates source change ($STALE) -- stale;"
    echo "re-run 'python bench.py' on the chip to refresh; skipping"
  else
    # serving-trace keys (ISSUE 20): absolute TTFT/TPOT/overhead on a
    # shared CI box swing wider than chip throughput — the bench's own
    # self-checks hold the hard floors (bitwise tokens, 1.5x overhead,
    # 2% analyzer agreement); here only a collapse should fail.
    python -m apex_tpu.prof.regress BENCH_r05.json BENCH_SUMMARY.json \
      --tol-default 25 --tol vs_prev=10000 --tol window_gap_pct=10000 \
      --tol loader_stall_pct=10000 --tol serving_ttft=200 \
      --tol serving_trace_overhead_ratio=50 --tol serving_goodput_pct=100
  fi
else
  echo "no fresh BENCH_SUMMARY.json (bench has not run on this box) -- skipping"
fi

echo "=== import smoke from outside the tree ==="
(cd /tmp && PYTHONPATH="$OLDPWD" python -c "
import apex_tpu
from apex_tpu import amp, optimizers, parallel, normalization
print('import surface ok:', apex_tpu.__name__)")

echo "ALL TIERS GREEN"
