"""Fused label-smoothing softmax-cross-entropy.

TPU-native re-design of reference ``apex/contrib/xentropy/softmax_xentropy.py``
+ ``apex/contrib/csrc/xentropy/xentropy_kernel.cu``:

* forward returns per-example ``losses`` and saves only ``max_log_sum_exp``
  (one fp32 scalar per row) instead of materialized log-probs — the memory
  trick of the CUDA kernel (interface returns ``(losses, max_log_sum_exp)``).
* backward is fused: ``d logits = g * (softmax - (1-s)·onehot - s/H)``,
  recomputed from logits + mlse.
* positions where ``labels == padding_idx`` contribute zero loss and zero
  gradient (reference ``softmax_xentropy.py:9,23``).

Loss definition (reference test oracle ``test_label_smoothing.py:10-28``)::

    loss = (1-smoothing) * nll + smoothing * smooth_loss
    nll = logsumexp(x) - x[label];  smooth_loss = logsumexp(x) - mean(x)

On TPU a Pallas kernel processes a block of rows per grid step (row max /
sum-exp on the VPU, label extraction via iota-select); off TPU the same math
runs as jnp, doubling as the oracle.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax

from ...pallas_compat import sds_with_vma as _sds
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...normalization.fused_layer_norm import _use_pallas
from ...tune.dispatch import kernel_config as _tuned_config
from ...tune.space import pow2_bucket as _pow2

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]

#: config-cache version of this kernel's blocking scheme (ISSUE 14).
TUNE_VERSION = 1


# -- reference math (jnp fallback + oracle) -----------------------------------

def _fwd_ref(logits, labels, smoothing):
    xf = logits.astype(jnp.float32)
    h = xf.shape[-1]
    m = jnp.max(xf, axis=-1)
    mlse = m + jnp.log(jnp.sum(jnp.exp(xf - m[:, None]), axis=-1))
    label_logit = jnp.take_along_axis(xf, labels[:, None], axis=-1)[:, 0]
    mean_logit = jnp.mean(xf, axis=-1)
    losses = mlse - (1.0 - smoothing) * label_logit - smoothing * mean_logit
    return losses, mlse


def _bwd_ref(g, logits, mlse, labels, smoothing):
    xf = logits.astype(jnp.float32)
    h = xf.shape[-1]
    soft = jnp.exp(xf - mlse[:, None])
    onehot = jax.nn.one_hot(labels, h, dtype=jnp.float32)
    dx = g[:, None] * (soft - (1.0 - smoothing) * onehot - smoothing / h)
    return dx.astype(logits.dtype)


# -- pallas kernels -----------------------------------------------------------

_ROW_BLOCK = 128
_VMEM_BUFFER_BUDGET = 2 * 1024 * 1024   # bytes per fp32 [R, H] working buffer


def _row_block(n, h, row_block=None):
    """Rows per grid step, sized so the fp32 [R, H] working buffers stay
    inside the TPU's ~16MB scoped-VMEM limit even for LM-head-sized
    vocabularies (e.g. H=30522).  The backward kernel holds up to ~6 live
    [R, H] intermediates (logits, softmax, onehot/iota, grad-out), hence the
    conservative per-buffer budget.  ``row_block`` overrides the 128-row
    cap (the autotuner's knob, ISSUE 14); the budget clamp below it
    keeps any tuned value VMEM-legal."""
    rows = min(row_block or _ROW_BLOCK, _VMEM_BUFFER_BUDGET // (4 * h))
    rows = max(8, (rows // 8) * 8)      # sublane multiple
    return min(rows, max(8, n))


def tune_bucket(n, h):
    """Config-cache shape bucket: vocab width exact (it sets the budget
    math), rows rounded to a power of two."""
    return f"r{_pow2(n)}_h{h}"


def _tuned_rows(n, h):
    """Dispatch-time consult (ISSUE 14): the tuned ``row_block`` for
    this shape bucket, or None (the hard-coded default)."""
    cfg = _tuned_config("xentropy", TUNE_VERSION, tune_bucket(n, h),
                        params=("row_block",))
    return cfg["row_block"] if cfg else None


def _pallas_fits(h):
    """Even the minimum 8-row block must fit the scoped-VMEM budget."""
    return 8 * h * 4 <= 2 * _VMEM_BUFFER_BUDGET


# Per-row vectors (labels, losses, mlse, incoming grads) travel as [R, 1]
# 2-D arrays: Mosaic requires lane-tiled ≥2-D layouts; 1-D s32 operands hit
# an XLA/Mosaic layout mismatch on real TPUs.

def _fwd_kernel(x_ref, lab_ref, loss_ref, mlse_ref, *, smoothing):
    xf = x_ref[:].astype(jnp.float32)                   # [R, H]
    h = xf.shape[1]
    m = jnp.max(xf, axis=1, keepdims=True)
    mlse = m + jnp.log(jnp.sum(jnp.exp(xf - m), axis=1, keepdims=True))
    lab = lab_ref[:]                                    # [R, 1]
    col = jax.lax.broadcasted_iota(jnp.int32, xf.shape, 1)
    picked = jnp.sum(jnp.where(col == lab, xf, 0.0), axis=1, keepdims=True)
    mean_logit = jnp.sum(xf, axis=1, keepdims=True) / h
    loss_ref[:] = (mlse - (1.0 - smoothing) * picked
                   - smoothing * mean_logit)
    mlse_ref[:] = mlse


def _bwd_kernel(g_ref, x_ref, mlse_ref, lab_ref, dx_ref, *, smoothing):
    xf = x_ref[:].astype(jnp.float32)
    h = xf.shape[1]
    mlse = mlse_ref[:]                                  # [R, 1]
    g = g_ref[:]                                        # [R, 1]
    lab = lab_ref[:]                                    # [R, 1]
    soft = jnp.exp(xf - mlse)
    col = jax.lax.broadcasted_iota(jnp.int32, xf.shape, 1)
    onehot = (col == lab).astype(jnp.float32)
    dx = g * (soft - (1.0 - smoothing) * onehot - smoothing / h)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _fwd_pallas(logits, labels, smoothing, interpret=False,
                row_block=None):
    n, h = logits.shape
    blk = _row_block(n, h, row_block)
    grid = (n + blk - 1) // blk
    loss, mlse = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=smoothing),
        grid=(grid,),
        in_specs=[pl.BlockSpec((blk, h), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                   pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_shape=[_sds((n, 1), jnp.float32, logits),
                   _sds((n, 1), jnp.float32, logits)],
        interpret=interpret,   # CPU tier-parity tests run the REAL kernel
    )(logits, labels[:, None])
    return loss[:, 0], mlse[:, 0]


def _bwd_pallas(g, logits, mlse, labels, smoothing, interpret=False,
                row_block=None):
    n, h = logits.shape
    blk = _row_block(n, h, row_block)
    grid = (n + blk - 1) // blk
    return pl.pallas_call(
        functools.partial(_bwd_kernel, smoothing=smoothing),
        grid=(grid,),
        in_specs=[pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, h), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((blk, h), lambda i: (i, 0)),
        out_shape=_sds((n, h), logits.dtype, logits, g),
        interpret=interpret,
    )(g[:, None], logits, mlse[:, None], labels[:, None])


# -- public op with custom VJP ------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, padding_idx=0,
                               half_to_float=False):
    """Per-example label-smoothing cross entropy, padding masked to zero.

    ``half_to_float`` kept for reference signature parity (bf16 losses are
    always computed and returned in fp32 here, like the CUDA kernel's
    fp32 accumulation).
    """
    losses, _ = _fwd_impl(logits, labels, smoothing)
    return jnp.where(labels == padding_idx, 0.0, losses)


def _fwd_impl(logits, labels, smoothing):
    labels = labels.astype(jnp.int32)
    if _use_pallas() and _pallas_fits(logits.shape[-1]):
        n, h = logits.shape
        return _fwd_pallas(logits, labels, smoothing,
                           row_block=_tuned_rows(n, h))
    return _fwd_ref(logits, labels, smoothing)


def _fwd_vjp(logits, labels, smoothing, padding_idx, half_to_float):
    labels = labels.astype(jnp.int32)
    losses, mlse = _fwd_impl(logits, labels, smoothing)
    losses = jnp.where(labels == padding_idx, 0.0, losses)
    return losses, (logits, mlse, labels)


def _bwd_vjp(smoothing, padding_idx, half_to_float, res, g):
    logits, mlse, labels = res
    g = jnp.where(labels == padding_idx, 0.0,
                  g.astype(jnp.float32))
    if _use_pallas() and _pallas_fits(logits.shape[-1]):
        n, h = logits.shape
        dx = _bwd_pallas(g, logits, mlse, labels, smoothing,
                         row_block=_tuned_rows(n, h))
    else:
        dx = _bwd_ref(g, logits, mlse, labels, smoothing)
    return dx, None


softmax_cross_entropy_loss.defvjp(_fwd_vjp, _bwd_vjp)


class SoftmaxCrossEntropyLoss:
    """Reference-compatible callable (``softmax_xentropy.py:4-28`` exposes
    ``SoftmaxCrossEntropyLoss.apply(...)``)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)

    def __call__(self, logits, labels, smoothing=0.0, padding_idx=0,
                 half_to_float=False):
        return self.apply(logits, labels, smoothing, padding_idx,
                          half_to_float)
