"""apex_tpu.contrib — opt-in extensions (reference ``apex/contrib/``).

* ``xentropy`` — fused label-smoothing softmax-cross-entropy
  (reference ``apex/contrib/xentropy`` + ``csrc/xentropy``).
* ``groupbn`` — NHWC BatchNorm with cross-replica bn_group sync
  (reference ``apex/contrib/groupbn`` — CUDA-IPC peer exchange there,
  sub-mesh XLA collectives here).
"""

from . import xentropy   # noqa: F401
from . import groupbn    # noqa: F401
