"""GroupBN — NHWC BatchNorm with cross-replica bn_group sync.

Re-design of reference ``apex/contrib/groupbn`` (``batch_norm.py:101+``,
``csrc/groupbn/*``).  The reference builds this from ~5,600 lines of CUDA:
persistent NHWC kernels + raw CUDA-IPC peer buffers so ``bn_group`` ranks can
exchange statistics without NCCL.  On TPU:

* NHWC is the native layout — "channels-last" is the default everywhere.
* bn_group peer exchange = sub-mesh collectives (``axis_index_groups`` on the
  stats psum) — no IPC analog needed, ICI handles it.
* the semi-fused bn/bn-add-relu epilogues = ``fuse_relu``/``z`` on our
  SyncBatchNorm, which XLA fuses into neighbors.

So the whole contrib module reduces to a thin wrapper with the reference's
constructor surface over :class:`apex_tpu.parallel.SyncBatchNorm`.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ...parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["BatchNorm2d_NHWC"]


class BatchNorm2d_NHWC(nn.Module):
    """Reference ctor: ``BatchNorm2d_NHWC(planes, fuse_relu=False,
    bn_group=1)`` (``contrib/groupbn/batch_norm.py:101+``).  ``bn_group``
    is the number of replicas that share statistics; groups are contiguous
    rank blocks like ``create_syncbn_process_group``
    (``apex/parallel/__init__.py:55-96``).  ``num_features`` may be left
    None to infer from the input's channel dim — the norm-factory
    contract :class:`apex_tpu.models.resnet.ResNet` calls with
    (``norm_cls=``)."""
    num_features: Optional[int] = None
    fuse_relu: bool = False
    bn_group: int = 1
    eps: float = 1e-5
    momentum: float = 0.1
    axis_name: Optional[str] = None
    world_size: Optional[int] = None
    use_running_average: Optional[bool] = None
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, z=None, use_running_average=None):
        process_group = None
        axis_name = self.axis_name
        if self.bn_group > 1:
            if self.world_size is None:
                raise ValueError("bn_group > 1 requires world_size")
            if axis_name is None:
                raise ValueError(
                    "bn_group > 1 requires axis_name (the mesh axis the "
                    "replicas live on); without it statistics would stay "
                    "per-replica")
            n = self.world_size
            g = self.bn_group
            if n % g != 0:
                raise ValueError(
                    f"world_size {n} not divisible by bn_group {g}")
            process_group = [list(range(i, i + g)) for i in range(0, n, g)]
        elif self.bn_group == 1:
            # group size 1 == no cross-replica sync
            axis_name = None
        bn = SyncBatchNorm(
            num_features=self.num_features, eps=self.eps,
            momentum=self.momentum, axis_name=axis_name,
            process_group=process_group, channel_last=True,
            fuse_relu=self.fuse_relu,
            use_running_average=self.use_running_average,
            scale_init=self.scale_init, bias_init=self.bias_init,
            name="bn")
        return bn(x, z=z, use_running_average=use_running_average)
