"""Metrics registry — counters, gauges, and reservoir histograms.

The host-side half of the run-telemetry engine (ISSUE 5): named
instruments a run can bump cheaply from any thread, snapshotted once
into the stream's final ``summary`` event (and on demand via
:meth:`MetricsRegistry.snapshot`).

Device-side values never enter this registry directly — they piggyback
on the :class:`~apex_tpu.runtime.DeferredMetrics` one-dispatch-behind
read (:meth:`apex_tpu.telemetry.Recorder.observe_window_metrics`), so
enabling telemetry adds **zero** extra host syncs per window: the only
device->host transfers are the ones the training loop already pays for
its own metric prints.

Histograms keep a bounded uniform reservoir (default 512 samples, the
classic Vitter Algorithm R with a deterministic per-instrument RNG), so
percentiles over million-step runs cost O(reservoir) memory and the
same stream analyzed twice reports the same numbers.

A registry built with ``enabled=False`` hands out shared no-op
instruments: every ``inc``/``set``/``observe`` is a single attribute
lookup plus a no-op call, so instrumented library code never needs an
``if telemetry:`` guard of its own.
"""

from __future__ import annotations

import bisect
import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "Rolling",
           "nearest_rank_percentiles", "LATENCY_BUCKETS_S",
           "default_buckets"]

#: Fixed cumulative-histogram bounds (seconds) every ``*_s`` latency
#: histogram gets by default (ISSUE 20 satellite): log-ish spacing from
#: 1 ms to 60 s.  FIXED per histogram for the whole run — Prometheus
#: ``_bucket{le=...}`` series are only rate()-able when the bounds
#: never move under the scraper.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def default_buckets(name: str) -> Optional[Tuple[float, ...]]:
    """The fixed bucket bounds a histogram named ``name`` gets when the
    caller supplies none: seconds-valued instruments (``*_s``) take
    :data:`LATENCY_BUCKETS_S`; everything else keeps reservoir-only
    percentiles (no ``_bucket`` exposition)."""
    return LATENCY_BUCKETS_S if name.endswith("_s") else None


def nearest_rank_percentiles(samples: Sequence[float],
                             qs: Sequence[float] = (50.0, 90.0, 99.0)
                             ) -> List[Optional[float]]:
    """Nearest-rank percentiles of a sample list ([] -> all None) — the
    ONE percentile definition shared by :class:`Histogram` reservoirs
    and the offline timeline analyzer, so in-run summaries and offline
    reports can never diverge on interpolation."""
    data = sorted(samples)
    if not data:
        return [None for _ in qs]
    out = []
    for q in qs:
        idx = min(len(data) - 1,
                  max(0, int(round(q / 100.0 * (len(data) - 1)))))
        out.append(data[idx])
    return out


class Counter:
    """Monotonic counter (events seen, batches delivered, skips fired)."""

    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v

    def snapshot(self):
        return self._v


class Gauge:
    """Last-value-wins instrument (current loss scale, queue depth).

    :meth:`set_max` is the high-water-mark variant the HBM gauges use
    (ISSUE 10): a live ``bytes_in_use`` poll naturally dips, but a
    *peak* gauge must never regress — ``peak_hbm_bytes`` keeps the
    highest harvest the run ever recorded, across pipelines and
    re-harvests alike."""

    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, v) -> None:
        # plain set is last-value-wins by contract: a single float
        # assignment, no lock on the hot path
        self._v = float(v)

    def set_max(self, v) -> None:
        """Monotonic set: keep ``max(current, v)``.  Locked — the
        compare-and-set races otherwise (exporter render threads and
        the loop thread both publish peaks) and a stale writer could
        regress the high-water mark it promises never regresses."""
        v = float(v)
        with self._lock:
            if self._v is None or v > self._v:
                self._v = v

    @property
    def value(self) -> Optional[float]:
        return self._v

    def snapshot(self):
        return self._v


class Histogram:
    """Streaming distribution with reservoir percentiles.

    Exact ``count``/``sum``/``min``/``max``; percentiles come from a
    bounded uniform reservoir (Algorithm R), so p50/p90/p99 over an
    unbounded stream cost O(reservoir) memory.  The replacement RNG is
    seeded per instrument — re-analyzing the same run reproduces the
    same percentiles bit for bit.

    ``buckets`` (optional, sorted upper bounds) additionally keeps
    EXACT per-bucket counts, so the Prometheus exporter can render a
    true cumulative ``_bucket{le=...}`` family external alerting can
    ``rate()`` — something the reservoir cannot reconstruct (ISSUE 20
    satellite).  The bounds are fixed for the instrument's lifetime.
    """

    __slots__ = ("_lock", "_res", "_cap", "_rng", "count", "sum",
                 "min", "max", "_bounds", "_bucket_counts")

    def __init__(self, reservoir: int = 512, seed: int = 0,
                 buckets: Optional[Sequence[float]] = None):
        self._lock = threading.Lock()
        self._res: List[float] = []
        self._cap = max(1, int(reservoir))
        self._rng = random.Random(seed)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._bounds: Optional[Tuple[float, ...]] = (
            tuple(sorted(float(b) for b in buckets)) if buckets else None)
        # one slot per bound plus the +Inf overflow slot
        self._bucket_counts: Optional[List[int]] = (
            [0] * (len(self._bounds) + 1) if self._bounds else None)

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if self._bucket_counts is not None:
                self._bucket_counts[
                    bisect.bisect_left(self._bounds, v)] += 1
            if len(self._res) < self._cap:
                self._res.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._res[j] = v

    def bucket_counts(self):
        """``(bounds, cumulative_counts)`` — counts[i] is the number of
        observations ``<= bounds[i]`` (the Prometheus ``le`` contract;
        the implicit ``+Inf`` bucket is :attr:`count`).  ``None`` when
        the instrument was built without bounds."""
        if self._bounds is None:
            return None
        with self._lock:
            raw = list(self._bucket_counts)
        cum, running = [], 0
        for c in raw[:-1]:
            running += c
            cum.append(running)
        return self._bounds, cum

    def percentiles(self, qs: Sequence[float] = (50.0, 90.0, 99.0)):
        """Reservoir percentiles (nearest-rank); [] -> all None."""
        with self._lock:
            data = list(self._res)
        return nearest_rank_percentiles(data, qs)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def snapshot(self):
        p50, p90, p99 = self.percentiles((50.0, 90.0, 99.0))
        out = {"count": self.count,
               "sum": round(self.sum, 6),
               "min": self.min, "max": self.max,
               "mean": (round(self.mean, 6)
                        if self.count else None),
               "p50": p50, "p90": p90, "p99": p99}
        bc = self.bucket_counts()
        if bc is not None:
            out["buckets"] = {"le": list(bc[0]), "counts": bc[1]}
        return out


class Rolling:
    """Fixed-window rolling statistics — the last ``window``
    observations only.

    The :mod:`~apex_tpu.telemetry.watchdog` anomaly rules compare each
    fresh sample against a ROLLING baseline (median of the recent past),
    which a :class:`Histogram` reservoir cannot provide: a reservoir
    remembers the whole run, so a step-time regression an hour in would
    be judged against hour-old samples and never look anomalous.  Median
    (not mean) so the compile-sized outliers that seed the window do not
    drag the baseline."""

    __slots__ = ("_buf", "_cap", "_idx", "count")

    def __init__(self, window: int = 32):
        self._buf: List[float] = []
        self._cap = max(1, int(window))
        self._idx = 0
        self.count = 0

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        if len(self._buf) < self._cap:
            self._buf.append(v)
        else:
            self._buf[self._idx] = v
            self._idx = (self._idx + 1) % self._cap
    # NOTE: single-consumer by design (the watchdog folds on whichever
    # thread emitted the event, under the Watchdog lock) — no lock here.

    @property
    def full(self) -> bool:
        return len(self._buf) >= self._cap

    def median(self) -> Optional[float]:
        if not self._buf:
            return None
        return nearest_rank_percentiles(self._buf, (50.0,))[0]

    @property
    def mean(self) -> Optional[float]:
        return sum(self._buf) / len(self._buf) if self._buf else None

    @property
    def total(self) -> float:
        return sum(self._buf)


class _NoopInstrument:
    """Shared disabled instrument: accepts every instrument method as a
    no-op, so disabled-registry call sites stay guard-free."""

    __slots__ = ()
    value = None
    count = 0
    mean = None

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def set_max(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass

    def percentiles(self, qs=(50.0, 90.0, 99.0)):
        return [None for _ in qs]

    def snapshot(self):
        return None


_NOOP = _NoopInstrument()


class MetricsRegistry:
    """Named instrument factory + snapshot.

    ``counter(name)`` / ``gauge(name)`` / ``histogram(name)`` create on
    first use and return the same instrument afterwards (thread-safe).
    ``enabled=False`` makes every accessor return the shared no-op
    instrument — the strict-no-op contract of the disabled telemetry
    path.
    """

    def __init__(self, enabled: bool = True, reservoir: int = 512):
        self.enabled = bool(enabled)
        self._reservoir = reservoir
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    def _get(self, table, name: str, factory):
        if not self.enabled:
            return _NOOP
        with self._lock:
            inst = table.get(name)
            if inst is None:
                inst = table[name] = factory()
            return inst

    def counter(self, name: str):
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str):
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None):
        # Deterministic per-name seed (crc32, not hash(): str hashing is
        # salted per process): same run, same reservoir.  Bucket bounds
        # bind on FIRST creation (fixed-per-histogram contract); omitted,
        # `*_s` names get the shared latency ladder (default_buckets).
        import zlib
        if buckets is None:
            buckets = default_buckets(name)
        return self._get(
            self._hists, name,
            lambda: Histogram(self._reservoir,
                              seed=zlib.crc32(name.encode()),
                              buckets=buckets))

    def snapshot(self) -> dict:
        """One nested dict of every instrument's current value."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        return {
            "counters": {k: v.snapshot() for k, v in counters.items()},
            "gauges": {k: v.snapshot() for k, v in gauges.items()},
            "histograms": {k: v.snapshot() for k, v in hists.items()},
        }
