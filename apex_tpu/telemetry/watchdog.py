"""Run-health watchdog — an online rule engine folding the telemetry
event stream into debounced ``alert`` events (ISSUE 6).

A long training run fails in stereotyped ways the raw stream records
but nobody reads until the run is dead: the loss goes NaN, the loss
scale collapses under repeated overflow skips, the input engine starts
stalling the loop, a step quietly triples, a shape bug retraces every
window.  The watchdog watches for exactly those, ONLINE, with zero
marginal cost to the training loop:

* it folds events **on the thread that emitted them** (the
  :class:`~apex_tpu.telemetry.events.Recorder` calls
  :meth:`Watchdog.observe` after writing each line) — every input is a
  host-side dict that already exists, so no extra device syncs, no
  polling thread, and with no recorder installed the instrumented paths
  are the SAME disabled no-op as plain telemetry (``bench.py`` gates
  the bitwise identity and the 1.5x overhead ceiling with the watchdog
  attached);
* every firing is a structured ``alert`` event in the SAME stream —
  ``tail -f`` shows it live, the ``finally``-closed recorder flushes a
  dying run's last alerts, and ``python -m apex_tpu.prof.timeline``
  reports them under ``alerts``;
* alerts are **debounced** per rule (default: one per rule per
  ``debounce_steps`` global steps) so a wedged run emits a heartbeat of
  evidence, not a megabyte of repetition.

Rules (each a small stateful fold; thresholds are constructor kwargs):

========================  =====================================================
``nonfinite``             a fetched ``metrics`` window contains a NaN/inf loss
``scale_collapse``        loss scale hit the floor, or >= ``max_skips``
                          CONSECUTIVE overflow skips (the death spiral, vs the
                          benign isolated skip dynamic scaling expects)
``loader_stall``          the input engine is throttling the loop: the final
                          ``loader`` snapshot's stall pct, or a rolling window
                          of ``loader_wait`` events, exceeds ``stall_pct``
``step_time``             a window's per-step wall time exceeds
                          ``anomaly_factor`` x the rolling-median baseline
                          (compile windows seed the window and are absorbed by
                          the median; alerting waits until the baseline fills)
``retrace_storm``         >= ``storm_count`` TRUE retraces (never-seen shape
                          signatures — the J004 class) within
                          ``storm_steps`` steps
``checkpoint_stall``      a ``checkpoint`` snapshot span exceeded
                          ``ckpt_stall_s`` (the async engine's stall
                          contract broke) or the writer reported a backlog
``checkpoint_failed``     a checkpoint write errored — the newest recovery
                          point is stale (critical)
``memory_headroom``       a ``memory`` event (harvested peak-HBM ledger or a
                          live device-memory read) reports free HBM below
                          ``min_headroom_pct`` of the device limit — the
                          pre-OOM warning, fired while the run still lives
``serving_queue_stall``   a ``serving`` admit event's queue wait exceeded
                          ``serving_stall_s`` — requests are aging in the
                          queue faster than decode slots/KV pages free up
                          (ISSUE 11: the inference twin of loader_stall)
``quant_scale_saturation``  ``quant`` saturation events report more than
                          ``quant_max_exceeded`` range overflows within one
                          observation window — the calibrated absmax has
                          gone stale (activations drifted past the frozen
                          int8 range) and the quantizer is clipping;
                          re-observe and re-freeze (ISSUE 13)
``slo_burn``              an ``slo`` evaluation reports BOTH burn windows
                          above ``slo_burn_rate`` — the serving SLO's error
                          budget is being spent faster than the target
                          allows, sustained (the classic multi-window
                          burn-rate alert; ISSUE 20)
``slo_exhausted``         the run-level error budget is GONE: the bad
                          fraction over everything served exceeds the
                          budget — the SLO cannot be met without a quiet
                          stretch; shed load or scale out (critical)
========================  =====================================================

Usage — the examples' ``--watchdog`` flag does exactly this::

    rec = telemetry.start("run.jsonl", watchdog=True)
    ...                                  # train; alerts land in the stream
    rec.close()
    print("health:", rec.watchdog.format_line())
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional

from .metrics import Rolling

__all__ = ["Watchdog", "attach", "RULE_NAMES"]

RULE_NAMES = ("nonfinite", "scale_collapse", "loader_stall", "step_time",
              "retrace_storm", "checkpoint_stall", "checkpoint_failed",
              "memory_headroom", "serving_queue_stall",
              "quant_scale_saturation", "slo_burn", "slo_exhausted")


class _Rule:
    """One stateful fold over the event stream.

    ``observe(event)`` returns None or an alert-field dict
    ``{"step", "message", "value"}``; severity and debouncing are the
    :class:`Watchdog`'s job."""

    name = "rule"
    severity = "warning"

    def observe(self, event: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


class _NonFinite(_Rule):
    name = "nonfinite"
    severity = "critical"

    def observe(self, event):
        if event.get("kind") != "metrics":
            return None
        loss = event.get("loss")
        if not loss:
            return None
        step0 = int(event.get("step", 0))
        for j, v in enumerate(loss):
            if not math.isfinite(v):
                return {"step": step0 + j, "value": repr(v),
                        "message": f"non-finite loss at step {step0 + j}"}
        return None


class _ScaleCollapse(_Rule):
    name = "scale_collapse"
    severity = "critical"

    def __init__(self, scale_floor: float = 1.0, max_skips: int = 4):
        self.scale_floor = scale_floor
        self.max_skips = max_skips
        self._streak = 0
        self._last_skip_step: Optional[int] = None

    def observe(self, event):
        if event.get("kind") != "scale":
            return None
        step = int(event.get("step", 0))
        if event.get("event") == "grow":
            self._streak = 0
            return None
        if event.get("event") != "skip":
            return None
        if self._last_skip_step is not None \
                and step == self._last_skip_step + 1:
            self._streak += 1
        else:
            self._streak = 1
        self._last_skip_step = step
        scale = float(event.get("scale", float("inf")))
        if scale <= self.scale_floor:
            return {"step": step, "value": scale,
                    "message": f"loss scale at floor ({scale:g} <= "
                               f"{self.scale_floor:g}) and still skipping"}
        if self._streak >= self.max_skips:
            return {"step": step, "value": self._streak,
                    "message": f"{self._streak} consecutive overflow "
                               f"skips (scale {scale:g}) — loss-scale "
                               f"collapse, not an isolated overflow"}
        return None


class _LoaderStall(_Rule):
    name = "loader_stall"

    def __init__(self, stall_pct: float = 30.0, window: int = 32):
        self.stall_pct = stall_pct
        self.window = max(2, int(window))
        # tumbling measurement window: evaluate once per `window`
        # loader_wait events, then reset BOTH the wait sum and the wall
        # anchor together — resetting only the anchor would divide a
        # full window of waits by one inter-event gap and over-report
        # the stall fraction ~window-fold (review finding).
        self._wait_s = 0.0
        self._n = 0
        self._t_first: Optional[float] = None

    def observe(self, event):
        kind = event.get("kind")
        if kind == "loader":
            pct = float((event.get("stats") or {})
                        .get("loader_stall_pct", 0.0))
            if pct > self.stall_pct:
                return {"step": None, "value": pct,
                        "message": f"loader stall {pct:.1f}% of wall "
                                   f"(> {self.stall_pct:.0f}%) — the input "
                                   f"engine is throttling the loop"}
            return None
        if kind != "loader_wait":
            return None
        t = float(event.get("t", 0.0))
        if self._t_first is None:
            self._t_first = t
        self._wait_s += float(event.get("dur", 0.0))
        self._n += 1
        if self._n < self.window:
            return None
        wall = t - self._t_first
        wait_s = self._wait_s
        self._t_first = t
        self._wait_s = 0.0
        self._n = 0
        if wall <= 0:
            return None
        pct = 100.0 * wait_s / wall
        if pct > self.stall_pct:
            return {"step": None, "value": round(pct, 1),
                    "message": f"train loop spent {pct:.1f}% of the last "
                               f"{wall:.1f}s waiting on the loader "
                               f"(> {self.stall_pct:.0f}%)"}
        return None


class _StepTime(_Rule):
    name = "step_time"

    def __init__(self, anomaly_factor: float = 3.0, window: int = 32,
                 min_samples: int = 8):
        self.anomaly_factor = anomaly_factor
        self.min_samples = min_samples
        self._baseline = Rolling(window)

    def observe(self, event):
        if event.get("kind") != "window":
            return None
        n = max(1, int(event.get("n_valid", 1)))
        per_step = (float(event.get("dur", 0.0))
                    + float(event.get("gap", 0.0))) / n
        baseline = self._baseline.median()
        ready = self._baseline.count >= self.min_samples
        # compare BEFORE folding the sample in, so the anomaly cannot
        # pull its own baseline up
        self._baseline.observe(per_step)
        if not ready or baseline is None or baseline <= 0:
            return None
        if per_step > self.anomaly_factor * baseline:
            return {"step": int(event.get("step", 0)),
                    "value": round(per_step * 1e3, 3),
                    "message": f"step time {per_step * 1e3:.1f} ms is "
                               f"{per_step / baseline:.1f}x the rolling "
                               f"median ({baseline * 1e3:.1f} ms) — host "
                               f"stall, sync, or preemption"}
        return None


class _RetraceStorm(_Rule):
    name = "retrace_storm"
    severity = "critical"

    def __init__(self, storm_count: int = 3, storm_steps: int = 128):
        self.storm_count = storm_count
        self.storm_steps = storm_steps
        self._steps: List[int] = []

    def observe(self, event):
        if event.get("kind") != "retrace":
            return None
        # only TRUE retraces count: not the first compile, not the
        # benign same-signature call-1 re-specialization
        if event.get("first") or not event.get("new_sig", True):
            return None
        step = int(event.get("step", 0))
        self._steps.append(step)
        self._steps = [s for s in self._steps
                       if step - s <= self.storm_steps]
        if len(self._steps) >= self.storm_count:
            return {"step": step, "value": len(self._steps),
                    "message": f"{len(self._steps)} true retraces within "
                               f"{self.storm_steps} steps — varying "
                               f"shapes/dtypes are recompiling the hot "
                               f"program (jaxlint J004 class)"}
        return None


class _CheckpointStall(_Rule):
    """The async checkpoint engine's stall contract (ISSUE 9): the
    train loop should pay only the snapshot's D2H copy.  Fires when a
    ``checkpoint`` ``snapshot`` span exceeds ``ckpt_stall_s`` (the
    serialize/fsync work leaked back onto the loop thread, or the copy
    itself is drowning), or on a ``backlog`` event (the writer thread
    cannot keep up with the save cadence and the trigger is now
    blocking to bound host memory)."""

    name = "checkpoint_stall"

    def __init__(self, ckpt_stall_s: float = 2.0):
        self.ckpt_stall_s = ckpt_stall_s

    def observe(self, event):
        if event.get("kind") != "checkpoint":
            return None
        phase = event.get("phase")
        if phase == "backlog":
            return {"step": event.get("step"),
                    "value": event.get("value"),
                    "message": f"checkpoint writer backlog "
                               f"({event.get('value')} pending) — the "
                               f"save cadence outruns the writer thread "
                               f"and the snapshot trigger is blocking"}
        if phase != "snapshot":
            return None
        dur = float(event.get("dur", 0.0))
        if dur > self.ckpt_stall_s:
            return {"step": event.get("step"), "value": round(dur, 3),
                    "message": f"checkpoint snapshot stalled the loop "
                               f"{dur:.2f}s (> {self.ckpt_stall_s:.1f}s) "
                               f"— the D2H copy trigger is no longer "
                               f"cheap (serialize leaked onto the loop "
                               f"thread, or the state outgrew the link)"}
        return None


class _CheckpointFailed(_Rule):
    """A checkpoint write failed (ISSUE 9) — the run is still training
    but its recovery point is stale; every further step widens the loss
    a preemption would cause.  Critical, debounced like the rest."""

    name = "checkpoint_failed"
    severity = "critical"

    def observe(self, event):
        if event.get("kind") != "checkpoint" \
                or event.get("phase") != "error":
            return None
        return {"step": event.get("step"),
                "value": event.get("error"),
                "message": f"checkpoint write FAILED "
                           f"({event.get('error')}) — the newest "
                           f"recovery point is stale; fix storage or "
                           f"drain now"}


class _MemoryHeadroom(_Rule):
    """HBM is the resource that kills runs first at scale, and it kills
    them instantly — by the time an OOM raises there is no stream left
    to warn from.  This rule fires from the ``memory`` events the
    ledger emits BEFORE the water reaches the deck: a harvested
    peak-HBM estimate (:func:`apex_tpu.prof.memory.record_memory`) or a
    live device read whose free fraction drops under
    ``min_headroom_pct`` of the device limit.  Events without a limit
    (CPU backends expose no ``memory_stats``) fold to nothing — no
    false alarms from boxes that cannot OOM this way."""

    name = "memory_headroom"

    def __init__(self, min_headroom_pct: float = 10.0):
        self.min_headroom_pct = float(min_headroom_pct)

    def observe(self, event):
        if event.get("kind") != "memory":
            return None
        headroom = event.get("headroom_pct")
        if headroom is None:
            limit = float(event.get("bytes_limit", 0) or 0)
            used = float(event.get("bytes_in_use", 0)
                         or event.get("peak_bytes", 0) or 0)
            if limit <= 0:
                return None
            headroom = 100.0 * max(0.0, 1.0 - used / limit)
        headroom = float(headroom)
        if headroom < self.min_headroom_pct:
            src = event.get("source") or event.get("phase") or "memory"
            return {"step": event.get("step"),
                    "value": round(headroom, 2),
                    "message": f"HBM headroom {headroom:.1f}% "
                               f"(< {self.min_headroom_pct:.0f}%) per "
                               f"{src} — the next growth (longer batch, "
                               f"retrace, fragmentation) OOMs; shrink "
                               f"the model/batch or shard further"}
        return None


class _ServingQueueStall(_Rule):
    """Request latency under load is queue wait + prefill + decode, and
    queue wait is the term that explodes when traffic outruns capacity
    (no free decode slots or KV pages).  The serving engine stamps every
    admission with the request's measured queue wait; this rule fires
    when one exceeds ``serving_stall_s`` — the "scale out or shed load"
    signal, debounced like the rest (ISSUE 11)."""

    name = "serving_queue_stall"

    def __init__(self, serving_stall_s: float = 2.0):
        self.serving_stall_s = float(serving_stall_s)

    def observe(self, event):
        if event.get("kind") != "serving" \
                or event.get("phase") != "admit":
            return None
        wait = float(event.get("queue_wait", 0.0) or 0.0)
        if wait <= self.serving_stall_s:
            return None
        return {"step": None, "value": round(wait, 3),
                "message": f"request waited {wait:.2f}s in the serving "
                           f"queue (> {self.serving_stall_s:.1f}s) — "
                           f"traffic is outrunning decode slots/KV "
                           f"pages; add capacity or shed load"}


class _QuantScaleSaturation(_Rule):
    """The int8 engine's staleness alarm (ISSUE 13): frozen calibration
    scales are a bet that the observed activation range keeps holding.
    :meth:`apex_tpu.quant.calibrate.Calibration.note_saturation` emits a
    ``quant`` event whenever fetched runtime absmax checks find the
    range exceeded; this rule fires when one window's ``exceeded``
    count passes ``quant_max_exceeded`` — isolated single clips are the
    normal tail LLM.int8()-style percentile calibration accepts, a
    burst means the distribution moved and accuracy is silently
    degrading.  Warning severity: the run is still numerically valid
    (clipping, not NaN), the fix is a re-observation pass."""

    name = "quant_scale_saturation"

    def __init__(self, quant_max_exceeded: int = 4):
        self.quant_max_exceeded = int(quant_max_exceeded)

    def observe(self, event):
        if event.get("kind") != "quant" \
                or event.get("phase") != "saturation":
            return None
        exceeded = int(event.get("exceeded", 0) or 0)
        if exceeded <= self.quant_max_exceeded:
            return None
        name = event.get("name", "?")
        win = event.get("window")
        return {"step": event.get("step"), "value": exceeded,
                "message": f"quant site {name!r} exceeded its calibrated "
                           f"absmax {exceeded} times"
                           f"{f' in {win} steps' if win else ''} "
                           f"(> {self.quant_max_exceeded}) — the frozen "
                           f"int8 range is stale; re-observe and "
                           f"re-freeze the calibration"}


class _SLOBurn(_Rule):
    """The serving SLO's sustained burn alarm (ISSUE 20): the
    :class:`apex_tpu.telemetry.slo.SLOEngine` folds ``done`` events
    into short/long-window burn rates and emits ``slo`` evaluations;
    this rule fires when BOTH windows burn above ``slo_burn_rate`` —
    the short window makes the alert fast, the long window makes it
    evidence of a trend rather than one slow request (the standard
    multi-window burn-rate page).  Warning severity: the budget is
    being spent, not yet gone."""

    name = "slo_burn"

    def __init__(self, slo_burn_rate: float = 1.0):
        self.slo_burn_rate = float(slo_burn_rate)

    def observe(self, event):
        if event.get("kind") != "slo" or event.get("phase") != "eval":
            return None
        short = float(event.get("burn_short", 0.0) or 0.0)
        long_ = float(event.get("burn_long", 0.0) or 0.0)
        if short <= self.slo_burn_rate or long_ <= self.slo_burn_rate:
            return None
        return {"step": None, "value": round(long_, 3),
                "message": f"SLO error budget burning {short:.1f}x/"
                           f"{long_:.1f}x (short/long windows, both > "
                           f"{self.slo_burn_rate:g}x) — goodput "
                           f"{event.get('goodput_pct')}% vs target "
                           f"{event.get('target_pct')}%"}


class _SLOExhausted(_Rule):
    """The run-level SLO budget is spent (ISSUE 20): the bad fraction
    over EVERYTHING served exceeds the allowance, so no remaining
    traffic mix can bring this run back inside its target — the
    scale-out/shed-load page.  Critical, debounced like the rest."""

    name = "slo_exhausted"
    severity = "critical"

    def observe(self, event):
        if event.get("kind") != "slo" or event.get("phase") != "eval" \
                or not event.get("exhausted"):
            return None
        return {"step": None, "value": event.get("goodput_pct"),
                "message": f"SLO error budget EXHAUSTED: "
                           f"{event.get('bad')}/{event.get('n')} requests "
                           f"out of SLO (target "
                           f"{event.get('target_pct')}%) — the run can "
                           f"no longer meet its objectives; shed load "
                           f"or add capacity"}


class Watchdog:
    """Folds recorder events through the rule set and emits debounced
    ``alert`` events back into the same stream.

    Attach with :func:`attach` (or ``telemetry.start(path,
    watchdog=True)``).  ``observe`` is called by the recorder after
    every written event, on the emitting thread, under this object's
    own lock (producers span the train loop and the loader threads).
    Alerts are both written to the stream and kept in :attr:`alerts`
    for the end-of-run ``health:`` line."""

    def __init__(self, recorder=None, *, debounce_steps: int = 64,
                 rules: Optional[List[_Rule]] = None, **thresholds):
        self._recorder = recorder
        self.debounce_steps = int(debounce_steps)
        if rules is None:
            rules = [
                _NonFinite(),
                _ScaleCollapse(
                    scale_floor=thresholds.get("scale_floor", 1.0),
                    max_skips=thresholds.get("max_skips", 4)),
                _LoaderStall(
                    stall_pct=thresholds.get("stall_pct", 30.0)),
                _StepTime(
                    anomaly_factor=thresholds.get("anomaly_factor", 3.0),
                    min_samples=thresholds.get("min_samples", 8)),
                _RetraceStorm(
                    storm_count=thresholds.get("storm_count", 3),
                    storm_steps=thresholds.get("storm_steps", 128)),
                _CheckpointStall(
                    ckpt_stall_s=thresholds.get("ckpt_stall_s", 2.0)),
                _CheckpointFailed(),
                _MemoryHeadroom(
                    min_headroom_pct=thresholds.get(
                        "min_headroom_pct", 10.0)),
                _ServingQueueStall(
                    serving_stall_s=thresholds.get(
                        "serving_stall_s", 2.0)),
                _QuantScaleSaturation(
                    quant_max_exceeded=thresholds.get(
                        "quant_max_exceeded", 4)),
                _SLOBurn(
                    slo_burn_rate=thresholds.get("slo_burn_rate", 1.0)),
                _SLOExhausted(),
            ]
        self.rules = rules
        self.alerts: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._last_fired: Dict[str, float] = {}   # rule -> step (or count)
        self._events_seen = 0

    def observe(self, event: Dict[str, Any]) -> None:
        """Fold one already-written event (never an ``alert``) through
        every rule; emit debounced alerts.  Swallows nothing silently —
        a rule raising is a bug, but it must not kill the training run,
        so it degrades to an ``alert`` about the watchdog itself."""
        fired: List[Dict[str, Any]] = []
        with self._lock:
            self._events_seen += 1
            for rule in self.rules:
                try:
                    hit = rule.observe(event)
                except Exception as e:       # pragma: no cover - rule bug
                    hit = {"step": None, "value": None,
                           "message": f"watchdog rule crashed: "
                                      f"{type(e).__name__}: {e}"}
                if hit is None:
                    continue
                # Debounce on the global step when the alert has one,
                # else on the event count — one alert per rule per
                # debounce window keeps a wedged run's stream readable.
                clock = (float(hit["step"]) if hit.get("step") is not None
                         else float(self._events_seen))
                last = self._last_fired.get(rule.name)
                if last is not None and clock - last < self.debounce_steps:
                    continue
                self._last_fired[rule.name] = clock
                alert = {"rule": rule.name, "severity": rule.severity,
                         **{k: v for k, v in hit.items() if v is not None}}
                self.alerts.append(alert)
                fired.append(alert)
        # Emit OUTSIDE the fold lock; Recorder.event skips kind="alert"
        # on the observe hook, so this cannot recurse.
        rec = self._recorder
        if rec is not None:
            for alert in fired:
                rec.event("alert", **alert)

    # -- end-of-run summary ---------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """``{"ok", "alerts", "by_rule", "worst"}`` — the dict the
        recorder folds into its final ``summary`` event."""
        with self._lock:
            alerts = list(self.alerts)
        by_rule: Dict[str, int] = {}
        worst = None
        for a in alerts:
            by_rule[a["rule"]] = by_rule.get(a["rule"], 0) + 1
            if a["severity"] == "critical":
                worst = "critical"
            elif worst is None:
                worst = "warning"
        return {"ok": not alerts, "alerts": len(alerts),
                "by_rule": by_rule, "worst": worst}

    def format_line(self) -> str:
        """One-line ``health:`` summary the examples print at exit."""
        h = self.health()
        if h["ok"]:
            return "ok (0 alerts)"
        rules = ", ".join(f"{k} x{v}" for k, v in sorted(h["by_rule"].items()))
        return f"{h['worst'].upper()} — {h['alerts']} alert(s): {rules}"


def attach(recorder, **kwargs) -> Watchdog:
    """Build a :class:`Watchdog` and hook it onto ``recorder`` (every
    subsequently written event is folded online).  Returns the watchdog;
    threshold kwargs are forwarded to the default rule set."""
    wd = Watchdog(recorder, **kwargs)
    recorder.attach_watchdog(wd)
    return wd
