"""SLO engine — declarative latency objectives folded online into
goodput and burn-rate signals (ISSUE 20 tentpole, piece 3).

An SLO turns the serving histograms into a decision: *is this replica
healthy enough to keep taking traffic?*  The spec is the operator's
one-liner::

    ttft_p99<200ms,tpot_p99<30ms

read as "99% of requests must see their first token within 200 ms AND
sustain under 30 ms per output token".  Each objective names a
per-request metric (``ttft``/``tpot``/``e2e``/``queue_wait``), a
percentile qualifier that doubles as the compliance target (``p99`` →
99% of requests), and a threshold with units (``ms``/``s``/``us``).
A request is **good** when every objective's metric is under its
threshold; **goodput** is the good fraction; the **error budget** is
what the target leaves (``100 - target_pct``); the **burn rate** is
how fast the window is spending it (``bad_fraction / budget`` — 1.0
means exactly on budget, 14x means the budget is gone in 1/14th of the
window).

:class:`SLOEngine` folds ``serving`` ``done`` events **on the recorder
thread like the watchdog** (no polling thread, no device syncs),
exports ``slo_goodput_pct`` + multi-window ``slo_burn_rate_short`` /
``slo_burn_rate_long`` gauges through the existing Prometheus
exporter, and emits debounced ``slo`` events the watchdog's
``slo_burn`` (warning) and ``slo_exhausted`` (critical) rules alert
on.  The classic multi-window discipline: alert only when BOTH the
short window (fast trigger) and the long window (sustained evidence)
burn hot — a single slow request cannot page anyone.

All clocks are the stream clock (event ``t``) — a synthetic stream
replayed through the fold reproduces the same verdicts bit for bit.

Usage::

    rec = telemetry.start("run.jsonl", watchdog=True,
                          slo="ttft_p99<200ms,tpot_p99<30ms")
    ...                         # serve; slo/alert events land in-stream
    print(rec.slo.format_line())

Offline, the same spec string drives ``python -m apex_tpu.prof.requests
--slo`` (goodput over a recorded stream, via :func:`evaluate`).
"""

from __future__ import annotations

import re
import threading
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

__all__ = ["Objective", "SLOSpec", "parse_slo", "evaluate", "SLOEngine",
           "attach"]

#: objective metric name -> the ``done`` event / timings field it reads
METRIC_FIELDS = {"ttft": "ttft_s", "tpot": "tpot_s", "e2e": "total_s",
                 "queue_wait": "queue_wait_s"}

_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0}

_OBJ_RE = re.compile(
    r"^\s*(?P<metric>[a-z][a-z0-9_]*?)(?:_p(?P<pct>\d+(?:\.\d+)?))?\s*"
    r"(?P<op><=?)\s*(?P<value>\d+(?:\.\d+)?)\s*(?P<unit>us|ms|s)?\s*$")


class Objective(NamedTuple):
    """One parsed objective: ``metric`` (a :data:`METRIC_FIELDS` key),
    ``pct`` the compliance percentile (``p99`` -> 99.0), and
    ``threshold_s`` in seconds.  A request with the metric missing
    (e.g. TPOT on a single-token request) passes vacuously."""
    metric: str
    pct: float
    threshold_s: float

    def describe(self) -> str:
        t = self.threshold_s
        unit, scale = (("ms", 1e3) if t < 1.0 else ("s", 1.0))
        return f"{self.metric}_p{self.pct:g}<{t * scale:g}{unit}"

    def good(self, request: Dict[str, Any]) -> bool:
        v = request.get(METRIC_FIELDS[self.metric])
        return v is None or float(v) <= self.threshold_s


class SLOSpec(NamedTuple):
    """A parsed spec: the objectives plus the overall compliance target
    (the strictest percentile qualifier — ``p99`` objectives demand 99%
    of requests good)."""
    objectives: tuple
    target_pct: float

    def good(self, request: Dict[str, Any]) -> bool:
        return all(o.good(request) for o in self.objectives)

    def budget(self) -> float:
        """Error budget as a fraction (``p99`` -> 0.01), floored so a
        pathological ``p100`` target cannot divide burn rates by 0."""
        return max((100.0 - self.target_pct) / 100.0, 1e-4)

    def describe(self) -> str:
        return ",".join(o.describe() for o in self.objectives)


def parse_slo(spec) -> SLOSpec:
    """Parse ``"ttft_p99<200ms,tpot_p99<30ms"`` (an already-parsed
    :class:`SLOSpec` passes through).  Unknown metrics, units, or
    shapes raise ``ValueError`` with the offending clause — a typo'd
    SLO must fail the launch, not silently gate nothing."""
    if isinstance(spec, SLOSpec):
        return spec
    objectives: List[Objective] = []
    for clause in str(spec).split(","):
        if not clause.strip():
            continue
        m = _OBJ_RE.match(clause)
        if not m:
            raise ValueError(
                f"unparseable SLO clause {clause.strip()!r} (expected "
                f"e.g. 'ttft_p99<200ms'; metrics: "
                f"{', '.join(sorted(METRIC_FIELDS))})")
        metric = m.group("metric")
        if metric not in METRIC_FIELDS:
            raise ValueError(
                f"unknown SLO metric {metric!r} (have: "
                f"{', '.join(sorted(METRIC_FIELDS))})")
        pct = float(m.group("pct") or 99.0)
        if not 0.0 < pct <= 100.0:
            raise ValueError(f"SLO percentile p{pct:g} out of (0, 100]")
        scale = _UNITS[m.group("unit") or "s"]
        objectives.append(Objective(metric, pct,
                                    float(m.group("value")) * scale))
    if not objectives:
        raise ValueError(f"empty SLO spec {spec!r}")
    return SLOSpec(tuple(objectives),
                   target_pct=max(o.pct for o in objectives))


def evaluate(spec, requests: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Goodput of a finished request set against ``spec`` — the offline
    evaluation ``prof.requests --slo`` reports (same per-request
    ``good`` predicate as the online fold, so live gauges and offline
    reports can never disagree on classification)."""
    spec = parse_slo(spec)
    n = len(requests)
    good = sum(1 for r in requests if spec.good(r))
    out: Dict[str, Any] = {
        "spec": spec.describe(),
        "target_pct": spec.target_pct,
        "n_requests": n,
        "good": good,
        "goodput_pct": round(100.0 * good / n, 3) if n else None,
        "met": (None if not n
                else (100.0 * good / n) >= spec.target_pct),
    }
    from .metrics import nearest_rank_percentiles
    per_obj = []
    for o in spec.objectives:
        vals = [float(r[METRIC_FIELDS[o.metric]]) for r in requests
                if r.get(METRIC_FIELDS[o.metric]) is not None]
        achieved = nearest_rank_percentiles(vals, (o.pct,))[0]
        per_obj.append({
            "objective": o.describe(),
            "achieved_s": (round(achieved, 6)
                           if achieved is not None else None),
            "ok": achieved is None or achieved <= o.threshold_s,
        })
    out["objectives"] = per_obj
    return out


class SLOEngine:
    """Online fold of ``serving`` ``done`` events into goodput/burn
    gauges and ``slo`` events (see module docstring).

    ``observe`` is called by the recorder after every written event on
    the emitting thread, under this object's lock; the ``slo`` events
    an evaluation emits go back through ``Recorder.event`` OUTSIDE the
    lock (the recorder skips re-folding ``slo``/``alert`` kinds, so
    this cannot recurse)."""

    def __init__(self, recorder, spec, *, short_window_s: float = 60.0,
                 long_window_s: float = 600.0, eval_every: int = 4,
                 min_requests: int = 8):
        self._rec = recorder
        self.spec = parse_slo(spec)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.eval_every = max(1, int(eval_every))
        self.min_requests = int(min_requests)
        self._lock = threading.Lock()
        # (t, good) per finished request; bounded — the long window at
        # any plausible request rate fits, and an hour-long burst
        # cannot grow host memory without bound.
        self._done: deque = deque(maxlen=65536)
        self.total = 0
        self.bad_total = 0
        self._since_eval = 0
        #: last evaluation's fields (the ``slo`` event body), for the
        #: exit line and tests.
        self.last: Optional[Dict[str, Any]] = None

    # -- fold ---------------------------------------------------------------
    def observe(self, event: Dict[str, Any]) -> None:
        if event.get("kind") != "serving" or event.get("phase") != "done":
            return
        if event.get("ttft_s") is None and event.get("total_s") is None:
            return                        # pre-ISSUE-20 stream shape
        emit: Optional[Dict[str, Any]] = None
        with self._lock:
            t = float(event.get("t", 0.0))
            good = self.spec.good(event)
            self._done.append((t, good))
            self.total += 1
            self.bad_total += 0 if good else 1
            self._since_eval += 1
            if self._since_eval >= self.eval_every or self.total == 1:
                self._since_eval = 0
                emit = self._eval_locked(t)
        if emit is None:
            return
        rec = self._rec
        if rec is not None and rec.enabled:
            for name in ("slo_goodput_pct", "slo_burn_rate_short",
                         "slo_burn_rate_long"):
                key = {"slo_goodput_pct": "goodput_pct",
                       "slo_burn_rate_short": "burn_short",
                       "slo_burn_rate_long": "burn_long"}[name]
                rec.metrics.gauge(name).set(emit[key])
            rec.event("slo", phase="eval", **emit)

    def _window(self, now: float, window_s: float):
        n = bad = 0
        for t, good in reversed(self._done):
            if now - t > window_s:
                break
            n += 1
            bad += 0 if good else 1
        return n, bad

    def _eval_locked(self, now: float) -> Dict[str, Any]:
        budget = self.spec.budget()
        n_s, bad_s = self._window(now, self.short_window_s)
        n_l, bad_l = self._window(now, self.long_window_s)
        goodput = 100.0 * (n_l - bad_l) / n_l if n_l else 100.0
        burn_short = (bad_s / n_s / budget) if n_s else 0.0
        burn_long = (bad_l / n_l / budget) if n_l else 0.0
        # the run-level budget: exhausted when the bad fraction over
        # EVERYTHING served has consumed the whole allowance (not a
        # window blip — the SLO for this run is unrecoverable without
        # a quiet stretch).
        exhausted = (self.total >= self.min_requests
                     and (self.bad_total / self.total) > budget)
        self.last = {
            "goodput_pct": round(goodput, 3),
            "burn_short": round(burn_short, 3),
            "burn_long": round(burn_long, 3),
            "window_n": n_l,
            "n": self.total,
            "bad": self.bad_total,
            "target_pct": self.spec.target_pct,
            "exhausted": exhausted,
        }
        return dict(self.last)

    # -- exit line ----------------------------------------------------------
    def format_line(self) -> str:
        """One-line SLO verdict for the examples' exit print."""
        if self.last is None:
            return f"{self.spec.describe()}: no requests evaluated"
        s = self.last
        state = ("EXHAUSTED" if s["exhausted"]
                 else "burning" if s["burn_long"] > 1.0 else "ok")
        return (f"{self.spec.describe()}: goodput "
                f"{s['goodput_pct']:.1f}% (target "
                f"{s['target_pct']:g}%), burn {s['burn_short']:.1f}x/"
                f"{s['burn_long']:.1f}x short/long — {state}")


def attach(recorder, spec, **kwargs) -> SLOEngine:
    """Build an :class:`SLOEngine` and hook it onto ``recorder``
    (``telemetry.start(slo=...)`` calls this).  Returns the engine."""
    eng = SLOEngine(recorder, spec, **kwargs)
    recorder.attach_slo(eng)
    return eng
