"""Request-level tracing — deterministic trace/span ids over the event
stream (ISSUE 20 tentpole, piece 1).

The recorder's aggregate histograms say *how slow* serving is; they
cannot say *why request 7 took 900 ms*.  This module adds the missing
per-request view with the same zero-marginal-cost discipline as the
rest of the telemetry engine:

* a **trace** is one request's journey (submit → queue → prefill →
  decode steps → done); a **span** is one timed phase of it, emitted
  through the existing :class:`~apex_tpu.telemetry.events.Recorder` as
  ``span`` events — rotation, the watchdog fold, the exporter tick,
  and ``prof.fleet`` multi-host reassembly all work unchanged;
* ids are **deterministic and counter-based** (``t<host>-<n>`` /
  ``s<n>``): no wall-clock or RNG entropy on the hot path, so the same
  load replayed produces the same tree and the disabled path stays
  bitwise-identical to an uninstrumented build;
* **sampling** bounds the overhead: ``sample_n=N`` traces every Nth
  sampled unit (request), ``sample_n=0`` (the default when
  ``APEX_TPU_TRACE_SAMPLE`` is unset) traces nothing.  Untraced
  requests pay ONE counter increment at submit and nothing per token —
  the established 1.5x telemetry overhead gate holds with
  ``sample_n=1`` (``bench.py`` gates it);
* with **no recorder installed** every entry point is a strict no-op:
  :func:`get_tracer` returns ``None`` and the instrumented call sites
  reduce to the same one-global-read the rest of telemetry pays.

Span event schema (one JSONL line per finished span)::

    {"t": <end, stream clock>, "kind": "span", "name": "prefill",
     "trace": "t0-000007", "span": "s000042", "parent": "s000041",
     "dur": 0.0183, ...free-form fields (slot/bucket/batch_size/...)}

``t`` is the span's END on the stream clock and ``dur`` its length —
the same convention as ``window`` events, so ``start = t - dur`` and
the Chrome exporter renders spans without a special case.  The root
span of a trace has no ``parent``.  Offline reassembly:
``python -m apex_tpu.prof.requests`` (waterfalls, TTFT/TPOT
percentiles, goodput, the batch-size/TPOT join).

Usage::

    rec = telemetry.start("run.jsonl", trace_sample_n=1)   # or env
    tr = rec.tracer
    trace = tr.sample()                  # every Nth call -> a trace id
    if trace is not None:
        root = tr.emit("request", trace, dur=total_s)
        with tr.span("prefill", trace, parent=root, slot=0):
            ...
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Optional

__all__ = ["Tracer", "attach", "sample_n_from_env"]


def sample_n_from_env() -> int:
    """``APEX_TPU_TRACE_SAMPLE`` as an int (0 / unset / garbage -> 0,
    i.e. tracing off) — the flags-free wiring ``telemetry.start`` uses."""
    raw = (os.environ.get("APEX_TPU_TRACE_SAMPLE") or "").strip()
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        return 0


class Tracer:
    """Deterministic id factory + ``span`` event emitter for one
    recorder.

    ``sample()`` is the sampling gate: every ``sample_n``-th call
    returns a fresh trace id (the caller traces that unit), the rest
    return ``None`` (the caller emits nothing).  ``sample_n <= 0``
    never samples.  Counters are plain itertools counters under a lock
    — cheap, deterministic, and unique per process; the trace id embeds
    the recorder's ``process_index`` so merged multi-host streams never
    collide."""

    def __init__(self, recorder, sample_n: int = 1):
        self._rec = recorder
        self.sample_n = int(sample_n)
        self._lock = threading.Lock()
        self._seen = 0                       # sampling-unit counter
        self._traces = itertools.count()     # allocated trace ids
        self._spans = itertools.count()      # allocated span ids
        self._host = int(getattr(recorder, "process_index", 0) or 0)

    # -- ids ----------------------------------------------------------------
    def sample(self) -> Optional[str]:
        """One sampling decision: a new trace id for every
        ``sample_n``-th call, else ``None``.  Thread-safe (submit runs
        on caller threads)."""
        if self.sample_n <= 0:
            return None
        with self._lock:
            n = self._seen
            self._seen += 1
            if n % self.sample_n:
                return None
            return f"t{self._host}-{next(self._traces):06d}"

    def next_span_id(self) -> str:
        """A fresh span id (unique within this process' stream)."""
        with self._lock:
            return f"s{next(self._spans):06d}"

    # -- emission -----------------------------------------------------------
    def emit(self, name: str, trace: Optional[str], *,
             parent: Optional[str] = None, dur: float = 0.0,
             span: Optional[str] = None, **fields) -> Optional[str]:
        """Emit one already-measured span (the engine times a batched
        decode dispatch ONCE and emits a span per traced participant).
        ``trace=None`` is the not-sampled no-op; returns the span id so
        children can parent to it."""
        if trace is None:
            return None
        rec = self._rec
        if rec is None or not rec.enabled:
            return None
        sid = span if span is not None else self.next_span_id()
        if parent is not None:
            fields["parent"] = parent
        rec.event("span", name=name, trace=trace, span=sid,
                  dur=round(float(dur), 6), **fields)
        return sid

    @contextlib.contextmanager
    def span(self, name: str, trace: Optional[str], *,
             parent: Optional[str] = None, **fields):
        """Context manager measuring and emitting one span; yields the
        span id (``None`` when the trace is unsampled — the strict
        no-op path: no clock read, no allocation)."""
        if trace is None:
            yield None
            return
        sid = self.next_span_id()
        t0 = time.perf_counter()
        try:
            yield sid
        finally:
            self.emit(name, trace, parent=parent, span=sid,
                      dur=time.perf_counter() - t0, **fields)


def attach(recorder, sample_n: int = 1) -> Tracer:
    """Build a :class:`Tracer` and hook it onto ``recorder``
    (``telemetry.start(trace_sample_n=...)`` calls this).  Returns the
    tracer; instrumented subsystems discover it via
    ``recorder.tracer``."""
    tr = Tracer(recorder, sample_n=sample_n)
    recorder.attach_tracer(tr)
    return tr
