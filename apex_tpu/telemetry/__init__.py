"""apex_tpu.telemetry — run-wide observability engine (ISSUE 5).

The runtime counterpart of the ``prof`` package's static analysis (the
PyProf pillar, SURVEY.md §2.9): a low-overhead structured event stream
you can tail in production and analyze offline, plus a metrics registry
whose device-side values piggyback on the existing one-dispatch-behind
metric reads (zero extra host syncs per window).

* :class:`Recorder` / :func:`start` — thread-safe JSONL event stream
  (step windows, dispatch gaps, loader stage/stall, loss-scale
  skip/growth, retraces, per-psum collective bytes).
* :class:`MetricsRegistry` — counters / gauges / reservoir-percentile
  histograms; a strict no-op when disabled.
* :class:`Watchdog` (:mod:`~apex_tpu.telemetry.watchdog`) — run-health
  rule engine folding events online into debounced ``alert`` events
  (non-finite loss, loss-scale collapse, loader-stall spikes, step-time
  anomalies, retrace storms); ``telemetry.start(path, watchdog=True)``.
* :func:`to_chrome_trace` — Chrome ``trace_event`` export (Perfetto).
* Offline analysis: ``python -m apex_tpu.prof.timeline run.jsonl``;
  cross-run regression diffing: ``python -m apex_tpu.prof.regress``.

Instrumented subsystems discover the active recorder through
:func:`get_recorder`; with none installed the hot paths reduce to one
global read — the disabled path dispatches bit-identically to an
uninstrumented build (``bench.py`` gates this).

See ``docs/telemetry.md`` for the event schema and overhead model.
"""

from .events import (Recorder, get_recorder, set_recorder,  # noqa: F401
                     start, start_from_env, to_chrome_trace,
                     expand_stream_paths)
from .export import PrometheusExporter, attach_exporter     # noqa: F401
from .metrics import (Counter, Gauge, Histogram,            # noqa: F401
                      MetricsRegistry, Rolling)
from .slo import SLOEngine, SLOSpec, parse_slo              # noqa: F401
from .tracing import Tracer                                 # noqa: F401
from .watchdog import Watchdog                              # noqa: F401

__all__ = ["Recorder", "get_recorder", "set_recorder", "start",
           "start_from_env", "to_chrome_trace", "expand_stream_paths",
           "PrometheusExporter", "attach_exporter", "Counter", "Gauge",
           "Histogram", "MetricsRegistry", "Rolling", "Watchdog",
           "Tracer", "SLOEngine", "SLOSpec", "parse_slo"]
