"""Structured event stream — the runtime half of the PyProf pillar.

The reference's PyProf turns a live run into an analyzable record by
pushing NVTX ranges into a CUPTI SQLite DB (``pyprof/nvtx`` +
``pyprof/parse``).  The TPU-native equivalent cannot annotate from
inside a compiled program, so the record is assembled at the HOST
boundaries the runtime already crosses:

* window dispatch + dispatch gap       (:class:`apex_tpu.runtime.StepPipeline`)
* the one-dispatch-behind metric fetch (:class:`apex_tpu.runtime.DeferredMetrics`)
* loader wait / device staging         (:class:`apex_tpu.data.PrefetchLoader`)
* loss-scale skip/growth               (derived from the fetched metrics,
  plus the imperative :class:`apex_tpu.amp.LossScaler` /
  :class:`apex_tpu.optimizers.FusedOptimizer` paths)
* retraces                             (jit tracing-cache growth, keyed by
  the window's shape signature)
* per-psum collective bytes            (recorded at TRACE time from the
  static avals — zero runtime cost)

:class:`Recorder` writes one JSON object per line (JSONL): ``tail -f``
it in production, feed it to the offline analyzer
(``python -m apex_tpu.prof.timeline run.jsonl``), or export a Chrome
``trace_event`` file (:func:`to_chrome_trace`) for Perfetto /
``chrome://tracing``.

Overhead model: every event is one small dict + one ``json.dumps`` + one
buffered write (~single-digit microseconds); the hot loop emits 2-3
events per WINDOW (not per step) and the loader a couple per batch on
its own threads.  With no recorder installed the instrumented call sites
reduce to one global read returning ``None`` — the disabled path
dispatches bit-identically to an uninstrumented build (gated by
``bench.py`` self-validation).

Usage::

    from apex_tpu import telemetry

    rec = telemetry.start("run.jsonl", example="imagenet")
    ...             # StepPipeline / PrefetchLoader / amp pick it up
    rec.close()     # writes the summary event

or scoped: ``with telemetry.start(path): ...``.
"""

from __future__ import annotations

import contextlib
import glob as _glob
import json
import os
import re
import threading
import time
from typing import Any, Dict, IO, List, Optional, Union

from .metrics import MetricsRegistry

__all__ = ["Recorder", "get_recorder", "set_recorder", "start",
           "start_from_env", "to_chrome_trace", "expand_stream_paths"]

_active: Optional["Recorder"] = None
_active_lock = threading.Lock()


def get_recorder() -> Optional["Recorder"]:
    """The process-wide active recorder, or None when telemetry is off —
    the ONE read every instrumented hot path pays when disabled."""
    return _active


def set_recorder(rec: Optional["Recorder"]) -> Optional["Recorder"]:
    """Install (or clear, with None) the active recorder; returns the
    previous one so scoped users can restore it."""
    global _active
    with _active_lock:
        prev, _active = _active, rec
    return prev


def _env_flag(name: str) -> Optional[bool]:
    """Tri-state env-var read: unset -> None, else the usual truthy set."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return None
    return raw.strip().lower() not in ("0", "false", "no", "off")


def start(path: Optional[str] = None, watchdog: Optional[bool] = None,
          run_id: Optional[str] = None, *,
          max_bytes: Optional[int] = None,
          export_textfile: Optional[str] = None,
          export_port: Optional[int] = None,
          export_every_s: float = 5.0,
          trace_sample_n: Optional[int] = None,
          slo: Optional[str] = None,
          process_index: Optional[int] = None,
          process_count: Optional[int] = None,
          **meta) -> "Recorder":
    """Open a recorder on ``path`` and install it as the active one.
    Keyword args land in the stream's leading ``run`` event.

    ``path=None`` reads ``APEX_TPU_TELEMETRY`` — any entrypoint (the
    docker matrix, ``bench.py``, a user script) can be instrumented by
    exporting the env var instead of plumbing a flag (ISSUE 10
    satellite); with neither a ``ValueError`` says so.  ``watchdog``
    likewise defaults from ``APEX_TPU_WATCHDOG`` (``0``/``1``), and the
    export knobs from ``APEX_TPU_METRICS_TEXTFILE`` /
    ``APEX_TPU_METRICS_PORT``.  See :func:`start_from_env` for the
    quiet does-nothing-when-unconfigured variant.

    ``watchdog=True`` also attaches the run-health rule engine
    (:mod:`apex_tpu.telemetry.watchdog`): events are folded online on
    the emitting thread and debounced ``alert`` events land in the same
    stream; read ``rec.watchdog.format_line()`` at exit for the
    one-line health summary.

    ``run_id`` names the run across interruptions (ISSUE 9): a resumed
    process passes the id restored from its checkpoint so the resumed
    stream is attributable to the same logical run; omitted, a fresh id
    is generated.  Either way it rides the ``run`` event and
    ``rec.run_id``.

    ``max_bytes`` bounds each stream segment (ISSUE 10 satellite): when
    the file crosses it, the recorder writes a ``rotate`` event,
    atomically renames the segment to ``path.<seq>`` and reopens
    ``path`` — a week-long fleet run never grows one unbounded file.
    ``prof.timeline`` / ``prof.fleet`` re-assemble the rotated set
    (:func:`expand_stream_paths`).

    ``export_textfile`` / ``export_port`` attach the live Prometheus
    exporter (:mod:`apex_tpu.telemetry.export`): registry
    counters/gauges/histograms plus watchdog health rendered to
    text-exposition format every ``export_every_s`` seconds on the
    threads that already emit events (zero extra host syncs) and/or
    served from a stdlib http endpoint.

    ``trace_sample_n`` attaches the request tracer
    (:mod:`apex_tpu.telemetry.tracing`, ISSUE 20): every Nth sampled
    unit (serving request) emits a ``span`` tree into the same stream;
    defaults from ``APEX_TPU_TRACE_SAMPLE`` (unset/0 -> no tracing).
    ``slo`` attaches the SLO engine (:mod:`apex_tpu.telemetry.slo`) on
    a spec string like ``"ttft_p99<200ms,tpot_p99<30ms"`` (env
    ``APEX_TPU_SLO``): goodput/burn-rate gauges fold online and the
    watchdog's ``slo_burn``/``slo_exhausted`` rules alert on them."""
    if path is None:
        path = os.environ.get("APEX_TPU_TELEMETRY") or None
        if path is None:
            raise ValueError(
                "telemetry.start() needs a stream path: pass one, or set "
                "APEX_TPU_TELEMETRY=path (use telemetry.start_from_env() "
                "for an entrypoint that should quietly skip telemetry "
                "when unconfigured)")
    if watchdog is None:
        watchdog = bool(_env_flag("APEX_TPU_WATCHDOG"))
    if export_textfile is None:
        export_textfile = os.environ.get("APEX_TPU_METRICS_TEXTFILE") or None
    if export_port is None:
        raw_port = os.environ.get("APEX_TPU_METRICS_PORT")
        export_port = int(raw_port) if raw_port else None
    if trace_sample_n is None:
        from .tracing import sample_n_from_env
        trace_sample_n = sample_n_from_env()
    if slo is None:
        slo = (os.environ.get("APEX_TPU_SLO") or "").strip() or None
    rec = Recorder(path, meta=meta or None, run_id=run_id,
                   max_bytes=max_bytes, process_index=process_index,
                   process_count=process_count)
    if watchdog:
        from .watchdog import attach
        attach(rec)
    if export_textfile is not None or export_port is not None:
        from .export import attach_exporter
        attach_exporter(rec, textfile=export_textfile, port=export_port,
                        every_s=export_every_s)
    if trace_sample_n and trace_sample_n > 0:
        from .tracing import attach as attach_tracer
        attach_tracer(rec, sample_n=trace_sample_n)
    if slo is not None:
        from .slo import attach as attach_slo
        attach_slo(rec, slo)
    set_recorder(rec)
    return rec


def start_from_env(**meta) -> Optional["Recorder"]:
    """:func:`start` driven purely by env vars — returns the installed
    :class:`Recorder` when ``APEX_TPU_TELEMETRY`` names a stream path,
    else ``None`` without side effects.  The hook entrypoints call when
    they have no telemetry flags of their own (``bench.py``, the docker
    matrix): ``APEX_TPU_TELEMETRY=/tmp/run.jsonl APEX_TPU_WATCHDOG=1
    python bench.py`` instruments the whole run."""
    if not (os.environ.get("APEX_TPU_TELEMETRY") or "").strip():
        return None
    return start(**meta)


def _json_default(x):
    """Tolerant JSON encoding: numpy scalars/arrays and jax types show
    up in metric dicts; never let an exotic leaf kill the stream."""
    if hasattr(x, "item") and getattr(x, "ndim", None) == 0:
        try:
            return x.item()  # jaxlint: disable=J001 -- JSON encoding is the host boundary; values reaching the encoder were already fetched by the deferred reader
        except Exception:
            pass
    if hasattr(x, "tolist"):
        try:
            return x.tolist()
        except Exception:
            pass
    return repr(x)


def _process_identity() -> tuple:
    """``(process_index, process_count)`` of this host in the fleet —
    delegated to :func:`apex_tpu.parallel.multiproc.process_identity`
    (the one source the checkpoint shard writer also stamps with, so a
    spawned-but-not-yet-initialized worker's stream and shards agree)
    when jax is already imported; telemetry must stay usable on a
    stream-analysis box with no jax, so nothing here imports it."""
    import sys
    if sys.modules.get("jax") is not None:
        try:
            from ..parallel.multiproc import process_identity
            return process_identity()
        except Exception:
            pass
    return 0, 1


class Recorder:
    """Thread-safe JSONL event sink + metrics registry for one run.

    Every event is ``{"t": <seconds since the recorder opened>,
    "kind": <str>, ...fields}``.  Event kinds and their schema are
    documented in ``docs/telemetry.md`` (the table the analyzer and the
    Chrome exporter are written against).

    The recorder is a context manager (``close`` on exit, restoring the
    previously active recorder if this one was active).  After
    ``close()`` every ``event()`` is a silent no-op, so late producer
    threads (loader workers draining) cannot crash shutdown.
    """

    def __init__(self, path_or_file: Union[str, IO], *,
                 meta: Optional[dict] = None, reservoir: int = 512,
                 run_id: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import uuid
        #: stable identifier of the LOGICAL run — survives kill/resume
        #: when the resuming process passes the checkpointed id back
        #: through ``telemetry.start(run_id=...)`` (ISSUE 9).
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        if hasattr(path_or_file, "write"):
            self._f, self._owns, self.path = path_or_file, False, None
        else:
            self._f = open(path_or_file, "w", encoding="utf-8")
            self._owns, self.path = True, path_or_file
        self._t0 = time.perf_counter()
        #: run-start wall-clock anchor (unix seconds at ``t == 0``) — the
        #: coarse cross-host alignment ``prof.fleet`` refines with
        #: per-window dispatch indices (ISSUE 10).
        self.anchor_unix = time.time()
        if process_index is None or process_count is None:
            process_index, process_count = _process_identity()
        #: this host's slot in the fleet, stamped on the ``run`` event so
        #: a merged multi-host analysis can attribute every stream.
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        # stream rotation (ISSUE 10 satellite): segment byte budget; the
        # active file is always `path`, full segments atomically rename
        # to `path.<seq>` after a trailing `rotate` event.
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._bytes_written = 0
        self._segment = 0
        self._meta = dict(meta or {})
        #: free-form identity labels merged into the Prometheus
        #: ``run_info`` exposition (e.g. the serving engine's
        #: ``kv_cache_dtype`` — ISSUE 13).  Last-write-wins strings.
        self.run_info: Dict[str, str] = {}
        self._closed = False
        self._counts: Dict[str, int] = {}
        #: host-side instruments, snapshotted into the ``summary`` event.
        self.metrics = MetricsRegistry(reservoir=reservoir)
        # observe_window_metrics state: _obs_hwm marks the highest step
        # already observed (a re-fetched window — warmup drain + cadence
        # print hit the same WindowMetrics twice — is tagged
        # refetch=True, a real transfer but not new data); _scale_hwm
        # guards the loss-scale derivation against the same doubling.
        self._obs_hwm = 0
        self._scale_hwm = 0
        self._last_scale: Optional[float] = None
        #: optional run-health rule engine (attach_watchdog / watchdog.attach)
        self._watchdog = None
        #: optional live metrics exporter (export.attach_exporter)
        self._exporter = None
        #: optional request tracer (tracing.attach — ISSUE 20)
        self._tracer = None
        #: optional SLO fold (slo.attach — ISSUE 20)
        self._slo = None
        self.event("run", **self._run_fields())

    def _run_fields(self) -> Dict[str, Any]:
        """The ``run`` event's fields — re-emitted at the head of every
        rotated segment so each file in a rotated set is
        self-describing (same run_id / anchor / host identity)."""
        return {"run_id": self.run_id, "meta": self._meta,
                "process_index": self.process_index,
                "process_count": self.process_count,
                "anchor_unix": round(self.anchor_unix, 6),
                "segment": self._segment}

    # -- core sink ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return not self._closed

    def now(self) -> float:
        """Seconds since the recorder opened (the stream's clock)."""
        return time.perf_counter() - self._t0

    def event(self, kind: str, **fields) -> None:
        """Append one event; silently dropped after ``close()``."""
        if self._closed:
            return
        rec = {"t": round(self.now(), 6), "kind": kind}
        rec.update(fields)
        line = json.dumps(rec, default=_json_default)
        with self._lock:
            if self._closed:
                return
            self._f.write(line + "\n")
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._bytes_written += len(line) + 1
            if (self._max_bytes is not None and self._owns and self.path
                    and self._bytes_written >= self._max_bytes):
                self._rotate_locked()
        # Watchdog fold (ISSUE 6): outside the stream lock, on THIS
        # thread — the event dict already exists, so the rules cost a
        # few dict reads and no device work.  Alerts the fold emits come
        # back through event() with kind="alert" and are not re-folded.
        wd = self._watchdog
        if wd is not None and kind != "alert":
            wd.observe(rec)
        # SLO fold (ISSUE 20): same discipline — done events fold into
        # goodput/burn state here; the `slo` events an evaluation emits
        # re-enter event() (and ARE watchdog-folded, so slo_burn /
        # slo_exhausted can alert) but are not re-folded here.
        slo = self._slo
        if slo is not None and kind not in ("alert", "slo"):
            slo.observe(rec)
        # Live-export tick (ISSUE 10): same zero-extra-thread discipline
        # — the exporter piggybacks on whichever thread wrote the event
        # and renders only when its interval has elapsed.
        exp = self._exporter
        if exp is not None:
            exp.tick()

    def _rotate_locked(self) -> None:
        """Seal the current segment and reopen ``path`` (stream-lock
        held): append a ``rotate`` event, flush, atomically rename to
        ``path.<seq>``, then start the fresh segment with a
        continuation ``run`` event so every file in the rotated set is
        independently attributable.  The stream clock (``t``) runs on
        unbroken through rotations — concatenating segments in sequence
        order reproduces the unrotated stream exactly."""
        self._segment += 1
        target = f"{self.path}.{self._segment}"
        rot = {"t": round(self.now(), 6), "kind": "rotate",
               "seq": self._segment, "to": os.path.basename(target)}
        self._f.write(json.dumps(rot) + "\n")
        self._counts["rotate"] = self._counts.get("rotate", 0) + 1
        self._f.flush()
        self._f.close()
        os.replace(self.path, target)
        self._f = open(self.path, "w", encoding="utf-8")
        head = {"t": round(self.now(), 6), "kind": "run"}
        head.update(self._run_fields())
        line = json.dumps(head, default=_json_default)
        self._f.write(line + "\n")
        self._counts["run"] = self._counts.get("run", 0) + 1
        self._bytes_written = len(line) + 1

    def attach_watchdog(self, watchdog) -> None:
        """Install a run-health watchdog
        (:class:`apex_tpu.telemetry.watchdog.Watchdog`): every event
        written from now on is folded through its rules, and the final
        ``summary`` event carries its ``health`` verdict."""
        self._watchdog = watchdog

    @property
    def watchdog(self):
        """The attached watchdog, or None."""
        return self._watchdog

    def attach_exporter(self, exporter) -> None:
        """Install a live metrics exporter
        (:class:`apex_tpu.telemetry.export.PrometheusExporter`): its
        ``tick()`` runs after every written event on the emitting
        thread, and ``close()`` finalizes it (last render + endpoint
        shutdown)."""
        self._exporter = exporter

    @property
    def exporter(self):
        """The attached exporter, or None."""
        return self._exporter

    def attach_tracer(self, tracer) -> None:
        """Install a request tracer
        (:class:`apex_tpu.telemetry.tracing.Tracer`): instrumented
        subsystems (the serving engine) discover it here and emit
        sampled ``span`` trees through this recorder."""
        self._tracer = tracer

    @property
    def tracer(self):
        """The attached tracer, or None (tracing off)."""
        return self._tracer

    def attach_slo(self, slo) -> None:
        """Install an SLO fold
        (:class:`apex_tpu.telemetry.slo.SLOEngine`): every ``serving``
        ``done`` event written from now on updates its goodput/burn
        windows, and the final ``summary`` event carries its verdict."""
        self._slo = slo

    @property
    def slo(self):
        """The attached SLO engine, or None."""
        return self._slo

    @contextlib.contextmanager
    def span(self, kind: str, **fields):
        """Context manager emitting ``kind`` with a measured ``dur``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.event(kind, dur=round(time.perf_counter() - t0, 6),
                       **fields)

    # -- domain helpers -----------------------------------------------------
    def observe_window_metrics(self, step: int, n_valid: int, values,
                               fetch_s: float) -> None:
        """Record one window's fetched metrics (called from
        :meth:`apex_tpu.runtime.WindowMetrics.fetch` with HOST values —
        the one-dispatch-behind read the loop already pays, so this adds
        no host sync).  Emits a ``metrics`` event and derives ``scale``
        skip/growth events with global step indices."""
        import numpy as np

        fields: Dict[str, Any] = {"step": step, "n_valid": n_valid,
                                  "dur": round(fetch_s, 6)}
        loss = scale = overflow = None
        if isinstance(values, dict):
            def _series(key):
                v = values.get(key)
                if v is None:
                    return None
                flat = np.ravel(np.asarray(v))
                if flat.size == 0:
                    return None
                if flat.size < n_valid:     # per-window scalar metric
                    flat = np.repeat(flat[-1], n_valid)
                return [float(x) for x in flat[:n_valid]]
            loss = _series("loss")
            scale = _series("loss_scale")
            overflow = _series("overflow")
        if loss is not None:
            fields["loss"] = [round(v, 6) for v in loss]
            self.metrics.gauge("loss").set(loss[-1])
        if scale is not None:
            fields["loss_scale"] = scale
            self.metrics.gauge("loss_scale").set(scale[-1])
        if overflow is not None:
            fields["skips"] = int(sum(1 for v in overflow if v))
        if step + n_valid <= self._obs_hwm:
            # A transfer genuinely happened (the histogram counts it),
            # but the window was already observed — tag it so the
            # analyzer and readers can discount the duplicate.
            fields["refetch"] = True
        self._obs_hwm = max(self._obs_hwm, step + n_valid)
        self.metrics.histogram("metrics_fetch_s").observe(fetch_s)
        self.event("metrics", **fields)
        # Loss-scale trajectory events (skip on overflow, growth on the
        # scale-window doubling), derived host-side from values already
        # fetched.  Monotonic guard: a re-fetched window (warmup drain +
        # cadence print hit the same WindowMetrics twice) derives nothing.
        if scale is None or step + n_valid <= self._scale_hwm:
            return
        for j in range(n_valid):
            gstep = step + j
            if gstep < self._scale_hwm:
                continue
            s = scale[j]
            if overflow is not None and overflow[j]:
                self.metrics.counter("loss_scale_skips").inc()
                self.event("scale", event="skip", step=gstep, scale=s)
            elif self._last_scale is not None and s > self._last_scale:
                self.event("scale", event="grow", step=gstep, scale=s)
            self._last_scale = s
        self._scale_hwm = step + n_valid

    def note_collective(self, op: str, axis, nbytes: int, n: int,
                        dtype: Optional[str] = None,
                        participants: Optional[int] = None) -> None:
        """Record one collective's per-invocation traffic.  Called at
        TRACE time from ``parallel.reduce_gradients`` / ``zero1`` — the
        byte counts are static aval properties, so instrumentation costs
        nothing at run time and the event appears once per compile.
        ``participants`` is the collective's axis-size product (fleet
        wait-vs-wire modelling, ISSUE 10)."""
        fields = {"op": op,
                  "axis": (list(axis) if isinstance(axis, (tuple, list))
                           else axis),
                  "bytes": int(nbytes), "n": int(n)}
        if dtype is not None:
            fields["dtype"] = dtype
        if participants is not None:
            fields["participants"] = int(participants)
        self.event("collective", **fields)

    # -- lifecycle ----------------------------------------------------------
    def close(self, *, loader_stats: Optional[dict] = None) -> None:
        """Write the final ``summary`` event (registry snapshot + event
        counts, plus an optional last ``loader`` snapshot) and close the
        stream.  Idempotent."""
        if self._closed:
            return
        if loader_stats:
            self.event("loader", final=True, stats=dict(loader_stats))
        summary_fields = {"metrics": self.metrics.snapshot()}
        if self._watchdog is not None:
            summary_fields["health"] = self._watchdog.health()
        if self._slo is not None and self._slo.last is not None:
            summary_fields["slo"] = dict(self._slo.last)
        self.event("summary", events=dict(self._counts), **summary_fields)
        if self._exporter is not None:
            # final render BEFORE the stream closes: the scrape target
            # sees the run's last numbers (and the endpoint goes away).
            try:
                self._exporter.close()
            except Exception:
                pass
        with self._lock:
            self._closed = True
            try:
                self._f.flush()
                if self._owns:
                    self._f.close()
            except Exception:
                pass
        if get_recorder() is self:
            set_recorder(None)

    def __enter__(self) -> "Recorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- Chrome trace_event export ------------------------------------------------

# Stream kinds -> synthetic thread rows of the Chrome trace.
_CHROME_TIDS = {
    "window": (1, "device-loop dispatch"),
    "metrics": (2, "metric fetch (1 behind)"),
    "loader_wait": (3, "consumer wait (loader)"),
    "stage": (4, "device staging (H2D)"),
    "opt_step": (5, "optimizer step"),
    "span": (10, "request spans"),
}
_CHROME_INSTANT = {"scale": 6, "retrace": 7, "collective": 8, "marker": 9}
_CHROME_INSTANT_ROW = {6: "loss scale", 7: "retrace", 8: "collectives",
                       9: "markers"}


#: rotated-segment suffix: ``run.jsonl.3`` is segment 3 of ``run.jsonl``
_SEGMENT_RE = re.compile(r"^(?P<base>.+)\.(?P<seq>\d+)$")


def expand_stream_paths(path_or_glob: str) -> List[str]:
    """Resolve one stream argument — a path, a glob, or a member of a
    rotated set — into the ordered list of segment files to read.

    For each distinct stream base, rotated segments (``base.1``,
    ``base.2``, …) come first in sequence order, then the live ``base``
    file — the order :meth:`Recorder._rotate_locked` sealed them in, so
    concatenation reproduces the unrotated stream.  A glob that matches
    nothing returns the input unchanged (the open error stays the
    caller's, with the user's own spelling)."""
    matches = (sorted(_glob.glob(path_or_glob))
               if _glob.has_magic(path_or_glob) else [path_or_glob])
    if not matches:
        return [path_or_glob]
    bases: Dict[str, List[tuple]] = {}
    for p in matches:
        m = _SEGMENT_RE.match(p)
        if m and (m.group("base") in matches
                  or os.path.exists(m.group("base"))
                  or _glob.glob(m.group("base") + ".*")):
            bases.setdefault(m.group("base"), []).append(
                (int(m.group("seq")), p))
        else:
            bases.setdefault(p, [])
    out: List[str] = []
    for base in sorted(bases):
        segs = {p for _, p in bases[base]}
        # pick up rotated siblings the glob itself did not name
        for p in _glob.glob(_glob.escape(base) + ".*"):
            m = _SEGMENT_RE.match(p)
            if m and p not in segs:
                bases[base].append((int(m.group("seq")), p))
                segs.add(p)
        out.extend(p for _, p in sorted(bases[base]))
        if os.path.exists(base) or not bases[base]:
            out.append(base)
    return out


def _read_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue            # a torn tail line must not kill analysis
    return out


def _iter_events(events_or_path) -> List[dict]:
    if isinstance(events_or_path, str):
        out: List[dict] = []
        for p in expand_stream_paths(events_or_path):
            out.extend(_read_jsonl(p))
        return out
    return list(events_or_path)


def chrome_events(events, *, pid: int = 0, host: Optional[str] = None,
                  t_offset_s: float = 0.0) -> List[dict]:
    """One stream's Chrome ``trace_event`` dicts on process lane ``pid``
    (metadata rows + slices/instants).  ``host`` names the lane
    (``process_name`` metadata — ``prof.fleet`` passes ``host<i>`` so a
    merged trace opens as a fleet timeline); ``t_offset_s`` shifts the
    stream onto a common clock (the fleet merge's aligned offset)."""
    out: List[dict] = []
    if host is not None:
        out.append({"ph": "M", "pid": pid, "tid": 0,
                    "name": "process_name", "args": {"name": host}})
    for tid, name in sorted(
            list(_CHROME_TIDS.values())
            + [(t, n) for t, n in _CHROME_INSTANT_ROW.items()]):
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": name}})
    off_us = float(t_offset_s) * 1e6
    for e in events:
        kind = e.get("kind")
        t_us = float(e.get("t", 0.0)) * 1e6 + off_us
        if kind in _CHROME_TIDS:
            tid = _CHROME_TIDS[kind][0]
            dur_us = float(e.get("dur", 0.0)) * 1e6
            args = {k: v for k, v in e.items()
                    if k not in ("t", "kind", "dur")}
            name = kind
            if kind == "window":
                name = f"window@{e.get('step')}"
            elif kind == "metrics":
                name = f"fetch@{e.get('step')}"
            elif kind == "span":
                # nested complete slices on one row: queue/prefill/
                # decode sit inside their request span time-wise, so
                # Perfetto renders the waterfall as a flame
                name = f"{e.get('name', 'span')}@{e.get('trace')}"
            out.append({"ph": "X", "pid": pid, "tid": tid, "name": name,
                        "ts": t_us - dur_us, "dur": max(dur_us, 1.0),
                        "args": args})
        elif kind in _CHROME_INSTANT:
            args = {k: v for k, v in e.items() if k not in ("t", "kind")}
            name = kind if kind != "scale" else \
                f"scale:{e.get('event')}@{e.get('step')}"
            out.append({"ph": "i", "pid": pid, "tid": _CHROME_INSTANT[kind],
                        "name": name, "ts": t_us, "s": "t", "args": args})
    return out


def to_chrome_trace(events_or_path, out_path: str) -> int:
    """Convert a telemetry stream (path or loaded event list) into a
    Chrome ``trace_event`` JSON file (load in Perfetto /
    ``chrome://tracing``).  Durational events become complete ("X")
    slices on per-subsystem rows; scale/retrace/collective/marker events
    become instants.  Returns the number of trace events written.  For
    a merged multi-host trace (one ``pid`` lane per host) see
    ``python -m apex_tpu.prof.fleet --chrome``."""
    events = _iter_events(events_or_path)
    out = chrome_events(events)
    n = sum(1 for e in out if e["ph"] != "M")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": out,
                   "displayTimeUnit": "ms"}, f)
    return n
