"""Live metrics export — Prometheus text exposition for a running
stream (ISSUE 10 tentpole, piece 2).

PR 5's telemetry is tail-able but nothing can *scrape* it: the
watchdog's alerts and the registry's gauges die in the local JSONL
file, so a fleet dashboard has no live numbers until the run ends and
someone runs the offline analyzer.  This module closes that gap with
the same zero-marginal-cost discipline as the recorder itself:

* :func:`render` turns a :class:`~apex_tpu.telemetry.MetricsRegistry`
  snapshot plus the watchdog's health fold into Prometheus
  `text exposition format <https://prometheus.io/docs/instrumenting/exposition_formats/>`_
  — counters, gauges, and histogram summaries (count/sum + reservoir
  quantiles), all pure host-side string work;
* :class:`PrometheusExporter` re-renders **on the threads that already
  emit events** (the recorder calls :meth:`~PrometheusExporter.tick`
  after each written line; a render only actually happens when
  ``every_s`` has elapsed — zero extra host syncs, zero polling
  threads), writing the result to an **atomically-renamed textfile**
  (the node-exporter ``textfile`` collector contract: a scraper never
  reads a torn file) and/or serving it from an optional stdlib
  ``http.server`` endpoint (``GET /metrics``, which renders fresh per
  scrape — an idle run still scrapes current);
* instrumented subsystems publish live gauges into the recorder's
  registry — ``steps_per_s`` (:class:`apex_tpu.runtime.StepPipeline`),
  ``loader_stall_pct`` / ``loader_queue_depth``
  (:class:`apex_tpu.data.PrefetchLoader`), ``checkpoint_backlog``
  (:class:`apex_tpu.checkpoint.CheckpointManager`), ``loss_scale`` and
  ``loss`` (the deferred metric reads), device-memory gauges where the
  backend exposes them (:func:`apex_tpu.prof.memory.device_memory`) —
  so a dashboard sees steps/s, loader stall, loss-scale, backlog, HBM
  use, and alert counts while the run is live.

With no recorder installed nothing here ever runs — the disabled path
stays bitwise-identical to an uninstrumented build (gated, with the
exporter attached, by ``bench.py`` self-validation).

Usage — either flags-free via env vars (ISSUE 10 satellite)::

    APEX_TPU_TELEMETRY=run.jsonl APEX_TPU_METRICS_PORT=9100 python train.py

or explicit::

    rec = telemetry.start("run.jsonl", export_port=9100,
                          export_textfile="metrics.prom")
    print(rec.exporter.describe())      # scrape URL + textfile path
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["PrometheusExporter", "attach_exporter", "render",
           "sanitize_name"]

#: metric-name prefix; every exported family is ``apex_tpu_<name>``.
NAMESPACE = "apex_tpu"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Registry instrument name -> legal Prometheus metric name."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _label_value(v) -> str:
    """Escape a label VALUE per the exposition format (backslash, double
    quote, newline) — run_info values are free-form caller strings and
    one bad character would invalidate the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _line(name: str, value, labels: Optional[Dict[str, str]] = None) -> str:
    if labels:
        lab = ",".join(f'{k}="{_label_value(v)}"'
                       for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {_num(value)}"
    return f"{name} {_num(value)}"


def _num(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    # non-finite values are legal Prometheus literals — a NaN loss
    # gauge (one overflow-skipped window) must render, not crash the
    # textfile into self-disable (found by the verify probe)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(recorder) -> str:
    """Render one recorder's registry + watchdog health as Prometheus
    text exposition (``text/plain; version=0.0.4``).

    Counters become ``<ns>_<name>_total`` counters, gauges plain
    gauges, histograms a summary-style family (``_count``/``_sum`` plus
    ``{quantile=...}`` gauges from the deterministic reservoir).  Run
    identity rides an ``<ns>_run_info`` gauge labelled with ``run_id``
    and the host's ``process_index``/``process_count`` so a fleet
    scrape can aggregate per host; watchdog health exports as
    ``<ns>_watchdog_ok`` plus per-rule ``<ns>_watchdog_alerts_total``.
    """
    snap = recorder.metrics.snapshot()
    lines: List[str] = []
    info_labels = {"run_id": recorder.run_id,
                   "process_index": str(recorder.process_index),
                   "process_count": str(recorder.process_count)}
    # free-form identity labels (e.g. serving kv_cache_dtype, ISSUE 13)
    for k, v in sorted((getattr(recorder, "run_info", None) or {}).items()):
        info_labels.setdefault(sanitize_name(str(k)), str(v))
    lines.append(f"# TYPE {NAMESPACE}_run_info gauge")
    lines.append(_line(f"{NAMESPACE}_run_info", 1, info_labels))
    for name, value in sorted((snap.get("counters") or {}).items()):
        metric = f"{NAMESPACE}_{sanitize_name(name)}_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(_line(metric, value))
    for name, value in sorted((snap.get("gauges") or {}).items()):
        metric = f"{NAMESPACE}_{sanitize_name(name)}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(_line(metric, value))
    for name, h in sorted((snap.get("histograms") or {}).items()):
        if not isinstance(h, dict):
            continue
        metric = f"{NAMESPACE}_{sanitize_name(name)}"
        lines.append(f"# TYPE {metric} summary")
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if h.get(key) is not None:
                lines.append(_line(metric, h[key], {"quantile": q}))
        lines.append(_line(f"{metric}_sum", h.get("sum", 0.0)))
        lines.append(_line(f"{metric}_count", h.get("count", 0)))
        # true cumulative histogram (ISSUE 20 satellite): exact
        # fixed-bound bucket counts as a SEPARATE `_hist` family —
        # Prometheus forbids mixing summary and histogram series under
        # one name, and the summary family above is the stable surface
        # existing dashboards scrape.  `rate()`/`histogram_quantile()`
        # work on this one.
        buckets = h.get("buckets")
        if isinstance(buckets, dict) and buckets.get("le"):
            hist = f"{metric}_hist"
            lines.append(f"# TYPE {hist} histogram")
            for le, c in zip(buckets["le"], buckets.get("counts") or []):
                lines.append(_line(f"{hist}_bucket", c, {"le": _num(le)}))
            lines.append(_line(f"{hist}_bucket", h.get("count", 0),
                               {"le": "+Inf"}))
            lines.append(_line(f"{hist}_sum", h.get("sum", 0.0)))
            lines.append(_line(f"{hist}_count", h.get("count", 0)))
    wd = recorder.watchdog
    if wd is not None:
        health = wd.health()
        lines.append(f"# TYPE {NAMESPACE}_watchdog_ok gauge")
        lines.append(_line(f"{NAMESPACE}_watchdog_ok",
                           1 if health.get("ok") else 0))
        metric = f"{NAMESPACE}_watchdog_alerts_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(_line(metric, health.get("alerts", 0)))
        for rule, n in sorted((health.get("by_rule") or {}).items()):
            lines.append(_line(f"{NAMESPACE}_watchdog_rule_alerts_total",
                               n, {"rule": sanitize_name(rule)}))
    return "\n".join(lines) + "\n"


class PrometheusExporter:
    """Periodic Prometheus renderer riding the recorder's event flow.

    ``tick()`` — called by :meth:`Recorder.event` after every written
    line, on the emitting thread — is one clock read and a compare
    until ``every_s`` elapses, then one render + one atomic textfile
    replace (``os.replace`` of a ``.tmp`` sibling, the node-exporter
    textfile-collector contract).  The optional HTTP endpoint
    (``port=0`` binds an ephemeral port, read it back from ``.port``)
    renders fresh on each ``GET /metrics``, entirely on the server
    thread — an idle training loop still scrapes current numbers.
    """

    def __init__(self, recorder, *, textfile: Optional[str] = None,
                 port: Optional[int] = None, every_s: float = 5.0,
                 bind: str = "127.0.0.1"):
        self._rec = recorder
        self.textfile = textfile
        #: endpoint bind address — loopback by DEFAULT: the exposition
        #: carries run identity and health with no auth, so reaching it
        #: from off-host is an explicit choice (``bind="0.0.0.0"``),
        #: not a surprise (review finding).
        self.bind = bind
        self.every_s = max(0.05, float(every_s))
        self._render_lock = threading.Lock()   # interval gate (tick)
        self._write_lock = threading.Lock()    # serializes .tmp writes
        self._last_render = 0.0
        self.renders = 0          # textfile render count (tests/bench)
        self._httpd = None
        self._http_thread = None
        self.port: Optional[int] = None
        if port is not None:
            self._start_http(int(port))

    # -- render paths -------------------------------------------------------
    def render(self) -> str:
        """Fresh exposition text (also refreshes device-memory gauges
        when the backend exposes them — a host API read, no device
        sync)."""
        self._update_device_memory()
        return render(self._rec)

    def _update_device_memory(self) -> None:
        try:
            from ..prof import memory as _memory
            _memory.update_device_memory_gauges(self._rec)
        except Exception:
            pass

    def tick(self, now: Optional[float] = None) -> bool:
        """Maybe render (interval elapsed) — returns True when a
        textfile write actually happened.  Never raises into the
        recorder's event path: an unwritable textfile disables itself
        loudly once rather than poisoning every subsequent event."""
        if self.textfile is None:
            return False
        now = time.monotonic() if now is None else now
        if now - self._last_render < self.every_s:
            return False
        with self._render_lock:
            if now - self._last_render < self.every_s:
                return False
            self._last_render = now
        try:
            self.write_textfile()
            return True
        except Exception as e:
            import sys
            print(f"telemetry.export: textfile write failed "
                  f"({type(e).__name__}: {e}) — disabling the textfile "
                  f"exporter", file=sys.stderr)
            self.textfile = None
            return False

    def write_textfile(self) -> str:
        """Render now and atomically replace the textfile (write a
        ``.tmp`` sibling, fsync-free ``os.replace``).  Returns the
        path.  Serialized under its own lock: two emitting threads (or
        a tick racing ``close()``) must never interleave writes into
        the same ``.tmp`` — the scraper's never-torn contract holds all
        the way through shutdown (review finding)."""
        target = self.textfile
        if target is None:
            raise ValueError("textfile exporter is disabled")
        text = self.render()
        with self._write_lock:
            tmp = f"{target}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, target)
            self.renders += 1
        return target

    # -- http endpoint ------------------------------------------------------
    def _start_http(self, port: int) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):              # noqa: N802 (stdlib API name)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):     # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((self.bind, port), _Handler)
        self.port = int(self._httpd.server_address[1])
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="apex-tpu-metrics-http")
        self._http_thread.start()

    # -- lifecycle ----------------------------------------------------------
    def describe(self) -> str:
        """Human-readable scrape target(s) — the examples' exit line."""
        parts = []
        if self.port is not None:
            host = ("localhost" if self.bind in ("127.0.0.1", "")
                    else self.bind)
            parts.append(f"http://{host}:{self.port}/metrics")
        if self.textfile is not None:
            parts.append(f"textfile {self.textfile}")
        return " + ".join(parts) if parts else "disabled"

    def close(self) -> None:
        """Final textfile render + endpoint shutdown.  Idempotent."""
        if self.textfile is not None:
            try:
                self.write_textfile()
            except Exception:
                pass
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                pass


def attach_exporter(recorder, *, textfile: Optional[str] = None,
                    port: Optional[int] = None,
                    every_s: float = 5.0,
                    bind: str = "127.0.0.1") -> PrometheusExporter:
    """Build a :class:`PrometheusExporter` and hook it onto
    ``recorder`` (``telemetry.start(export_textfile=..., export_port=
    ...)`` calls this).  Returns the exporter.  ``bind`` defaults to
    loopback; pass ``"0.0.0.0"`` to expose the endpoint off-host."""
    exp = PrometheusExporter(recorder, textfile=textfile, port=port,
                             every_s=every_s, bind=bind)
    recorder.attach_exporter(exp)
    return exp
