"""Step-pipelining runtime: K-step device loops over staged batch windows.

BENCH r05 measured the gap this module closes: the chip finishes a
ResNet-50 amp-O2 step in 46.9 ms but the per-step jitted wall time is
52.3 ms (~10% pure dispatch), and the flagship examples were far worse
(imagenet held 1529 img/s against a 2492 img/s best window; DCGAN 4.67
it/s against 57).  The reference hides the same class of overhead with
CUDA-stream prefetch (``examples/imagenet/main_amp.py`` ``data_prefetcher``)
and per-step kernel fusion; the TPU-native answer is to make the *program*
— not the step — the unit of host dispatch:

* :class:`StepPipeline` runs K jitted train steps per host dispatch as ONE
  compiled ``lax.scan`` over a stacked ``[K, ...]`` batch window, donating
  both the carried state and the consumed window;
* :func:`stage_windows` groups a per-step batch stream into such windows
  and stages them through :class:`apex_tpu.data.PrefetchLoader`, so the
  host->device transfer of window N+1 overlaps the device loop of window N
  (the ``data_prefetcher`` analog, one level up);
* :class:`DeferredMetrics` holds each window's per-step metrics as DEVICE
  arrays and hands reads back one dispatch behind, so the hot loop never
  blocks on a scalar — by the time window N-1's metrics are fetched,
  window N is already enqueued and the device keeps working through the
  round-trip.

Ragged epoch tails (a final window with fewer than K real batches) and
mid-window dynamic-loss-scale skips are handled WITHOUT retracing: the
tail is padded to the same ``[K, ...]`` shape and executed by a separate
masked program (compiled once, ever) whose per-step carry is select-gated
on a ``valid`` mask, and the scaler's overflow flag never leaves the
device (``multi_tensor`` keeps it a traced scalar).  The hot-window
program therefore compiles exactly once per (K, shape) — pin it with
:func:`apex_tpu.prof.assert_trace_count`.

Usage::

    from apex_tpu import runtime

    pipe = runtime.StepPipeline(step_fn, k=16)
    windows = runtime.stage_windows(batch_stream, k=16,
                                    transform=normalize)
    reader = runtime.DeferredMetrics()
    for window, n_valid in windows:
        state, metrics = pipe.step_window(state, window, n_valid)
        prev = reader.push(metrics, n_valid)
        if prev is not None and want_to_print(prev.step):
            host = prev.fetch()            # one stacked transfer, one
            ...                            # dispatch behind the device

    final = reader.last()                  # drains the pipeline

For SPMD runs pass ``wrap`` — a callable (e.g. a ``shard_map`` partial)
applied to the loop function ``(state, window, valid) -> (state, metrics)``
before ``jax.jit``; the window's leading K axis stays unsharded.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Iterable, Iterator, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .training import chain_steps

__all__ = ["StepPipeline", "DeferredMetrics", "WindowMetrics",
           "stage_windows", "window_batches"]


def _select_tree(flag, new, old):
    """Per-leaf ``where(flag, new, old)`` — the carry gate for masked
    (padded) steps.  ``flag`` is a traced bool scalar, so the whole tail
    window runs data-dependently with zero retraces."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(flag, n, o), new, old)


class StepPipeline:
    """K train steps per host dispatch, as one compiled device loop.

    ``step_fn(state, batch) -> (state, metrics)`` is the usual fully-jitted
    amp step (:func:`apex_tpu.training.make_train_step`).  The pipeline
    compiles it into ``lax.scan`` over a ``[K, ...]``-stacked batch window
    (:func:`apex_tpu.training.chain_steps`) so host dispatch, argument
    marshalling, and metric plumbing cost once per K steps.

    Two programs back one pipeline:

    * the **hot loop** — full windows, no masking overhead, compiled once
      per (K, shapes);
    * the **tail loop** — same signature, per-step carry select-gated on a
      ``[K]`` bool ``valid`` mask; compiled lazily the first time a ragged
      window (``n_valid < k``) shows up, then reused for every tail.

    ``donate_window=True`` (default) donates the consumed window alongside
    the state (``donate_argnums=(0, 1)``), releasing its device memory for
    the next staged window; pass ``False`` when cycling a pre-staged pool
    of windows (re-using a donated buffer is an error).

    ``wrap`` is applied to the loop function — signature
    ``(state, window, valid) -> (state, metrics)`` — before ``jax.jit``;
    use it for ``shard_map`` over a mesh (the valid mask is replicated,
    spec ``P()``; the window's leading K axis stays unsharded).
    """

    def __init__(self, step_fn: Callable, k: int, *,
                 wrap: Optional[Callable] = None,
                 donate_window: bool = True):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self._step_fn = step_fn
        self._wrap = wrap
        donate = (0, 1) if donate_window else (0,)
        self.donate_window = donate_window

        chained = chain_steps(step_fn)

        def hot(state, window, valid):
            del valid                     # full window: nothing to mask
            return chained(state, window)

        def masked_step(state, xs):
            batch, valid = xs
            new_state, metrics = step_fn(state, batch)
            # Padded steps run (same program, no retrace) but their state
            # update is gated out, so the carry leaving the window is
            # exactly the carry after the last REAL step.
            return _select_tree(valid, new_state, state), metrics

        def tail(state, window, valid):
            return jax.lax.scan(masked_step, state, (window, valid))

        if wrap is not None:
            hot, tail = wrap(hot), wrap(tail)
        #: the hot-window jitted callable — one compile per (K, shape);
        #: wrap in ``prof.assert_trace_count`` to pin that.
        self.loop = jax.jit(hot, donate_argnums=donate)
        #: the ragged-tail jitted callable (compiled on first tail, ever).
        self.tail_loop = jax.jit(tail, donate_argnums=donate)
        self._full_valid = np.ones((self.k,), np.bool_)

    def step_window(self, state, window, n_valid: Optional[int] = None):
        """Dispatch one window: K steps, ONE program.

        ``window`` is the batch pytree stacked on a leading K axis;
        ``n_valid`` (default K) marks a ragged tail — only the first
        ``n_valid`` steps advance the state, the padded remainder is
        select-gated out on device.  Returns ``(state, metrics)`` with
        per-step metrics stacked ``[K]`` as DEVICE arrays (no host sync;
        read them through :class:`DeferredMetrics`).
        """
        if n_valid is None or n_valid >= self.k:
            return self._dispatch(self.loop, state, window, self._full_valid)
        if n_valid < 1:
            raise ValueError(f"n_valid must be >= 1, got {n_valid}")
        valid = np.arange(self.k) < n_valid      # [K] bool, shape-stable
        return self._dispatch(self.tail_loop, state, window, valid)

    def _dispatch(self, loop, state, window, valid):
        if not self.donate_window:
            return loop(state, window, valid)
        with warnings.catch_warnings():
            # The window rarely matches an output aval, so backends
            # without XLA buffer-donor support warn that the donation
            # was "not usable" at compile time; where the feature exists
            # (current TPU jaxlibs) the donation releases the window's
            # HBM for reuse while the loop runs.  The intent is
            # deliberate either way — keep the compile log clean.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return loop(state, window, valid)

    def run(self, state, windows: Iterable, *,
            on_metrics: Optional[Callable] = None):
        """Drive the pipeline over ``(window, n_valid)`` pairs (the
        :func:`stage_windows` protocol).  ``on_metrics``, when given, is
        called with a :class:`WindowMetrics` one dispatch behind the hot
        loop.  Returns ``(state, reader)``; ``reader.last()`` drains the
        final window's metrics."""
        reader = DeferredMetrics()
        for window, n_valid in windows:
            state, metrics = self.step_window(state, window, n_valid)
            prev = reader.push(metrics, n_valid)
            if prev is not None and on_metrics is not None:
                on_metrics(prev)
        if on_metrics is not None and reader.newest() is not None:
            on_metrics(reader.newest())
        return state, reader


class WindowMetrics(NamedTuple):
    """One window's stacked per-step metrics, still on device.

    ``step`` is the global index of the window's FIRST step; ``n_valid``
    how many leading entries are real (a ragged tail pads to K).
    ``fetch()`` is the one sanctioned host transfer — a single stacked
    device->host read of everything the window recorded."""
    step: int
    n_valid: int
    metrics: Any

    def fetch(self):
        """ONE batched device->host transfer of this window's metrics
        (each leaf arrives as a host array stacked ``[K]``; entries past
        ``n_valid`` are padding)."""
        return jax.device_get(self.metrics)  # jaxlint: disable=J001 -- the deferred reader's contract: one batched transfer, one dispatch behind the hot loop


class DeferredMetrics:
    """One-dispatch-behind metric reader.

    ``push`` stores the window just dispatched and returns the PREVIOUS
    window's :class:`WindowMetrics` — device handles only, no transfer.
    The caller fetches (``.fetch()``) at its own cadence; because the
    fetch always trails the newest dispatch by one window, the device is
    already executing window N while the host waits on window N-1's
    values, so the hot loop never drains the pipeline on a scalar.
    ``last()`` reads the final window at shutdown (this one DOES wait for
    the device — it is the end-of-training drain)."""

    def __init__(self):
        self._held: Optional[WindowMetrics] = None
        self._behind: Optional[WindowMetrics] = None
        self._next_step = 0

    def push(self, metrics, n_valid: int) -> Optional[WindowMetrics]:
        """Record a freshly dispatched window; returns the previous
        window's handles (or None on the first push)."""
        self._behind = self._held
        self._held = WindowMetrics(self._next_step, n_valid, metrics)
        self._next_step += n_valid
        return self._behind

    def behind(self) -> Optional[WindowMetrics]:
        """The window one dispatch behind the newest (unfetched view)."""
        return self._behind

    def newest(self) -> Optional[WindowMetrics]:
        """The most recently pushed window (fetching it waits for the
        device to finish it — end-of-loop use only)."""
        return self._held

    def last(self) -> Optional[Any]:
        """Fetch the NEWEST window's metrics (host values).  Blocks until
        the device finishes it — call once, after the loop."""
        if self._held is None:
            return None
        return self._held.fetch()

    @property
    def steps_pushed(self) -> int:
        return self._next_step


def window_batches(batches: Iterable, k: int, *,
                   transform: Optional[Callable] = None,
                   pad_tail: bool = True) -> Iterator:
    """Group a per-step batch stream into host-stacked ``[k, ...]``
    windows; yields ``(window, n_valid)``.

    A final ragged group is padded to ``k`` by repeating its last batch
    (``n_valid`` marks the real count; :class:`StepPipeline` gates the
    padding out on device) — or dropped when ``pad_tail=False``, the
    ``drop_last`` analog.  ``transform`` runs per BATCH before stacking
    (decode/normalize), on the caller's thread — wrap the result in
    :class:`apex_tpu.data.PrefetchLoader` (or use :func:`stage_windows`)
    to move it off the hot loop.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    for group in _group_batches(batches, k, pad_tail):
        yield _assemble_window(group, k, transform)


def _assemble_window(group, k: int, transform: Optional[Callable]):
    """One window from one ``_group_batches`` group: per-batch
    ``transform``, tail pad with the TRANSFORMED last batch (padding
    before the transform would re-run the whole decode/augment ``k - n``
    extra times), host stack.  Shared by :func:`window_batches` (caller
    thread) and :func:`stage_windows` (worker pool) so the two paths
    cannot diverge."""
    items, n_valid = group
    if transform is not None:
        items = [transform(b) for b in items]
    if len(items) < k:
        items = items + [items[-1]] * (k - len(items))
    window = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *items)
    return window, n_valid


def _group_batches(batches: Iterable, k: int, pad_tail: bool) -> Iterator:
    """Group a batch stream into ``(list of <= k raw items, n_valid)``
    pairs WITHOUT transforming, padding, or stacking — cheap enough to
    sit under the :class:`~apex_tpu.data.PrefetchLoader` source lock;
    the heavy per-window assembly (and the tail pad, AFTER the
    transform, so the transform runs exactly once per source batch) is
    the worker pool's job (see :func:`stage_windows`)."""
    buf = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield buf, k
            buf = []
    if buf and pad_tail:
        yield buf, len(buf)


def stage_windows(batches: Iterable, k: int, *,
                  transform: Optional[Callable] = None,
                  pad_tail: bool = True, depth: int = 2,
                  device=None, workers: int = 1):
    """Window assembly + device staging through the multi-worker
    :class:`apex_tpu.data.PrefetchLoader` input engine: ``workers``
    threads each assemble WHOLE ``[k, ...]`` windows ahead (per-batch
    ``transform`` — decode/augment/normalize — plus the host stack, in
    parallel, no per-batch barrier), and the staging thread
    ``jax.device_put``s finished windows so the host->device DMA of
    window N+1 overlaps the device loop of window N (the reference
    ``data_prefetcher``'s stream-overlap, at window granularity).
    ``device`` may be a ``Sharding`` — e.g.
    ``NamedSharding(mesh, P(None, "data"))`` to shard the per-step batch
    axis while the leading K axis stays unsharded.

    Returns the :class:`~apex_tpu.data.PrefetchLoader` itself — iterate
    it for ``(window, n_valid)`` pairs with ``window`` already on device
    (fresh buffers, safe to donate under
    ``StepPipeline(donate_window=True)``); read ``.stats.snapshot()``
    for the queue-depth / producer-stall / consumer-wait counters
    (``loader_stall_pct``, the number ``bench.py`` reports per example);
    and ``close()`` it (or use it as a context manager) to
    deterministically release the worker threads and any staged device
    windows when abandoning the stream early.
    """
    from .data import PrefetchLoader

    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    # PrefetchLoader device_puts every leaf with a .shape — the window
    # arrays — and passes the plain-int n_valid through untouched.
    return PrefetchLoader(_group_batches(batches, k, pad_tail),
                          depth=depth, device=device,
                          transform=lambda g: _assemble_window(
                              g, k, transform),
                          workers=workers)
