"""Step-pipelining runtime: K-step device loops over staged batch windows.

BENCH r05 measured the gap this module closes: the chip finishes a
ResNet-50 amp-O2 step in 46.9 ms but the per-step jitted wall time is
52.3 ms (~10% pure dispatch), and the flagship examples were far worse
(imagenet held 1529 img/s against a 2492 img/s best window; DCGAN 4.67
it/s against 57).  The reference hides the same class of overhead with
CUDA-stream prefetch (``examples/imagenet/main_amp.py`` ``data_prefetcher``)
and per-step kernel fusion; the TPU-native answer is to make the *program*
— not the step — the unit of host dispatch:

* :class:`StepPipeline` runs K jitted train steps per host dispatch as ONE
  compiled ``lax.scan`` over a stacked ``[K, ...]`` batch window, donating
  both the carried state and the consumed window;
* :func:`stage_windows` groups a per-step batch stream into such windows
  and stages them through :class:`apex_tpu.data.PrefetchLoader`, so the
  host->device transfer of window N+1 overlaps the device loop of window N
  (the ``data_prefetcher`` analog, one level up);
* :class:`DeferredMetrics` holds each window's per-step metrics as DEVICE
  arrays and hands reads back one dispatch behind, so the hot loop never
  blocks on a scalar — by the time window N-1's metrics are fetched,
  window N is already enqueued and the device keeps working through the
  round-trip.

Ragged epoch tails (a final window with fewer than K real batches) and
mid-window dynamic-loss-scale skips are handled WITHOUT retracing: the
tail is padded to the same ``[K, ...]`` shape and executed by a separate
masked program (compiled once, ever) whose per-step carry is select-gated
on a ``valid`` mask, and the scaler's overflow flag never leaves the
device (``multi_tensor`` keeps it a traced scalar).  The hot-window
program therefore compiles exactly once per (K, shape) — pin it with
:func:`apex_tpu.prof.assert_trace_count`.

Usage::

    from apex_tpu import runtime

    pipe = runtime.StepPipeline(step_fn, k=16)
    windows = runtime.stage_windows(batch_stream, k=16,
                                    transform=normalize)
    reader = runtime.DeferredMetrics()
    for window, n_valid in windows:
        state, metrics = pipe.step_window(state, window, n_valid)
        prev = reader.push(metrics, n_valid)
        if prev is not None and want_to_print(prev.step):
            host = prev.fetch()            # one stacked transfer, one
            ...                            # dispatch behind the device

    final = reader.last()                  # drains the pipeline

For SPMD runs pass ``wrap`` — a callable (e.g. a ``shard_map`` partial)
applied to the loop function ``(state, window, valid) -> (state, metrics)``
before ``jax.jit``; the window's leading K axis stays unsharded.
"""

from __future__ import annotations

import signal as _signal
import threading
import time
import warnings
from typing import Any, Callable, Iterable, Iterator, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry as _telemetry
from .training import chain_steps

__all__ = ["StepPipeline", "DeferredMetrics", "WindowMetrics",
           "GracefulShutdown", "stage_windows", "window_batches"]


class GracefulShutdown:
    """Preemption drain: SIGTERM/SIGINT request a clean stop at the next
    window boundary (ISSUE 9).

    A fleet preempts with a signal and a deadline; today that signal
    kills the loop mid-window and loses everything since the last
    checkpoint.  Installed around the training loop, this handler turns
    the FIRST signal into a *drain request* the loop polls at each
    window boundary — finish the in-flight window, write the final
    checkpoint, flush the recorder summary and the watchdog health line
    (the examples' ``finally``-flushed recorders already prove that
    half), then exit cleanly.  A SECOND signal escalates to the default
    handling (the operator insists), so a wedged drain can still be
    killed interactively.

    Usage (the examples' default)::

        with runtime.GracefulShutdown() as stop:
            for window, n_valid in windows:
                state, metrics = pipe.step_window(state, window, n_valid)
                if stop.draining:
                    mgr.save(step, state, block=True)   # final checkpoint
                    break

    Thread-safe: the drain flag is a ``threading.Event`` (signals land
    on the main thread; the loop may poll from anywhere).  With a
    telemetry recorder active, the request emits a ``drain`` event
    carrying the signal name.  Outside the main thread (where
    ``signal.signal`` raises), installation degrades to a no-op handler
    set and :meth:`request` remains the programmatic trigger.
    """

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT), *,
                 telemetry=None):
        self.signals = tuple(signals)
        self._telemetry = telemetry
        self._drain = threading.Event()
        self._prev: dict = {}
        self._installed = False
        self.reason: Optional[str] = None

    # -- the flag -----------------------------------------------------------
    @property
    def draining(self) -> bool:
        """True once a drain has been requested (signal or programmatic)."""
        return self._drain.is_set()

    def request(self, reason: str = "programmatic") -> None:
        """Trigger the drain without a signal (tests, schedulers)."""
        first = not self._drain.is_set()
        self.reason = self.reason or reason
        self._drain.set()
        if first:
            rec = (self._telemetry if self._telemetry is not None
                   else _telemetry.get_recorder())
            if rec is not None:
                rec.event("drain", reason=reason)

    # -- signal plumbing ----------------------------------------------------
    def _handler(self, signum, frame):
        del frame
        try:
            name = _signal.Signals(signum).name
        except ValueError:        # pragma: no cover - exotic signum
            name = str(signum)
        if self._drain.is_set():
            # Second signal: the operator insists — restore the previous
            # disposition and re-raise so default handling (KeyboardInterrupt
            # / termination) takes over instead of a wedged drain.
            self.uninstall()
            _signal.raise_signal(signum)
            return
        self.request(f"signal:{name}")

    def install(self) -> "GracefulShutdown":
        """Install the handlers (idempotent).  Returns ``self``."""
        if self._installed:
            return self
        for sig in self.signals:
            try:
                self._prev[sig] = _signal.signal(sig, self._handler)
            except (ValueError, OSError):   # non-main thread / platform
                continue
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the previous handlers (idempotent)."""
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):   # pragma: no cover
                continue
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "GracefulShutdown":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def _select_tree(flag, new, old):
    """Per-leaf ``where(flag, new, old)`` — the carry gate for masked
    (padded) steps.  ``flag`` is a traced bool scalar, so the whole tail
    window runs data-dependently with zero retraces."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(flag, n, o), new, old)


class _AotLoop:
    """One dispatch through a warmed AOT executable, with jit fallback.

    The compiled executable rejects arguments whose sharding/layout
    drifted from the warmed signature; on such a failure the stale
    entry is dropped and the dispatch retries through the jit path
    (which traces/compiles as usual), so a bad warmup can cost at most
    one compile — never a crash.  Only argument-VALIDATION errors
    (ValueError/TypeError, raised before donation takes effect, so the
    fallback re-uses the same live buffers) are treated as drift;
    genuine runtime failures (device OOM, deleted buffers) propagate —
    silently re-running them through a fresh compile would mask the
    error AND double the damage."""

    def __init__(self, pipe, key, compiled, jit_loop):
        self._pipe, self._key = pipe, key
        self._compiled, self._jit = compiled, jit_loop

    def __call__(self, state, window, valid):
        try:
            return self._compiled(state, window, valid)
        except (ValueError, TypeError):
            self._pipe._aot.pop(self._key, None)
            return self._jit(state, window, valid)


class StepPipeline:
    """K train steps per host dispatch, as one compiled device loop.

    ``step_fn(state, batch) -> (state, metrics)`` is the usual fully-jitted
    amp step (:func:`apex_tpu.training.make_train_step`).  The pipeline
    compiles it into ``lax.scan`` over a ``[K, ...]``-stacked batch window
    (:func:`apex_tpu.training.chain_steps`) so host dispatch, argument
    marshalling, and metric plumbing cost once per K steps.

    Two programs back one pipeline:

    * the **hot loop** — full windows, no masking overhead, compiled once
      per (K, shapes);
    * the **tail loop** — same signature, per-step carry select-gated on a
      ``[K]`` bool ``valid`` mask; compiled lazily the first time a ragged
      window (``n_valid < k``) shows up, then reused for every tail.

    ``donate_window=True`` (default) donates the consumed window alongside
    the state (``donate_argnums=(0, 1)``), releasing its device memory for
    the next staged window; pass ``False`` when cycling a pre-staged pool
    of windows (re-using a donated buffer is an error).

    ``wrap`` is applied to the loop function — signature
    ``(state, window, valid) -> (state, metrics)`` — before ``jax.jit``;
    use it for ``shard_map`` over a mesh (the valid mask is replicated,
    spec ``P()``; the window's leading K axis stays unsharded).
    """

    def __init__(self, step_fn: Callable, k: int, *,
                 wrap: Optional[Callable] = None,
                 donate_window: bool = True,
                 telemetry=None):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self._step_fn = step_fn
        self._wrap = wrap
        donate = (0, 1) if donate_window else (0,)
        self.donate_window = donate_window
        # Telemetry (ISSUE 5): an explicit Recorder pins this pipeline to
        # it; None defers to telemetry.get_recorder() per dispatch, so a
        # recorder installed mid-run is picked up.  With no recorder the
        # dispatch path below is byte-for-byte the uninstrumented one.
        self._telemetry = telemetry
        self._steps_done = 0          # global step index for events
        self._t_last_dispatch: Optional[float] = None
        self._traces_seen = {"hot": 0, "tail": 0}
        self._sigs_seen = {"hot": set(), "tail": set()}

        chained = chain_steps(step_fn)

        def hot(state, window, valid):
            del valid                     # full window: nothing to mask
            return chained(state, window)

        def masked_step(state, xs):
            batch, valid = xs
            new_state, metrics = step_fn(state, batch)
            # Padded steps run (same program, no retrace) but their state
            # update is gated out, so the carry leaving the window is
            # exactly the carry after the last REAL step.
            return _select_tree(valid, new_state, state), metrics

        def tail(state, window, valid):
            return jax.lax.scan(masked_step, state, (window, valid))

        if wrap is not None:
            hot, tail = wrap(hot), wrap(tail)
        #: the hot-window jitted callable — one compile per (K, shape);
        #: wrap in ``prof.assert_trace_count`` to pin that.
        self.loop = jax.jit(hot, donate_argnums=donate)
        #: the ragged-tail jitted callable (compiled on first tail, ever).
        self.tail_loop = jax.jit(tail, donate_argnums=donate)
        self._full_valid = np.ones((self.k,), np.bool_)
        # AOT-warmed executables (ISSUE 7): (program, window signature)
        # -> compiled, installed by warmup(); step_window dispatches to
        # them directly, bypassing jit tracing entirely.
        self._aot: dict = {}
        # (state, window) ShapeDtypeStruct templates captured at the
        # first dispatch — memory_stats()'s relower fallback when no
        # AOT executable holds the compiled program (ISSUE 10).
        self._mem_template = None

    def warmup(self, state, window, *, tail: bool = False):
        """AOT-compile the device loop for this ``(state, window)``
        signature BEFORE step 0 (``apex_tpu.cache.warmup``:
        ``lower().compile()`` over abstract shapes — nothing runs,
        nothing is donated, ``state``/``window`` may be live arrays or
        ``ShapeDtypeStruct`` templates).  Subsequent ``step_window``
        calls with matching windows dispatch straight to the compiled
        executable: zero traces and zero compiles after step 0 (pin
        with ``prof.assert_trace_count(pipe.loop, 0)``), and the call-1
        donated-sharding re-specialization never happens because the
        jit cache is never consulted.  ``tail=True`` also pre-compiles
        the masked ragged-tail program.  With
        :func:`apex_tpu.cache.enable` the backend compiles are disk
        hits on the second process start.  Returns ``self``.
        """
        from . import cache as _cache
        sig = _cache.signature(window)
        self._aot[("hot", sig)] = _cache.warmup(
            self.loop, state, window, self._full_valid)
        if tail:
            self._aot[("tail", sig)] = _cache.warmup(
                self.tail_loop, state, window, self._full_valid)
        return self

    def step_window(self, state, window, n_valid: Optional[int] = None):
        """Dispatch one window: K steps, ONE program.

        ``window`` is the batch pytree stacked on a leading K axis;
        ``n_valid`` (default K) marks a ragged tail — only the first
        ``n_valid`` steps advance the state, the padded remainder is
        select-gated out on device.  Returns ``(state, metrics)`` with
        per-step metrics stacked ``[K]`` as DEVICE arrays (no host sync;
        read them through :class:`DeferredMetrics`).
        """
        if n_valid is None or n_valid >= self.k:
            loop, valid, n, program = (self.loop, self._full_valid,
                                       self.k, "hot")
        else:
            if n_valid < 1:
                raise ValueError(f"n_valid must be >= 1, got {n_valid}")
            # [K] bool, shape-stable
            loop, valid, n, program = (self.tail_loop,
                                       np.arange(self.k) < n_valid,
                                       n_valid, "tail")
        if self._aot:
            # Warm-start fast path: a warmed (program, window-signature)
            # dispatches to the AOT executable — no tracing machinery at
            # all.  A mismatch (e.g. input sharding drift vs the warmed
            # layout) drops the stale entry and falls back to the jit
            # path, which handles anything.
            from . import cache as _cache
            key = (program, _cache.signature(window))
            aot = self._aot.get(key)
            if aot is not None:
                loop = _AotLoop(self, key, aot, loop)
        if self._mem_template is None:
            sds = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype)
                if hasattr(l, "shape") and hasattr(l, "dtype") else l,
                (state, window))
            self._mem_template = sds
        step0 = self._steps_done
        self._steps_done += n
        rec = (self._telemetry if self._telemetry is not None
               else _telemetry.get_recorder())
        if rec is None:
            return self._dispatch(loop, state, window, valid)
        t0 = time.perf_counter()
        gap = (0.0 if self._t_last_dispatch is None
               else t0 - self._t_last_dispatch)
        out = self._dispatch(loop, state, window, valid)
        t1 = time.perf_counter()
        self._t_last_dispatch = t1
        self._note_retrace(rec, loop, program, window, step0, dur=t1 - t0)
        # dur is the host DISPATCH time (async — the device may still be
        # running); gap is host time since the previous dispatch returned
        # (metric fetches, loader waits, python glue).
        rec.event("window", step=step0, k=self.k, n_valid=n,
                  dur=round(t1 - t0, 6), gap=round(gap, 6),
                  program=program)
        rec.metrics.histogram("window_dispatch_s").observe(t1 - t0)
        rec.metrics.histogram("window_gap_s").observe(gap)
        rec.metrics.counter("steps_dispatched").inc(n)
        # live steps/s gauge for the Prometheus exporter (ISSUE 10):
        # host-clock arithmetic on numbers already in hand — the rate
        # the host actually sustained across the last dispatch cycle.
        rec.metrics.gauge("steps_per_s").set(
            n / max(t1 - t0 + gap, 1e-9))
        return out

    def memory_stats(self, *, emit: bool = True) -> Optional[dict]:
        """Peak-HBM ledger of the compiled hot loop (ISSUE 10): the
        byte dict of :func:`apex_tpu.prof.memory.stats_from_analysis`
        (argument/output/temp/generated/peak), or None when nothing was
        dispatched yet or the jax in use exposes no
        ``memory_analysis``.

        Cost model: a :meth:`warmup`-ed pipeline already HOLDS the
        compiled executable, so this is a pure host read; without AOT
        the hot program is re-lowered from the first dispatch's
        shape templates (seconds of host work at exit time — with
        :func:`apex_tpu.cache.enable` the backend compile is a disk
        hit).  ``emit=True`` also records the ``memory`` event +
        ``peak_hbm_bytes`` gauge on the active recorder, which is what
        the examples' exit ``health:`` line and the ``memory_headroom``
        watchdog rule read."""
        from .prof import memory as _memory

        stats = None
        for (program, _sig), compiled in self._aot.items():
            if program != "hot":
                continue
            try:
                stats = _memory.stats_from_analysis(
                    compiled.memory_analysis())  # jaxlint: disable=J010 -- exit-time host read of an ALREADY-compiled AOT executable (no retrace/recompile); the loop stops at the first usable result
            except Exception:
                stats = None
            if stats:
                break
        if stats is None and self._mem_template is not None:
            state_sds, window_sds = self._mem_template
            try:
                compiled = self.loop.lower(
                    state_sds, window_sds, self._full_valid).compile()
                stats = _memory.stats_from_analysis(
                    compiled.memory_analysis())
            except Exception:
                stats = None
        if stats is None:
            return None
        stats["source"] = "memory_analysis"
        if emit:
            rec = (self._telemetry if self._telemetry is not None
                   else _telemetry.get_recorder())
            if rec is not None:
                _memory.record_memory(rec, stats)
        return stats

    def _note_retrace(self, rec, loop, program: str, window,
                      step0: int, dur: float = 0.0) -> None:
        """Emit a ``retrace`` event when this dispatch grew the jit
        tracing cache, keyed by the window's shape signature (one int
        compare per dispatch; the signature is only built on growth).

        ``first`` marks the program's initial compile; ``new_sig``
        distinguishes a TRUE retrace (a window shape/dtype signature
        never traced before — the J004 bug class) from the known-benign
        call-1 re-specialization, where jit re-caches on the donated
        state's returned sharding with the SAME signature.  Only
        not-first + new-sig growth increments the ``retraces`` counter
        the analyzer and bench gate on.

        ``dur`` is the dispatch duration of the call that grew the
        cache — trace+compile time plus the enqueue, i.e. the compile
        share of the steady-vs-best-window gap.  The timeline analyzer
        sums it into ``retraces.compile_s`` and the roofline ledger's
        gap attribution reads it (ISSUE 6)."""
        try:
            size = loop._cache_size()
        except Exception:
            return
        prev = self._traces_seen.get(program, 0)
        if size <= prev:
            return
        self._traces_seen[program] = size
        leaves = jax.tree_util.tree_leaves(window)
        sig = "|".join(f"{getattr(l, 'dtype', type(l).__name__)}"
                       f"{list(getattr(l, 'shape', ()))}"
                       for l in leaves[:16])
        new_sig = sig not in self._sigs_seen[program]
        self._sigs_seen[program].add(sig)
        rec.event("retrace", program=program, step=step0,
                  n_traces=size, first=(prev == 0), new_sig=new_sig,
                  sig=sig, dur=round(dur, 6))
        if prev > 0 and new_sig:
            rec.metrics.counter("retraces").inc()

    def _dispatch(self, loop, state, window, valid):
        if not self.donate_window:
            return loop(state, window, valid)
        with warnings.catch_warnings():
            # The window rarely matches an output aval, so backends
            # without XLA buffer-donor support warn that the donation
            # was "not usable" at compile time; where the feature exists
            # (current TPU jaxlibs) the donation releases the window's
            # HBM for reuse while the loop runs.  The intent is
            # deliberate either way — keep the compile log clean.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return loop(state, window, valid)

    def run(self, state, windows: Iterable, *,
            on_metrics: Optional[Callable] = None):
        """Drive the pipeline over ``(window, n_valid)`` pairs (the
        :func:`stage_windows` protocol).  ``on_metrics``, when given, is
        called with a :class:`WindowMetrics` one dispatch behind the hot
        loop.  Returns ``(state, reader)``; ``reader.last()`` drains the
        final window's metrics."""
        reader = DeferredMetrics(telemetry=self._telemetry)
        for window, n_valid in windows:
            state, metrics = self.step_window(state, window, n_valid)
            prev = reader.push(metrics, n_valid)
            if prev is not None and on_metrics is not None:
                on_metrics(prev)
        if on_metrics is not None:
            for wm in reader.flush():   # the final in-flight window
                on_metrics(wm)
        return state, reader


class WindowMetrics(NamedTuple):
    """One window's stacked per-step metrics, still on device.

    ``step`` is the global index of the window's FIRST step; ``n_valid``
    how many leading entries are real (a ragged tail pads to K).
    ``fetch()`` is the one sanctioned host transfer — a single stacked
    device->host read of everything the window recorded."""
    step: int
    n_valid: int
    metrics: Any
    #: optional telemetry Recorder: fetch() reports the transfer to it
    #: (the piggyback point — telemetry reads ride THIS fetch, never a
    #: fetch of their own).
    telemetry: Any = None

    def fetch(self):
        """ONE batched device->host transfer of this window's metrics
        (each leaf arrives as a host array stacked ``[K]``; entries past
        ``n_valid`` are padding)."""
        if self.telemetry is None:
            return jax.device_get(self.metrics)  # jaxlint: disable=J001 -- the deferred reader's contract: one batched transfer, one dispatch behind the hot loop
        import time as _time
        t0 = _time.perf_counter()
        vals = jax.device_get(self.metrics)  # jaxlint: disable=J001 -- same sanctioned transfer as above, timed for the telemetry stream
        self.telemetry.observe_window_metrics(
            self.step, self.n_valid, vals, _time.perf_counter() - t0)
        return vals


class DeferredMetrics:
    """One-dispatch-behind metric reader.

    ``push`` stores the window just dispatched and returns the PREVIOUS
    window's :class:`WindowMetrics` — device handles only, no transfer.
    The caller fetches (``.fetch()``) at its own cadence; because the
    fetch always trails the newest dispatch by one window, the device is
    already executing window N while the host waits on window N-1's
    values, so the hot loop never drains the pipeline on a scalar.
    At loop exit, :meth:`flush` (or ``last()``) drains the final
    in-flight window — every pushed window is handed back exactly once
    between ``push`` returns and one ``flush``, so no metrics window is
    silently dropped (ISSUE 5 satellite).

    ``telemetry`` pins a Recorder whose ``observe_window_metrics`` rides
    each window's fetch; None defers to the active recorder at push
    time."""

    def __init__(self, telemetry=None):
        self._held: Optional[WindowMetrics] = None
        self._behind: Optional[WindowMetrics] = None
        self._next_step = 0
        self._telemetry = telemetry
        self._flushed = False

    def push(self, metrics, n_valid: int) -> Optional[WindowMetrics]:
        """Record a freshly dispatched window; returns the previous
        window's handles (or None on the first push)."""
        rec = (self._telemetry if self._telemetry is not None
               else _telemetry.get_recorder())
        self._behind = self._held
        self._held = WindowMetrics(self._next_step, n_valid, metrics, rec)
        self._next_step += n_valid
        self._flushed = False
        return self._behind

    def behind(self) -> Optional[WindowMetrics]:
        """The window one dispatch behind the newest (unfetched view)."""
        return self._behind

    def newest(self) -> Optional[WindowMetrics]:
        """The most recently pushed window (fetching it waits for the
        device to finish it — end-of-loop use only)."""
        return self._held

    def flush(self) -> list:
        """Drain the reader: return every window ``push`` has not yet
        handed back — exactly the newest in-flight one (each earlier
        window was returned by its successor's ``push``).  Returns
        ``[WindowMetrics]`` (handles; call ``.fetch()`` to read), or
        ``[]`` when already drained / nothing was pushed.  Call at loop
        exit so the final window's metrics are never silently dropped;
        idempotent until the next ``push``."""
        if self._held is None or self._flushed:
            return []
        self._flushed = True
        return [self._held]

    def last(self) -> Optional[Any]:
        """Fetch the NEWEST window's metrics (host values).  Blocks until
        the device finishes it — call once, after the loop.  Equivalent
        to ``flush()`` + fetch, and marks the reader drained."""
        if self._held is None:
            return None
        self._flushed = True
        return self._held.fetch()

    @property
    def steps_pushed(self) -> int:
        return self._next_step


def window_batches(batches: Iterable, k: int, *,
                   transform: Optional[Callable] = None,
                   pad_tail: bool = True) -> Iterator:
    """Group a per-step batch stream into host-stacked ``[k, ...]``
    windows; yields ``(window, n_valid)``.

    A final ragged group is padded to ``k`` by repeating its last batch
    (``n_valid`` marks the real count; :class:`StepPipeline` gates the
    padding out on device) — or dropped when ``pad_tail=False``, the
    ``drop_last`` analog.  ``transform`` runs per BATCH before stacking
    (decode/normalize), on the caller's thread — wrap the result in
    :class:`apex_tpu.data.PrefetchLoader` (or use :func:`stage_windows`)
    to move it off the hot loop.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    for group in _group_batches(batches, k, pad_tail):
        yield _assemble_window(group, k, transform)


def _assemble_window(group, k: int, transform: Optional[Callable]):
    """One window from one ``_group_batches`` group: per-batch
    ``transform``, tail pad with the TRANSFORMED last batch (padding
    before the transform would re-run the whole decode/augment ``k - n``
    extra times), host stack.  Shared by :func:`window_batches` (caller
    thread) and :func:`stage_windows` (worker pool) so the two paths
    cannot diverge."""
    items, n_valid = group
    if transform is not None:
        items = [transform(b) for b in items]
    if len(items) < k:
        items = items + [items[-1]] * (k - len(items))
    window = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *items)
    return window, n_valid


def _group_batches(batches: Iterable, k: int, pad_tail: bool) -> Iterator:
    """Group a batch stream into ``(list of <= k raw items, n_valid)``
    pairs WITHOUT transforming, padding, or stacking — cheap enough to
    sit under the :class:`~apex_tpu.data.PrefetchLoader` source lock;
    the heavy per-window assembly (and the tail pad, AFTER the
    transform, so the transform runs exactly once per source batch) is
    the worker pool's job (see :func:`stage_windows`)."""
    buf = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield buf, k
            buf = []
    if buf and pad_tail:
        yield buf, len(buf)


def stage_windows(batches: Iterable, k: int, *,
                  transform: Optional[Callable] = None,
                  pad_tail: bool = True, depth: int = 2,
                  device=None, workers: int = 1):
    """Window assembly + device staging through the multi-worker
    :class:`apex_tpu.data.PrefetchLoader` input engine: ``workers``
    threads each assemble WHOLE ``[k, ...]`` windows ahead (per-batch
    ``transform`` — decode/augment/normalize — plus the host stack, in
    parallel, no per-batch barrier), and the staging thread
    ``jax.device_put``s finished windows so the host->device DMA of
    window N+1 overlaps the device loop of window N (the reference
    ``data_prefetcher``'s stream-overlap, at window granularity).
    ``device`` may be a ``Sharding`` — e.g.
    ``NamedSharding(mesh, P(None, "data"))`` to shard the per-step batch
    axis while the leading K axis stays unsharded — or a
    :class:`~apex_tpu.parallel.mesh.MeshPlan`, whose
    ``window_sharding()`` (leading K unsharded, batch over dp×fsdp) is
    used so the loader's placement can never drift from the step's.

    Returns the :class:`~apex_tpu.data.PrefetchLoader` itself — iterate
    it for ``(window, n_valid)`` pairs with ``window`` already on device
    (fresh buffers, safe to donate under
    ``StepPipeline(donate_window=True)``); read ``.stats.snapshot()``
    for the queue-depth / producer-stall / consumer-wait counters
    (``loader_stall_pct``, the number ``bench.py`` reports per example);
    and ``close()`` it (or use it as a context manager) to
    deterministically release the worker threads and any staged device
    windows when abandoning the stream early.
    """
    from .data import PrefetchLoader

    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if hasattr(device, "window_sharding"):      # a MeshPlan (ISSUE 12)
        device = device.window_sharding()
    # PrefetchLoader device_puts every leaf with a .shape — the window
    # arrays — and passes the plain-int n_valid through untouched.
    return PrefetchLoader(_group_batches(batches, k, pad_tail),
                          depth=depth, device=device,
                          transform=lambda g: _assemble_window(
                              g, k, transform),
                          workers=workers)
