"""Pure functional optimizer updates over parameter pytrees.

These are the TPU-native equivalents of the reference's multi-tensor CUDA
functors (``csrc/multi_tensor_adam.cu``, ``multi_tensor_sgd_kernel.cu``,
``multi_tensor_lamb.cu``, ``multi_tensor_novograd.cu``): the whole model
updates in ONE compiled XLA program (the "one or a few kernel launches"
capability), with fp32 math regardless of storage dtype and an optional
``apply_mask`` implementing loss-scale step skipping as a device-side select
instead of host-controlled flow.

Internally each update flattens the pytrees to leaf lists — the direct analog
of the reference's tensor lists — computes per-leaf updates, and unflattens.
Each function is shaped like an optax update: ``(grads, state, params) ->
(new_params, new_state)``, jit/vmap/shard_map-safe, no Python control flow on
traced values.

**Bucketed mode** (ISSUE 4): pass ``store=BucketStore(params)`` and every
update runs over a few large per-dtype flat buffers instead of one subgraph
per leaf — O(buckets) HLO ops and jit arguments for deep pytrees.  The
optimizer state is then held as :class:`~apex_tpu.multi_tensor.buckets.
Packed` buckets (a valid scan carry / donation target); ``params`` and
``grads`` may be pytrees (packed/unpacked inside the program) or already-
``Packed`` values (kept packed, for callers that hold masters as buckets
across steps).  The elementwise math is performed in the identical order
per element, so the fp32 bucketed Adam/SGD trajectories are **bitwise**
equal to the leafwise ones; LAMB/NovoGrad per-tensor norms use segment
reductions whose accumulation order differs harmlessly (allclose).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..multi_tensor import multi_tensor_l2norm
from ..multi_tensor.buckets import BucketStore, Packed


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def _pack_args(store: BucketStore, grads, params):
    """Route (grads, params) through ``store``: returns fp32 grad buckets,
    param buckets (native dtype), and whether params arrived Packed (the
    caller then gets Packed params back)."""
    was_packed = isinstance(params, Packed)
    p_in = params if was_packed else store.pack(params)
    # Grads are consumed in fp32 whatever their storage dtype (the
    # leafwise ``_f32(g)`` cast) — pack them straight into fp32 buckets.
    g_in = (grads if isinstance(grads, Packed)
            else store.pack(grads, dtype=jnp.float32))
    return g_in, p_in, was_packed


def _bucket_masked(mask, new_data, old_packed: Packed) -> tuple:
    if mask is None:
        return tuple(new_data)
    return tuple(jnp.where(mask, n, jnp.asarray(o, n.dtype))
                 for n, o in zip(new_data, old_packed.data))


def _flatten(params, *other_trees):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    others = [jax.tree_util.tree_leaves(t) for t in other_trees]
    return treedef, leaves, others


def _masked(mask, new_leaves, old_tree):
    """new where mask (scalar bool), old otherwise — the step-skip select."""
    old_leaves = jax.tree_util.tree_leaves(old_tree)
    if mask is None:
        return new_leaves
    return [jnp.where(mask, n, jnp.asarray(o, n.dtype))
            for n, o in zip(new_leaves, old_leaves)]


def _count_step(step, mask):
    return step + (1 if mask is None else jnp.where(mask, 1, 0))


# -- Adam ---------------------------------------------------------------------

class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def adam_init(params, *, store: Optional[BucketStore] = None) -> AdamState:
    if store is not None:
        return AdamState(step=jnp.int32(0), exp_avg=store.zeros(),
                         exp_avg_sq=store.zeros())
    z = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return AdamState(step=jnp.int32(0), exp_avg=z(), exp_avg_sq=z())


def _bucket_adam_update(grads, state, params, *, store, lr, beta1, beta2,
                        eps, weight_decay, adam_w_mode, bias_correction,
                        grad_scale, apply_mask):
    """O(buckets) Adam: one fused elementwise sweep per (dtype, decay)
    bucket; bitwise-equal per element to the leafwise path."""
    step = _count_step(state.step, apply_mask)
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0
    g_in, p_in, was_packed = _pack_args(store, grads, params)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v, decay in zip(g_in.data, p_in.data, state.exp_avg.data,
                                 state.exp_avg_sq.data, store.decay_flags):
        wd = weight_decay if decay else 0.0
        g = jnp.asarray(g, jnp.float32) / grad_scale
        p32 = _f32(p)
        if not adam_w_mode and wd != 0.0:
            g = g + wd * p32
        m_n = beta1 * m + (1.0 - beta1) * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if adam_w_mode and wd != 0.0:
            update = update + wd * p32
        new_p.append((p32 - lr * update).astype(p.dtype))
        new_m.append(m_n)
        new_v.append(v_n)
    out = Packed(data=_bucket_masked(apply_mask, new_p, p_in),
                 rest=p_in.rest)
    return (out if was_packed else store.unpack(out),
            AdamState(step=step,
                      exp_avg=Packed(_bucket_masked(apply_mask, new_m,
                                                    state.exp_avg), ()),
                      exp_avg_sq=Packed(_bucket_masked(apply_mask, new_v,
                                                       state.exp_avg_sq),
                                        ())))


def adam_update(grads, state: AdamState, params, *,
                lr, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
                adam_w_mode=True, bias_correction=True, grad_scale=1.0,
                apply_mask=None, store: Optional[BucketStore] = None):
    """Fused Adam/AdamW (reference ``csrc/multi_tensor_adam.cu:23-127``:
    ADAM_MODE_0 = L2 regularization, ADAM_MODE_1 = decoupled AdamW; host-side
    bias corrections ``:131-171``).  fp32 math; params may be any float dtype.

    ``store`` switches to the O(buckets) flat-buffer path (state held as
    ``Packed`` buckets, created by ``adam_init(params, store=store)``).
    """
    if store is not None:
        return _bucket_adam_update(
            grads, state, params, store=store, lr=lr, beta1=beta1,
            beta2=beta2, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, bias_correction=bias_correction,
            grad_scale=grad_scale, apply_mask=apply_mask)
    step = _count_step(state.step, apply_mask)
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if not adam_w_mode and weight_decay != 0.0:
            g = g + weight_decay * p32
        m_n = beta1 * m + (1.0 - beta1) * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p32
        new_p.append((p32 - lr * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            AdamState(step=step, exp_avg=treedef.unflatten(new_m),
                      exp_avg_sq=treedef.unflatten(new_v)))


# -- SGD ----------------------------------------------------------------------

class SGDState(NamedTuple):
    momentum_buf: Any
    initialized: jnp.ndarray


def sgd_init(params, momentum=0.0, *,
             store: Optional[BucketStore] = None) -> SGDState:
    if store is not None:
        return SGDState(momentum_buf=store.zeros(),
                        initialized=jnp.asarray(False))
    buf = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return SGDState(momentum_buf=buf, initialized=jnp.asarray(False))


def _bucket_sgd_update(grads, state, params, *, store, lr, momentum,
                       dampening, nesterov, weight_decay, wd_after_momentum,
                       grad_scale, apply_mask):
    first_run = jnp.logical_not(state.initialized)
    g_in, p_in, was_packed = _pack_args(store, grads, params)
    new_p, new_m = [], []
    for g, p, m, decay in zip(g_in.data, p_in.data, state.momentum_buf.data,
                              store.decay_flags):
        wd = weight_decay if decay else 0.0
        g = jnp.asarray(g, jnp.float32) / grad_scale
        p32 = _f32(p)
        if wd != 0.0 and not wd_after_momentum:
            g = g + wd * p32
        if momentum != 0.0:
            m_n = jnp.where(first_run, g, momentum * m + (1.0 - dampening) * g)
            d = g + momentum * m_n if nesterov else m_n
        else:
            m_n = m
            d = g
        if wd != 0.0 and wd_after_momentum:
            d = d + wd * p32
        new_p.append((p32 - lr * d).astype(p.dtype))
        new_m.append(m_n)
    out = Packed(data=_bucket_masked(apply_mask, new_p, p_in),
                 rest=p_in.rest)
    initialized = jnp.logical_or(
        state.initialized,
        jnp.asarray(True) if apply_mask is None else apply_mask)
    return (out if was_packed else store.unpack(out),
            SGDState(momentum_buf=Packed(
                         _bucket_masked(apply_mask, new_m,
                                        state.momentum_buf), ()),
                     initialized=initialized))


def sgd_update(grads, state: SGDState, params, *,
               lr, momentum=0.0, dampening=0.0, nesterov=False,
               weight_decay=0.0, wd_after_momentum=False, grad_scale=1.0,
               apply_mask=None, store: Optional[BucketStore] = None):
    """Fused SGD (reference ``csrc/multi_tensor_sgd_kernel.cu:141-278``):
    weight decay, momentum, dampening, nesterov, ``first_run`` momentum
    initialization, ``wd_after_momentum`` and fused ``1/scale`` grad scaling,
    all inside the single compiled update.  ``store`` routes the sweep
    through O(buckets) flat buffers.
    """
    if store is not None:
        return _bucket_sgd_update(
            grads, state, params, store=store, lr=lr, momentum=momentum,
            dampening=dampening, nesterov=nesterov,
            weight_decay=weight_decay, wd_after_momentum=wd_after_momentum,
            grad_scale=grad_scale, apply_mask=apply_mask)
    first_run = jnp.logical_not(state.initialized)

    treedef, ps, (gs, ms) = _flatten(params, grads, state.momentum_buf)
    new_p, new_m = [], []
    for g, p, m in zip(gs, ps, ms):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if weight_decay != 0.0 and not wd_after_momentum:
            g = g + weight_decay * p32
        if momentum != 0.0:
            m_n = jnp.where(first_run, g, momentum * m + (1.0 - dampening) * g)
            d = g + momentum * m_n if nesterov else m_n
        else:
            m_n = m
            d = g
        if weight_decay != 0.0 and wd_after_momentum:
            d = d + weight_decay * p32
        new_p.append((p32 - lr * d).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.momentum_buf)
    initialized = jnp.logical_or(
        state.initialized,
        jnp.asarray(True) if apply_mask is None else apply_mask)
    return (treedef.unflatten(new_p),
            SGDState(momentum_buf=treedef.unflatten(new_m),
                     initialized=initialized))


# -- LAMB ---------------------------------------------------------------------

class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def lamb_init(params, *, store: Optional[BucketStore] = None) -> LambState:
    if store is not None:
        return LambState(step=jnp.int32(0), exp_avg=store.zeros(),
                         exp_avg_sq=store.zeros())
    z = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return LambState(step=jnp.int32(0), exp_avg=z(), exp_avg_sq=z())


def _bucket_lamb_update(grads, state, params, *, store, lr, beta1, beta2,
                        eps, weight_decay, adam_w_mode, bias_correction,
                        grad_averaging, max_grad_norm, use_nvlamb,
                        grad_scale, apply_mask):
    """O(buckets) LAMB: stage 1 (global clip + moment EMAs + update
    vector) is one elementwise sweep per bucket; stage 2's per-tensor
    trust ratios come from ONE segment reduction per bucket over the
    index map instead of two reductions per leaf."""
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0

    g_in, p_in, was_packed = _pack_args(store, grads, params)
    gs = [jnp.asarray(g, jnp.float32) / grad_scale for g in g_in.data]
    # Global gradient norm for clipping: one reduction per bucket.
    gnorm = jnp.sqrt(jnp.sum(jnp.stack(
        [jnp.sum(jnp.square(g)) for g in gs])))
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm, 1.0)
    else:
        clip = 1.0

    # Stage 1: moments + Adam-style update vector, per bucket.
    p32s, ups, new_m, new_v = [], [], [], []
    for g, p, m, v, decay in zip(gs, p_in.data, state.exp_avg.data,
                                 state.exp_avg_sq.data, store.decay_flags):
        wd = weight_decay if decay else 0.0
        g = g / clip
        p32 = _f32(p)
        m_n = beta1 * m + beta3 * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if wd != 0.0:
            update = update + wd * p32
        p32s.append(p32)
        ups.append(update)
        new_m.append(m_n)
        new_v.append(v_n)

    # Stage 2: per-tensor trust ratios via segment reductions.
    p_sq = store.per_leaf_sq_sums(p32s)
    u_sq = store.per_leaf_sq_sums(ups)
    new_p = []
    for bi, (p, p32, update) in enumerate(zip(p_in.data, p32s, ups)):
        p_norm = jnp.sqrt(p_sq[bi])
        u_norm = jnp.sqrt(u_sq[bi])
        if use_nvlamb:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / u_norm, 1.0)
        ratio_e = store.spread(bi, ratio)
        new_p.append((p32 - lr * ratio_e * update).astype(p.dtype))

    out = Packed(data=_bucket_masked(apply_mask, new_p, p_in),
                 rest=p_in.rest)
    return (out if was_packed else store.unpack(out),
            LambState(step=step,
                      exp_avg=Packed(_bucket_masked(apply_mask, new_m,
                                                    state.exp_avg), ()),
                      exp_avg_sq=Packed(_bucket_masked(apply_mask, new_v,
                                                       state.exp_avg_sq),
                                        ())))


def lamb_update(grads, state: LambState, params, *,
                lr, beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
                adam_w_mode=True, bias_correction=True, grad_averaging=True,
                max_grad_norm=1.0, use_nvlamb=False, grad_scale=1.0,
                apply_mask=None, store: Optional[BucketStore] = None):
    """Fused LAMB (reference ``csrc/multi_tensor_lamb.cu:29-289``):

    stage 1 — global grad-norm clip (l2norm over ALL grads), m/v update,
    per-tensor Adam-style update vector; stage 2 — per-tensor trust ratio
    ``|p| / |update|`` scales the step.  ``use_nvlamb`` applies the trust
    ratio even when a tensor's param norm is zero.  ``store`` routes both
    stages through O(buckets) flat buffers (trust ratios from segment
    reductions over the index map).
    """
    if store is not None:
        return _bucket_lamb_update(
            grads, state, params, store=store, lr=lr, beta1=beta1,
            beta2=beta2, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, bias_correction=bias_correction,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb, grad_scale=grad_scale,
            apply_mask=apply_mask)
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    gs = [_f32(g) / grad_scale for g in gs]
    # Global gradient norm for clipping (reference: one l2norm over all grads).
    gnorm = multi_tensor_l2norm(gs)
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm, 1.0)
    else:
        clip = 1.0

    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = g / clip
        p32 = _f32(p)
        m_n = beta1 * m + beta3 * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if weight_decay != 0.0:
            update = update + weight_decay * p32
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        if use_nvlamb:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
        new_p.append((p32 - lr * ratio * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            LambState(step=step, exp_avg=treedef.unflatten(new_m),
                      exp_avg_sq=treedef.unflatten(new_v)))


# -- NovoGrad -----------------------------------------------------------------

class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any           # per-element first moment
    exp_avg_sq: Any        # per-TENSOR scalar second moment (norm, not squared)


def novograd_init(params, *,
                  store: Optional[BucketStore] = None) -> NovoGradState:
    if store is not None:
        # exp_avg_sq: one scalar per tensor — [n_leaves_in_bucket] arrays
        # carried in a Packed container (never unpacked to the tree).
        return NovoGradState(
            step=jnp.int32(0), exp_avg=store.zeros(),
            exp_avg_sq=Packed(
                data=tuple(jnp.zeros((len(b.leaf_ids),), jnp.float32)
                           for b in store.buckets),
                rest=()))
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    scalars = jax.tree_util.tree_map(lambda p: jnp.float32(0), params)
    return NovoGradState(step=jnp.int32(0), exp_avg=zeros, exp_avg_sq=scalars)


def _bucket_novograd_update(grads, state, params, *, store, lr, beta1,
                            beta2, eps, weight_decay, grad_averaging,
                            norm_type, init_zero, adam_w_mode,
                            bias_correction, grad_scale, apply_mask):
    """O(buckets) NovoGrad: per-tensor grad norms via one segment
    reduction per bucket; the scalar second moments stay as
    ``[n_leaves_in_bucket]`` vectors."""
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0
    first = step == 1

    g_in, p_in, was_packed = _pack_args(store, grads, params)
    gs = [jnp.asarray(g, jnp.float32) / grad_scale for g in g_in.data]
    if norm_type == 2:
        g_norms = [jnp.sqrt(s) for s in store.per_leaf_sq_sums(gs)]
    else:
        g_norms = list(store.per_leaf_max_abs(gs))

    new_p, new_m, new_v = [], [], []
    for bi, (g, p, m, v, decay) in enumerate(
            zip(gs, p_in.data, state.exp_avg.data, state.exp_avg_sq.data,
                store.decay_flags)):
        wd = weight_decay if decay else 0.0
        p32 = _f32(p)
        if init_zero:
            v_n = beta2 * v + (1.0 - beta2) * g_norms[bi]
        else:
            v_n = jnp.where(first, g_norms[bi],
                            beta2 * v + (1.0 - beta2) * g_norms[bi])
        denom = v_n / jnp.sqrt(bc2) + eps if bias_correction else v_n + eps
        scaled_g = g / store.spread(bi, denom)
        if wd != 0.0 and not adam_w_mode:
            scaled_g = scaled_g + wd * p32
        m_n = beta1 * m + beta3 * scaled_g
        update = m_n / bc1
        if wd != 0.0 and adam_w_mode:
            update = update + wd * p32
        new_p.append((p32 - lr * update).astype(p.dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    out = Packed(data=_bucket_masked(apply_mask, new_p, p_in),
                 rest=p_in.rest)
    return (out if was_packed else store.unpack(out),
            NovoGradState(step=step,
                          exp_avg=Packed(_bucket_masked(apply_mask, new_m,
                                                        state.exp_avg), ()),
                          exp_avg_sq=Packed(
                              _bucket_masked(apply_mask, new_v,
                                             state.exp_avg_sq), ())))


def novograd_update(grads, state: NovoGradState, params, *,
                    lr, beta1=0.95, beta2=0.98, eps=1e-8, weight_decay=0.0,
                    grad_averaging=True, norm_type=2, init_zero=False,
                    adam_w_mode=True, bias_correction=False, grad_scale=1.0,
                    apply_mask=None, store: Optional[BucketStore] = None):
    """Fused NovoGrad (reference ``csrc/multi_tensor_novograd.cu`` +
    ``apex/optimizers/fused_novograd.py:157-176``): the second moment is ONE
    SCALAR PER TENSOR — an EMA of the per-tensor grad norm.  First step
    initializes it to the grad norm itself (or zero with ``init_zero``).
    ``store`` routes the norms through per-bucket segment reductions.
    """
    if store is not None:
        return _bucket_novograd_update(
            grads, state, params, store=store, lr=lr, beta1=beta1,
            beta2=beta2, eps=eps, weight_decay=weight_decay,
            grad_averaging=grad_averaging, norm_type=norm_type,
            init_zero=init_zero, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction, grad_scale=grad_scale,
            apply_mask=apply_mask)
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0
    first = step == 1

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if norm_type == 2:
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        else:
            g_norm = jnp.max(jnp.abs(g))
        if init_zero:
            v_n = beta2 * v + (1.0 - beta2) * g_norm
        else:
            v_n = jnp.where(first, g_norm, beta2 * v + (1.0 - beta2) * g_norm)
        denom = v_n / jnp.sqrt(bc2) + eps if bias_correction else v_n + eps
        scaled_g = g / denom
        if weight_decay != 0.0 and not adam_w_mode:
            scaled_g = scaled_g + weight_decay * p32
        m_n = beta1 * m + beta3 * scaled_g
        update = m_n / bc1
        if weight_decay != 0.0 and adam_w_mode:
            update = update + weight_decay * p32
        new_p.append((p32 - lr * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            NovoGradState(step=step, exp_avg=treedef.unflatten(new_m),
                          exp_avg_sq=treedef.unflatten(new_v)))
