"""Pure functional optimizer updates over parameter pytrees.

These are the TPU-native equivalents of the reference's multi-tensor CUDA
functors (``csrc/multi_tensor_adam.cu``, ``multi_tensor_sgd_kernel.cu``,
``multi_tensor_lamb.cu``, ``multi_tensor_novograd.cu``): the whole model
updates in ONE compiled XLA program (the "one or a few kernel launches"
capability), with fp32 math regardless of storage dtype and an optional
``apply_mask`` implementing loss-scale step skipping as a device-side select
instead of host-controlled flow.

Internally each update flattens the pytrees to leaf lists — the direct analog
of the reference's tensor lists — computes per-leaf updates, and unflattens.
Each function is shaped like an optax update: ``(grads, state, params) ->
(new_params, new_state)``, jit/vmap/shard_map-safe, no Python control flow on
traced values.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ..multi_tensor import multi_tensor_l2norm


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def _flatten(params, *other_trees):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    others = [jax.tree_util.tree_leaves(t) for t in other_trees]
    return treedef, leaves, others


def _masked(mask, new_leaves, old_tree):
    """new where mask (scalar bool), old otherwise — the step-skip select."""
    old_leaves = jax.tree_util.tree_leaves(old_tree)
    if mask is None:
        return new_leaves
    return [jnp.where(mask, n, jnp.asarray(o, n.dtype))
            for n, o in zip(new_leaves, old_leaves)]


def _count_step(step, mask):
    return step + (1 if mask is None else jnp.where(mask, 1, 0))


# -- Adam ---------------------------------------------------------------------

class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def adam_init(params) -> AdamState:
    z = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return AdamState(step=jnp.int32(0), exp_avg=z(), exp_avg_sq=z())


def adam_update(grads, state: AdamState, params, *,
                lr, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
                adam_w_mode=True, bias_correction=True, grad_scale=1.0,
                apply_mask=None):
    """Fused Adam/AdamW (reference ``csrc/multi_tensor_adam.cu:23-127``:
    ADAM_MODE_0 = L2 regularization, ADAM_MODE_1 = decoupled AdamW; host-side
    bias corrections ``:131-171``).  fp32 math; params may be any float dtype.
    """
    step = _count_step(state.step, apply_mask)
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if not adam_w_mode and weight_decay != 0.0:
            g = g + weight_decay * p32
        m_n = beta1 * m + (1.0 - beta1) * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p32
        new_p.append((p32 - lr * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            AdamState(step=step, exp_avg=treedef.unflatten(new_m),
                      exp_avg_sq=treedef.unflatten(new_v)))


# -- SGD ----------------------------------------------------------------------

class SGDState(NamedTuple):
    momentum_buf: Any
    initialized: jnp.ndarray


def sgd_init(params, momentum=0.0) -> SGDState:
    buf = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return SGDState(momentum_buf=buf, initialized=jnp.asarray(False))


def sgd_update(grads, state: SGDState, params, *,
               lr, momentum=0.0, dampening=0.0, nesterov=False,
               weight_decay=0.0, wd_after_momentum=False, grad_scale=1.0,
               apply_mask=None):
    """Fused SGD (reference ``csrc/multi_tensor_sgd_kernel.cu:141-278``):
    weight decay, momentum, dampening, nesterov, ``first_run`` momentum
    initialization, ``wd_after_momentum`` and fused ``1/scale`` grad scaling,
    all inside the single compiled update.
    """
    first_run = jnp.logical_not(state.initialized)

    treedef, ps, (gs, ms) = _flatten(params, grads, state.momentum_buf)
    new_p, new_m = [], []
    for g, p, m in zip(gs, ps, ms):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if weight_decay != 0.0 and not wd_after_momentum:
            g = g + weight_decay * p32
        if momentum != 0.0:
            m_n = jnp.where(first_run, g, momentum * m + (1.0 - dampening) * g)
            d = g + momentum * m_n if nesterov else m_n
        else:
            m_n = m
            d = g
        if weight_decay != 0.0 and wd_after_momentum:
            d = d + weight_decay * p32
        new_p.append((p32 - lr * d).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.momentum_buf)
    initialized = jnp.logical_or(
        state.initialized,
        jnp.asarray(True) if apply_mask is None else apply_mask)
    return (treedef.unflatten(new_p),
            SGDState(momentum_buf=treedef.unflatten(new_m),
                     initialized=initialized))


# -- LAMB ---------------------------------------------------------------------

class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def lamb_init(params) -> LambState:
    z = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    return LambState(step=jnp.int32(0), exp_avg=z(), exp_avg_sq=z())


def lamb_update(grads, state: LambState, params, *,
                lr, beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
                adam_w_mode=True, bias_correction=True, grad_averaging=True,
                max_grad_norm=1.0, use_nvlamb=False, grad_scale=1.0,
                apply_mask=None):
    """Fused LAMB (reference ``csrc/multi_tensor_lamb.cu:29-289``):

    stage 1 — global grad-norm clip (l2norm over ALL grads), m/v update,
    per-tensor Adam-style update vector; stage 2 — per-tensor trust ratio
    ``|p| / |update|`` scales the step.  ``use_nvlamb`` applies the trust
    ratio even when a tensor's param norm is zero.
    """
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    gs = [_f32(g) / grad_scale for g in gs]
    # Global gradient norm for clipping (reference: one l2norm over all grads).
    gnorm = multi_tensor_l2norm(gs)
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm, 1.0)
    else:
        clip = 1.0

    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = g / clip
        p32 = _f32(p)
        m_n = beta1 * m + beta3 * g
        v_n = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if weight_decay != 0.0:
            update = update + weight_decay * p32
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        if use_nvlamb:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
        new_p.append((p32 - lr * ratio * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            LambState(step=step, exp_avg=treedef.unflatten(new_m),
                      exp_avg_sq=treedef.unflatten(new_v)))


# -- NovoGrad -----------------------------------------------------------------

class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any           # per-element first moment
    exp_avg_sq: Any        # per-TENSOR scalar second moment (norm, not squared)


def novograd_init(params) -> NovoGradState:
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    scalars = jax.tree_util.tree_map(lambda p: jnp.float32(0), params)
    return NovoGradState(step=jnp.int32(0), exp_avg=zeros, exp_avg_sq=scalars)


def novograd_update(grads, state: NovoGradState, params, *,
                    lr, beta1=0.95, beta2=0.98, eps=1e-8, weight_decay=0.0,
                    grad_averaging=True, norm_type=2, init_zero=False,
                    adam_w_mode=True, bias_correction=False, grad_scale=1.0,
                    apply_mask=None):
    """Fused NovoGrad (reference ``csrc/multi_tensor_novograd.cu`` +
    ``apex/optimizers/fused_novograd.py:157-176``): the second moment is ONE
    SCALAR PER TENSOR — an EMA of the per-tensor grad norm.  First step
    initializes it to the grad norm itself (or zero with ``init_zero``).
    """
    step = _count_step(state.step, apply_mask)
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** _f32(step)
        bc2 = 1.0 - beta2 ** _f32(step)
    else:
        bc1 = bc2 = 1.0
    first = step == 1

    treedef, ps, (gs, ms, vs) = _flatten(params, grads, state.exp_avg,
                                         state.exp_avg_sq)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        g = _f32(g) / grad_scale
        p32 = _f32(p)
        if norm_type == 2:
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        else:
            g_norm = jnp.max(jnp.abs(g))
        if init_zero:
            v_n = beta2 * v + (1.0 - beta2) * g_norm
        else:
            v_n = jnp.where(first, g_norm, beta2 * v + (1.0 - beta2) * g_norm)
        denom = v_n / jnp.sqrt(bc2) + eps if bias_correction else v_n + eps
        scaled_g = g / denom
        if weight_decay != 0.0 and not adam_w_mode:
            scaled_g = scaled_g + weight_decay * p32
        m_n = beta1 * m + beta3 * scaled_g
        update = m_n / bc1
        if weight_decay != 0.0 and adam_w_mode:
            update = update + weight_decay * p32
        new_p.append((p32 - lr * update).astype(jnp.asarray(p).dtype))
        new_m.append(m_n)
        new_v.append(v_n)

    new_p = _masked(apply_mask, new_p, params)
    new_m = _masked(apply_mask, new_m, state.exp_avg)
    new_v = _masked(apply_mask, new_v, state.exp_avg_sq)
    return (treedef.unflatten(new_p),
            NovoGradState(step=step, exp_avg=treedef.unflatten(new_m),
                          exp_avg_sq=treedef.unflatten(new_v)))
