"""FusedSGD — momentum/dampening/nesterov SGD, whole-model single program.

Reference: ``apex/optimizers/fused_sgd.py:6-217``.  The reference's marquee
trick — ``materialize_master_grads=False``, a depth-4 kernel that reads fp16
model grads and updates fp32 masters + fp16 model copies in one pass with the
unscale fused in (``:139-214``) — is the *default* here: when amp-wired with
master weights, ``step`` consumes the scaled bf16 grads directly and fuses
``1/most_recent_scale`` into the compiled update, so master grads are never
materialized.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import FusedOptimizer
from . import functional as F
from ..amp import policy as _policy


class FusedSGD(FusedOptimizer):
    def __init__(self, params, lr=1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False, materialize_master_grads=True,
                 set_grad_none=False, bucketed=False):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero "
                             "dampening")
        defaults = dict(lr=lr, momentum=momentum, dampening=dampening,
                        weight_decay=weight_decay, nesterov=nesterov,
                        wd_after_momentum=wd_after_momentum)
        self.materialize_master_grads = materialize_master_grads
        # Scaler handshake (reference fused_sgd.py most_recent_scale /
        # scale_set_by_backward): lets the update fuse the unscale.
        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        super().__init__(params, defaults, bucketed=bucketed)

    def _init_state(self, params, group=None):
        momentum = (group or self.defaults)["momentum"]
        return F.sgd_init(params, momentum,
                          store=(group or {}).get("_store"))

    def _update(self, grads, state, params, *, group, lr, grad_scale,
                apply_mask):
        d = group
        return F.sgd_update(
            grads, state, params, lr=lr, momentum=d["momentum"],
            dampening=d["dampening"], nesterov=d["nesterov"],
            weight_decay=d["weight_decay"],
            wd_after_momentum=d["wd_after_momentum"],
            grad_scale=grad_scale, apply_mask=apply_mask,
            store=d.get("_store"))

    def _post_amp_backward(self, loss_scaler):
        if not self.materialize_master_grads and self.master_params is not None:
            # Fused path: keep the scaled model-dtype grads; record the scale
            # so step() divides inside the kernel (reference :139-214).
            if self._pending_grads is None:
                return
            if self._stashed_grads is not None:
                # Accumulation still needs the fp32 sum.
                self._master_grads, _ = loss_scaler.unscale_with_stashed(
                    self._pending_grads, self._stashed_grads)
                self._stashed_grads = None
                self._pending_grads = None
                self.most_recent_scale = 1.0
                self.scale_set_by_backward = True
                return
            self._master_grads = self._pending_grads
            self._pending_grads = None
            self.most_recent_scale = loss_scaler.loss_scale()
            self.scale_set_by_backward = True
            # Overflow check still must happen (device-side).
            _, _ = loss_scaler.unscale(self._master_grads,
                                       scale=jnp.float32(self.most_recent_scale))
            return
        super()._post_amp_backward(loss_scaler)

    def step(self, grads=None, closure=None):
        # Deferred overflow flags must be read BEFORE the fast-path gate:
        # scale_loss no longer arms _skip_next_step eagerly (the flag read
        # is batched here), so the latch is still False at this point
        # when an overflow is pending.
        self._resolve_pending_overflows()
        if (grads is None and not self.materialize_master_grads
                and self.master_params is not None
                and self._master_grads is not None and not self._skip_next_step):
            if closure is not None:
                closure()
            scale = jnp.float32(self.most_recent_scale)
            new_params, self.state = self._run_update(
                self._to_groups(self._master_grads), self._masters, scale)
            self._masters = new_params
            self._set_group_params(self._masters_to_model())
            self._master_grads = None
            self.most_recent_scale = 1.0
            self.scale_set_by_backward = False
            return self.params
        result = super().step(grads=grads, closure=closure)
        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        return result
