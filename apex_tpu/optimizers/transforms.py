"""optax-compatible GradientTransformation wrappers.

The functional updates in ``functional.py`` return new params directly (the
fused formulation).  These wrappers adapt them to optax's
``(updates, state, params) -> (updates, state)`` protocol so apex_tpu
optimizers drop into existing optax/flax training loops::

    tx = apex_tpu.optimizers.fused_adam(lr=1e-3, weight_decay=0.01)
    opt_state = tx.init(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)

``lr`` may be a float or an optax-style schedule ``step -> lr``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

from . import functional as F

ScalarOrSchedule = Union[float, Callable]


def _lr_at(lr: ScalarOrSchedule, step):
    return lr(step) if callable(lr) else jnp.float32(lr)


def _delta(new_params, params):
    return jax.tree_util.tree_map(
        lambda n, p: (jnp.asarray(n, jnp.float32)
                      - jnp.asarray(p, jnp.float32)).astype(jnp.asarray(p).dtype),
        new_params, params)


def _make(update_fn, init_fn, lr, kwargs):
    def init(params):
        return init_fn(params)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("apex_tpu fused transforms require params")
        new_params, new_state = update_fn(
            grads, state, params, lr=_lr_at(lr, state.step), **kwargs)
        return _delta(new_params, params), new_state

    return optax.GradientTransformation(init, update)


def fused_adam(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
               adam_w_mode=True, bias_correction=True):
    return _make(F.adam_update, F.adam_init, lr,
                 dict(beta1=beta1, beta2=beta2, eps=eps,
                      weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                      bias_correction=bias_correction))


def fused_lamb(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
               adam_w_mode=True, bias_correction=True, grad_averaging=True,
               max_grad_norm=1.0, use_nvlamb=False):
    return _make(F.lamb_update, F.lamb_init, lr,
                 dict(beta1=beta1, beta2=beta2, eps=eps,
                      weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                      bias_correction=bias_correction,
                      grad_averaging=grad_averaging,
                      max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb))


def fused_novograd(lr=1e-3, beta1=0.95, beta2=0.98, eps=1e-8,
                   weight_decay=0.0, grad_averaging=True, norm_type=2,
                   init_zero=False, adam_w_mode=True, bias_correction=False):
    return _make(F.novograd_update, F.novograd_init, lr,
                 dict(beta1=beta1, beta2=beta2, eps=eps,
                      weight_decay=weight_decay, grad_averaging=grad_averaging,
                      norm_type=norm_type, init_zero=init_zero,
                      adam_w_mode=adam_w_mode, bias_correction=bias_correction))


class _SGDWrapperState(NamedTuple):
    inner: F.SGDState
    step: jnp.ndarray


def fused_sgd(lr=1e-3, momentum=0.0, dampening=0.0, weight_decay=0.0,
              nesterov=False, wd_after_momentum=False):
    def init(params):
        return _SGDWrapperState(inner=F.sgd_init(params, momentum),
                                step=jnp.int32(0))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("apex_tpu fused transforms require params")
        new_params, inner = F.sgd_update(
            grads, state.inner, params, lr=_lr_at(lr, state.step),
            momentum=momentum, dampening=dampening, nesterov=nesterov,
            weight_decay=weight_decay, wd_after_momentum=wd_after_momentum)
        return _delta(new_params, params), _SGDWrapperState(
            inner=inner, step=state.step + 1)

    return optax.GradientTransformation(init, update)
