"""FusedLAMB — layerwise adaptive large-batch optimizer.

Reference: ``apex/optimizers/fused_lamb.py:4-175`` — global grad norm
computed over all grads, per-tensor trust ratio inside the fused kernel.
"""

from __future__ import annotations

from .base import FusedOptimizer
from . import functional as F


class FusedLAMB(FusedOptimizer):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0, use_nvlamb=False,
                 bucketed=False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        adam_w_mode=adam_w_mode, grad_averaging=grad_averaging,
                        max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb)
        super().__init__(params, defaults, bucketed=bucketed)

    def _init_state(self, params, group=None):
        return F.lamb_init(params, store=(group or {}).get("_store"))

    def _update(self, grads, state, params, *, group, lr, grad_scale,
                apply_mask):
        d = group
        return F.lamb_update(
            grads, state, params, lr=lr,
            beta1=d["betas"][0], beta2=d["betas"][1], eps=d["eps"],
            weight_decay=d["weight_decay"], adam_w_mode=d["adam_w_mode"],
            bias_correction=d["bias_correction"],
            grad_averaging=d["grad_averaging"],
            max_grad_norm=d["max_grad_norm"], use_nvlamb=d["use_nvlamb"],
            grad_scale=grad_scale, apply_mask=apply_mask,
            store=d.get("_store"))
