"""FusedAdam — Adam/AdamW with the whole-model single-program update.

Reference: ``apex/optimizers/fused_adam.py:5-134`` (multi_tensor_adam launch,
``adam_w_mode`` decoupled weight decay default True, no AMSGrad/sparse).
"""

from __future__ import annotations

from .base import FusedOptimizer
from . import functional as F


class FusedAdam(FusedOptimizer):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True,
                 bucketed=False):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant (reference parity).")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        adam_w_mode=adam_w_mode)
        super().__init__(params, defaults, bucketed=bucketed)

    def _init_state(self, params, group=None):
        return F.adam_init(params, store=(group or {}).get("_store"))

    def _update(self, grads, state, params, *, group, lr, grad_scale,
                apply_mask):
        d = group
        return F.adam_update(
            grads, state, params, lr=lr,
            beta1=d["betas"][0], beta2=d["betas"][1], eps=d["eps"],
            weight_decay=d["weight_decay"], adam_w_mode=d["adam_w_mode"],
            bias_correction=d["bias_correction"], grad_scale=grad_scale,
            apply_mask=apply_mask, store=d.get("_store"))
