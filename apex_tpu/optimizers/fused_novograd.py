"""FusedNovoGrad — NovoGrad with per-tensor scalar second moments.

Reference: ``apex/optimizers/fused_novograd.py:4-210`` — ``exp_avg_sq`` is one
float per tensor (a norm EMA, not squared), initialized from the first step's
grad norm or zero; L2 or inf norm modes.
"""

from __future__ import annotations

from .base import FusedOptimizer
from . import functional as F


class FusedNovoGrad(FusedOptimizer):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                 amsgrad=False, reg_inside_moment=False, grad_averaging=True,
                 norm_type=2, init_zero=False, set_grad_none=True,
                 bucketed=False):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        if norm_type not in (2, float("inf"), "inf"):
            raise RuntimeError("FusedNovoGrad only supports l2/inf norm")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging,
                        norm_type=2 if norm_type == 2 else 0,
                        init_zero=init_zero,
                        reg_inside_moment=reg_inside_moment)
        super().__init__(params, defaults, bucketed=bucketed)

    def _init_state(self, params, group=None):
        return F.novograd_init(params, store=(group or {}).get("_store"))

    def _update(self, grads, state, params, *, group, lr, grad_scale,
                apply_mask):
        d = group
        return F.novograd_update(
            grads, state, params, lr=lr,
            beta1=d["betas"][0], beta2=d["betas"][1], eps=d["eps"],
            weight_decay=d["weight_decay"],
            grad_averaging=d["grad_averaging"],
            norm_type=2 if d["norm_type"] == 2 else "inf",
            init_zero=d["init_zero"],
            adam_w_mode=not d["reg_inside_moment"],
            bias_correction=d["bias_correction"],
            grad_scale=grad_scale, apply_mask=apply_mask,
            store=d.get("_store"))
