"""FP16_Optimizer (fused-flavor, legacy) — flat master-weight wrapper.

Reference: ``apex/optimizers/fp16_optimizer.py:4-250``: wraps FusedAdam with
flat bf16 param groups + flat fp32 masters; ``backward(loss)`` scales;
``step`` computes the flat grad norm (−1 ⇒ overflow ⇒ skip + dynamic scale
update) and applies the flat update.  Here "flat" is the pytree itself — XLA
already fuses — but the grad-norm/overflow/skip state machine is identical.

In JAX ``backward(loss)`` cannot run autodiff by side effect, so
``backward`` accepts the gradients of the *unscaled* loss times the current
``loss_scale`` (use ``value_and_grad`` helper), mirroring the legacy flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..amp.loss_scaler import LossScaler
from ..amp import policy as _policy
from ..multi_tensor import multi_tensor_l2norm, tree_finite


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=True):
        self.optimizer = init_optimizer
        # Masters: fp32 copies of the wrapped optimizer's params.
        self.fp16_params = init_optimizer.params
        self.fp32_masters = _policy.make_master(self.fp16_params)
        init_optimizer.params = self.fp32_masters
        if getattr(init_optimizer, "bucketed", False):
            # The update target just changed dtype (reduced-precision
            # model params -> fp32 masters): rebuild each group's bucket
            # store so bucket dtypes key on what step() actually packs.
            from ..multi_tensor.buckets import BucketStore
            for g in init_optimizer.param_groups:
                g["_store"] = BucketStore(g["params"])
            init_optimizer._jit_update = None
        init_optimizer.state = [
            init_optimizer._init_state(p, g) for p, g in
            zip(init_optimizer._to_groups(self.fp32_masters),
                init_optimizer.param_groups)]

        if dynamic_loss_scale:
            args = dynamic_loss_args or {}
            self.loss_scaler = LossScaler("dynamic", **args)
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self._grads = None
        self.overflow = False
        self.first_closure_call_this_step = True

    # -- API ----------------------------------------------------------------
    def value_and_grad(self, loss_fn, *args, **kwargs):
        """Compute (loss, grads-of-scaled-loss) w.r.t. the bf16 params."""
        def scaled(p, *a, **k):
            return self.loss_scaler.scale_loss(loss_fn(p, *a, **k))
        loss, grads = jax.value_and_grad(scaled)(self.fp16_params, *args, **kwargs)
        return loss / self.loss_scaler.state.loss_scale, grads

    def backward(self, grads, update_master_grads=True):
        if self._grads is None:
            self._grads = grads
        else:
            self._grads = jax.tree_util.tree_map(jnp.add, self._grads, grads)
        if update_master_grads:
            self.update_master_grads()

    def update_master_grads(self):
        if self._grads is None:
            return
        self._master_grads, _ = self.loss_scaler.unscale(self._grads)
        self._grads = None

    def _compute_grad_norm(self, grads):
        """Flat grad norm; returns −1 on overflow
        (reference ``fp16_optimizer.py:105-130``)."""
        norm = multi_tensor_l2norm(grads)
        finite = tree_finite(grads)
        return jnp.where(finite, norm, -1.0)

    def clip_master_grads(self, max_norm, norm_type=2):
        if getattr(self, "_master_grads", None) is None:
            return 0.0
        norm = float(jax.device_get(multi_tensor_l2norm(self._master_grads)))  # jaxlint: disable=J001 -- reference clip_master_grads returns a Python float norm
        if norm > max_norm and norm > 0:
            coef = max_norm / (norm + 1e-6)
            self._master_grads = jax.tree_util.tree_map(
                lambda g: g * coef, self._master_grads)
        return norm

    def step(self, closure=None):
        grads = getattr(self, "_master_grads", None)
        if grads is None:
            raise ValueError("step() before backward()/update_master_grads()")
        norm = jax.device_get(self._compute_grad_norm(grads))  # jaxlint: disable=J001 -- legacy FP16_Optimizer contract: Python-level skip decision per step (one sync); the jitted path is make_train_step
        norm_overflow = bool(norm == -1.0)    # host value, already fetched
        # Skip coherence (reference fp16_optimizer.py:176-194): the step is
        # gated on the scaler's recorded overflow AND the norm check, and the
        # dynamic scale update sees the combined decision — an overflow found
        # by either mechanism both skips the step and backs the scale off.
        if self.loss_scaler.dynamic:
            if norm_overflow:
                self.loss_scaler.state = self.loss_scaler.state._replace(
                    overflow=jnp.asarray(True))
            should_skip = self.loss_scaler.update_scale_sync()
        else:
            should_skip = norm_overflow
        self.overflow = should_skip or norm_overflow
        if self.overflow:
            print("OVERFLOW! Skipping step. Reducing loss scale to {}".format(
                self.loss_scaler.loss_scale()))
            self._master_grads = None
            return
        self.optimizer.step(grads=grads)
        self.fp32_masters = self.optimizer.params
        self.fp16_params = _policy.master_to_model(self.fp32_masters,
                                                   self.fp16_params)
        self._master_grads = None

    def zero_grad(self, set_grads_to_None=False):
        self._grads = None
        self._master_grads = None

    # -- checkpoint ----------------------------------------------------------
    def state_dict(self):
        return {
            "loss_scaler": self.loss_scaler.state_dict(),
            "overflow": self.overflow,
            "optimizer_state_dict": self.optimizer.state_dict(),
            "fp32_masters": jax.device_get(self.fp32_masters),
        }

    def load_state_dict(self, sd):
        self.loss_scaler.load_state_dict(sd["loss_scaler"])
        self.overflow = sd["overflow"]
        self.optimizer.load_state_dict(sd["optimizer_state_dict"])
        self.fp32_masters = jax.tree_util.tree_map(jnp.asarray,
                                                   sd["fp32_masters"])
        self.optimizer.params = self.fp32_masters
        self.fp16_params = _policy.master_to_model(self.fp32_masters,
                                                   self.fp16_params)

    # Properties (reference parity).
    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale()

    @property
    def state(self):
        return self.optimizer.state

    @property
    def param_groups(self):
        return self.optimizer.param_groups
