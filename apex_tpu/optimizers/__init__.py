"""apex_tpu.optimizers — fused optimizers (SURVEY.md §2.3).

Class API (reference parity): ``FusedAdam``, ``FusedLAMB``, ``FusedNovoGrad``,
``FusedSGD``, ``FP16_Optimizer``.  Functional API: ``functional`` module and
optax-style ``fused_adam``/``fused_lamb``/``fused_novograd``/``fused_sgd``.
"""

from .base import FusedOptimizer                      # noqa: F401
from .fused_adam import FusedAdam                     # noqa: F401
from .fused_sgd import FusedSGD                       # noqa: F401
from .fused_lamb import FusedLAMB                     # noqa: F401
from .fused_novograd import FusedNovoGrad             # noqa: F401
from .transforms import (fused_adam, fused_sgd,       # noqa: F401
                         fused_lamb, fused_novograd)
from . import functional                              # noqa: F401
from .fp16_optimizer import FP16_Optimizer            # noqa: F401
