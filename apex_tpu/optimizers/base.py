"""Stateful optimizer base class — the torch-like imperative API.

The idiomatic JAX path is the functional one (``apex_tpu.optimizers.
functional`` / the optax-style transforms in ``transforms.py``); this class
provides the reference's imperative surface (``opt.step()``,
``opt.zero_grad()``, ``state_dict``, multiple ``param_groups``) plus the amp
handshake that reference ``apex/amp/_process_optimizer.py`` injects with
``types.MethodType``:

* ``_amp_wire`` — master-weight setup (fp32 masters when the model params are
  reduced precision; reference ``:28-90``).
* ``_prepare_amp_backward`` / ``_post_amp_backward`` — stash + unscale
  machinery incl. gradient accumulation via fused axpby (reference
  ``:134-241`` and ``post_backward_models_are_masters`` ``:93-131``).
* ``_arm_skip_step`` — the one-shot skip-step latch armed on overflow
  (reference ``handle.py:126-151`` patches ``step``; the latch restores
  itself after one ``step`` call exactly like the patched function).

Parameter groups (reference ``apex/optimizers/fused_adam.py:75-134`` iterates
``param_groups`` with per-group lr/wd/betas): construct with either a params
pytree (one implicit group) or a list of dicts ``[{"params": subtree,
"lr": ..., "weight_decay": ...}, ...]``; per-group hyperparameters override
the defaults.  ``self.params`` (and the grads you pass to ``step``/
``backward``) then has the structure ``[group0_params, group1_params, ...]``.

The actual parameter update is still ONE jitted XLA program per optimizer
(the multi-tensor capability) — the per-group loop happens at trace time.
Learning rates are passed as traced scalars so lr changes never recompile;
other group hyperparameters are compile-time constants (mutating them
triggers one retrace on the next step, matching the rare-change pattern).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from .. import telemetry as _telemetry
from ..amp import policy as _policy
from ..amp._amp_state import maybe_print
from ..multi_tensor.buckets import BucketStore, Packed


def _is_group_list(params) -> bool:
    return (isinstance(params, (list, tuple)) and len(params) > 0
            and all(isinstance(g, dict) and "params" in g for g in params))


class FusedOptimizer:
    """Base: subclasses define ``_init_state(params, group)`` and ``_update``
    (a pure function ``(grads, state, params, group, lr, grad_scale,
    apply_mask) -> (params, state)`` reading static hyperparameters from
    ``group``).

    ``bucketed=True`` (ISSUE 4) switches each group onto the flat-bucket
    engine: optimizer state — and, when amp-wired, the fp32 masters —
    live as a few large per-dtype :class:`Packed` buffers *across* steps,
    so the jitted update's argument list and its HLO op count are
    O(buckets) instead of O(leaves).  The user-facing ``params`` /
    ``master_params`` surfaces still speak pytrees (unpacked in one
    compiled program when read).
    """

    def __init__(self, params, defaults: Dict[str, Any], *,
                 bucketed: bool = False):
        self.defaults = dict(defaults)
        self.bucketed = bool(bucketed)
        self._grouped = _is_group_list(params)
        raw_groups = list(params) if self._grouped else [{"params": params}]
        self.param_groups: List[Dict[str, Any]] = [
            dict(self.defaults, **g) for g in raw_groups]
        if self.bucketed:
            for g in self.param_groups:
                g["_store"] = BucketStore(g["params"])
        self._masters = None           # list of fp32 masters when amp-wired
        self.state = [self._init_state(g["params"], g)
                      for g in self.param_groups]
        self.loss_scaler = None
        self.properties = None
        self._amp_wired = False
        self._skip_next_step = False
        self._pending_overflow_flags = []  # deferred device-side flags
        self._pending_grads = None         # scaled, model-dtype grads
        self._stashed_grads = None         # for grad accumulation
        self._master_grads = None          # unscaled fp32 grads, step() input
        self._jit_update = None
        self._jit_key = None
        self._step_count = 0               # step() calls incl skips (telemetry)

    # -- group plumbing -----------------------------------------------------
    def _to_groups(self, tree):
        """User-facing structure -> canonical per-group list."""
        return list(tree) if self._grouped else [tree]

    def _from_groups(self, lst):
        """Canonical per-group list -> user-facing structure."""
        return list(lst) if self._grouped else lst[0]

    @property
    def params(self):
        """User-facing params: the original pytree for an implicit single
        group, ``[group0_params, ...]`` for grouped construction."""
        return self._from_groups([g["params"] for g in self.param_groups])

    @params.setter
    def params(self, value):
        self._set_group_params(self._to_groups(value))

    def _set_group_params(self, groups_list):
        for g, p in zip(self.param_groups, groups_list):
            g["params"] = p

    @property
    def master_params(self):
        """fp32 masters in the user-facing structure (None unless
        amp-wired with master weights).  Bucket-resident masters are
        unpacked here (one compiled program per store)."""
        if self._masters is None:
            return None
        return self._from_groups([
            g["_store"].unpack_jit(m) if isinstance(m, Packed) else m
            for m, g in zip(self._masters, self.param_groups)])

    @master_params.setter
    def master_params(self, value):
        if value is None:
            self._masters = None
            return
        groups = self._to_groups(value)
        if self.bucketed:
            groups = [m if isinstance(m, Packed)
                      else g["_store"].pack_jit(m, dtype=jnp.float32)
                      for m, g in zip(groups, self.param_groups)]
        self._masters = groups

    def _masters_to_model(self):
        """master -> model copy for every group (reference
        ``_process_optimizer.py:345-356``); bucket-resident masters cast
        at the *bucket* level (one astype per bucket) before unpacking."""
        model = []
        for mp, g in zip(self._masters, self.param_groups):
            if isinstance(mp, Packed):
                model.append(g["_store"].unpack_jit(mp, cast=True))
            else:
                model.append(_policy.master_to_model(mp, g["params"]))
        return model

    def _group_lrs(self):
        return [jnp.float32(g.get("lr", self.defaults.get("lr", 0.0)))
                for g in self.param_groups]

    def _static_key(self):
        def freeze(v):
            if isinstance(v, list):
                return tuple(v)
            return v
        return tuple(
            tuple(sorted((k, freeze(v)) for k, v in g.items()
                         if k not in ("params", "lr")))
            for g in self.param_groups)

    def _run_update(self, grads_groups, targets_groups, grad_scale):
        """The single jitted whole-model update over all groups.  Rebuilds
        the jitted function only when static group hyperparameters change."""
        key = self._static_key()
        if self._jit_update is None or key != self._jit_key:
            hparams = [{k: v for k, v in g.items() if k != "params"}
                       for g in self.param_groups]

            def update_all(grads, states, params, lrs, scale):
                new_p, new_s = [], []
                for g, s, p, h, lr in zip(grads, states, params, hparams,
                                          lrs):
                    np_, ns = self._update(g, s, p, group=h, lr=lr,
                                           grad_scale=scale, apply_mask=None)
                    new_p.append(np_)
                    new_s.append(ns)
                return new_p, new_s

            self._jit_update = jax.jit(update_all)
            self._jit_key = key
        return self._jit_update(grads_groups, self.state, targets_groups,
                                self._group_lrs(), grad_scale)

    def add_param_group(self, group: Dict[str, Any]):
        """Reference ``add_param_group`` patch (``_process_optimizer.py:
        403-479``): appends a group (with master creation when amp-wired)."""
        if not isinstance(group, dict) or "params" not in group:
            raise ValueError("param group must be a dict with a 'params' key")
        if not self._grouped and len(self.param_groups) == 1:
            # Promote to grouped mode: params/grads structures become lists.
            self._grouped = True
        g = dict(self.defaults, **group)
        if self._amp_wired and self.properties is not None:
            # Cast the appended group's params to the model dtype first,
            # like the reference's add_param_group patch
            # (_process_optimizer.py:403-479) — otherwise the new group
            # would silently stay fp32 while the rest runs bf16.
            cast_type = self.properties.cast_model_type
            if (cast_type is not None
                    and jnp.dtype(cast_type) != jnp.dtype(jnp.float32)):
                keep_bn = self.properties.keep_batchnorm_fp32
                keep_bn = True if keep_bn is None else keep_bn
                g["params"] = _policy.convert_params(
                    g["params"], cast_type, keep_norm_fp32=keep_bn,
                    norm_predicate=getattr(self, "_norm_predicate", None))
        if self.bucketed:
            g["_store"] = BucketStore(g["params"])
        self.param_groups.append(g)
        if self._masters is not None:
            master = _policy.make_master(g["params"])
            if self.bucketed:
                master = g["_store"].pack_jit(master, dtype=jnp.float32)
            self._masters = list(self._masters) + [master]
            self.state.append(self._init_state(master, g))
        else:
            self.state.append(self._init_state(g["params"], g))
        self._jit_update = None        # group count changed: retrace

    # -- subclass hooks -----------------------------------------------------
    def _init_state(self, params, group=None):
        raise NotImplementedError

    def _update(self, grads, state, params, *, group, lr, grad_scale,
                apply_mask):
        raise NotImplementedError

    # -- main API -----------------------------------------------------------
    @property
    def lr(self):
        return self.param_groups[0].get("lr", self.defaults.get("lr"))

    @lr.setter
    def lr(self, value):
        for g in self.param_groups:
            g["lr"] = value

    def value_and_grad(self, loss_fn: Callable, has_aux: bool = False,
                       jit: bool = True):
        """Return ``fn(*args) -> (loss, grads)`` differentiating the *scaled*
        loss w.r.t. the model params (amp-aware).

        The returned ``fn`` is already jitted (``jit=False`` opts out for
        non-jittable loss_fns); the CURRENT params and loss scale are
        passed as jit *arguments* on every call.  Do NOT wrap the result
        in another ``jax.jit``: an outer jit would capture the param tree
        as trace-time constants, silently freezing the gradients at the
        first step's weights (r5 fix — the DCGAN example did exactly
        this for four rounds).

        Hoist the call out of the training loop (``vg =
        opt.value_and_grad(loss_fn)`` once, then ``vg(batch)`` per step).
        Compiled functions are cached per ``loss_fn`` object, so a named
        loss_fn stays cached even if you don't hoist — but a fresh lambda
        per step would compile every iteration (the cache is identity-
        keyed and bounded)."""
        def plain(params, *args):
            return loss_fn(params, *args)

        def scaled(params, scale, *args):
            out = loss_fn(params, *args)
            loss = out[0] if has_aux else out
            loss = jnp.asarray(loss, jnp.float32) * scale
            return (loss, out[1]) if has_aux else loss

        # Cache the jitted pair per (loss_fn, has_aux, jit): the docs call
        # ``opt.value_and_grad(loss_fn)(batch)`` INSIDE training loops, and
        # a fresh jax.jit wrapper per call would retrace + recompile every
        # step (code-review r5).
        cache = getattr(self, "_vg_cache", None)
        if cache is None:
            cache = self._vg_cache = {}
        key = (loss_fn, has_aux, jit)
        if key in cache:
            vg_plain, vg_scaled = cache[key]
        else:
            vg_plain = jax.value_and_grad(plain, has_aux=has_aux)
            vg_scaled = jax.value_and_grad(scaled, has_aux=has_aux)
            if jit:
                vg_plain = jax.jit(vg_plain)
                vg_scaled = jax.jit(vg_scaled)
            if len(cache) >= 16:
                # FIFO-bounded: a fresh-lambda-per-step caller must not
                # leak a compiled pair (plus the lambda's captured batch
                # arrays) per training iteration.
                cache.pop(next(iter(cache)))
            cache[key] = (vg_plain, vg_scaled)

        def fn(*args):
            ls = self.loss_scaler
            if ls is None or (not ls.dynamic and ls._initial_scale == 1.0):
                # static scale 1.0: identity fast path, same program shape
                # as the pre-amp world (reference handle.py:93-102)
                return vg_plain(self.params, *args)
            return vg_scaled(self.params, ls.state.loss_scale, *args)
        return fn

    def backward(self, grads):
        """Deliver gradients of the scaled loss (the ``.backward()`` analog).
        Multiple calls between steps accumulate (reference grad accumulation
        contract)."""
        if self._pending_grads is None:
            self._pending_grads = grads
        else:
            self._pending_grads = jax.tree_util.tree_map(
                jnp.add, self._pending_grads, grads)

    # -- amp handshake ------------------------------------------------------
    def _amp_wire(self, properties, loss_scaler, cast_params=None,
                  norm_predicate=None):
        self.properties = properties
        self.loss_scaler = loss_scaler
        self._amp_wired = True
        self._norm_predicate = norm_predicate
        if self._grouped:
            # A grouped optimizer owns subtrees of the model; the i-th model
            # pytree passed by amp.initialize does NOT match the group
            # structure (reference groups are views of the same tensors, so
            # casting the model suffices there).  Only accept ``cast_params``
            # as a per-group list when every element's tree structure matches
            # the corresponding group — a length-N model pytree that merely
            # *looks* like a group list must not be mis-wired.  Otherwise
            # cast each group's own params with the same policy.
            ts = jax.tree_util.tree_structure
            if (isinstance(cast_params, (list, tuple))
                    and len(cast_params) == len(self.param_groups)
                    and all(ts(c) == ts(g["params"])
                            for c, g in zip(cast_params, self.param_groups))):
                model_groups = list(cast_params)
            else:
                cast_type = properties.cast_model_type
                if (cast_type is not None and
                        jnp.dtype(cast_type) != jnp.dtype(jnp.float32)):
                    keep_bn = properties.keep_batchnorm_fp32
                    keep_bn = True if keep_bn is None else keep_bn
                    model_groups = [
                        _policy.convert_params(g["params"], cast_type,
                                               keep_norm_fp32=keep_bn,
                                               norm_predicate=norm_predicate)
                        for g in self.param_groups]
                else:
                    model_groups = [g["params"] for g in self.param_groups]
        else:
            model_params = (cast_params if cast_params is not None
                            else self.params)
            model_groups = self._to_groups(model_params)
        if self.bucketed:
            # The model params were just cast: rebuild each group's store
            # so bucket dtypes key on the MODEL dtypes (the unpack-with-
            # cast master->model copy then reproduces keep-norm-fp32
            # leaves exactly).
            for g, mp in zip(self.param_groups, model_groups):
                g["_store"] = BucketStore(mp)
            self._jit_update = None
            if not properties.master_weights:
                # No-master levels (O3): the update target IS the cast
                # model params — the Packed state built pre-cast carries
                # the stale segmentation, so rebuild it on the new
                # stores (state is still zero at initialize time, same
                # as the master-weights re-init below).
                self.state = [self._init_state(mp, g) for mp, g in
                              zip(model_groups, self.param_groups)]
        if properties.master_weights:
            # fp32 masters are the update target (reference
            # _process_optimizer.py:28-90: masters swapped into param_groups).
            self._masters = [_policy.make_master(mp)
                             for mp in model_groups]
            if self.bucketed:
                # Masters live AS fp32 buckets across steps: the jitted
                # update's carry is a few large buffers, not O(leaves).
                self._masters = [
                    g["_store"].pack_jit(m, dtype=jnp.float32)
                    for m, g in zip(self._masters, self.param_groups)]
            self.state = [self._init_state(mp, g) for mp, g in
                          zip(self._masters, self.param_groups)]
            self._jit_update = None
        self._set_group_params(model_groups)

    def _prepare_amp_backward(self):
        """Reference ``_prepare_amp_backward`` (:134-150): stash existing
        grads for accumulation, clear the slate for the new backward."""
        self._stashed_grads = self._master_grads
        self._master_grads = None
        self._pending_grads = None

    def _post_amp_backward(self, loss_scaler):
        """Unscale scaled model-dtype grads into fp32 master grads
        (reference ``:153-194``); with stashed grads use the fused axpby
        accumulation path (``:216-241``)."""
        if self._pending_grads is None:
            return
        if self._stashed_grads is None:
            if self.bucketed and not self._grouped:
                # Pack the scaled model-dtype grads and unscale on the
                # buckets: the fp32 master grads then enter step() as a
                # few large buffers (one overflow reduce per bucket).
                store = self.param_groups[0]["_store"]
                packed = store.pack_jit(self._pending_grads)
                self._master_grads, _ = loss_scaler.unscale(packed,
                                                            store=store)
            else:
                self._master_grads, _ = loss_scaler.unscale(
                    self._pending_grads)
        elif isinstance(self._stashed_grads, Packed):
            # Accumulation onto a bucket-resident stash: pack the new
            # scaled grads and run the fused axpby per bucket (mixing a
            # Packed stash with a pytree would fail in tree_map).
            store = self.param_groups[0]["_store"]
            packed = store.pack_jit(self._pending_grads)
            self._master_grads, _ = loss_scaler.unscale_with_stashed(
                packed, self._stashed_grads, store=store)
            self._stashed_grads = None
        else:
            self._master_grads, _ = loss_scaler.unscale_with_stashed(
                self._pending_grads, self._stashed_grads)
            self._stashed_grads = None
        self._pending_grads = None

    def _arm_skip_step(self):
        self._skip_next_step = True

    def _note_pending_overflow(self, flag, loss_id):
        """Deferral hook for ``amp.scale_loss`` (see
        ``LossScaler.update_scale_deferred``): stash the device-side
        overflow flag; :meth:`step` reads every pending flag in ONE
        stacked transfer and arms the one-shot skip if any fired."""
        self._pending_overflow_flags.append((flag, loss_id))
        if len(self._pending_overflow_flags) >= 64:
            # An optimizer that keeps receiving backwards without ever
            # stepping (frozen branch, aborted loop) must not hoard
            # device buffers without bound — fold into the latch now.
            self._resolve_pending_overflows()

    def _resolve_pending_overflows(self):
        if not self._pending_overflow_flags:
            return
        flags = [f for f, _ in self._pending_overflow_flags]
        ids = [i for _, i in self._pending_overflow_flags]
        self._pending_overflow_flags = []
        vals = jax.device_get(jnp.stack(flags))       # ONE host round-trip  # jaxlint: disable=J001 -- the deferral design: every pending scaler flag batched into one stacked transfer per step
        if bool(vals.any()):                  # host value, already fetched
            self._skip_next_step = True
            fired = [i for i, v in zip(ids, vals) if bool(v)]
            maybe_print(f"Gradient overflow.  Skipping step "
                        f"(loss scaler(s) {fired} reduced their scale)")

    # -- step ---------------------------------------------------------------
    def step(self, grads=None, closure=None):
        """Apply one update.  ``grads`` defaults to the amp-delivered master
        grads; without amp pass (unscaled) grads directly.  With multiple
        param groups the grads structure is ``[grads_group0, ...]``."""
        if closure is not None:
            closure()
        rec = _telemetry.get_recorder()
        step_idx = self._step_count
        self._step_count += 1
        self._resolve_pending_overflows()
        if self._skip_next_step:
            # One-shot skip; clears itself like the reference's
            # self-restoring patched step (handle.py:126-151).
            self._skip_next_step = False
            self._master_grads = None
            maybe_print("apex_tpu.amp: skipping optimizer step "
                        "(gradient overflow)")
            if rec is not None:
                # Skip event with the optimizer's own step index — the
                # deferred flags were just resolved, no extra sync.
                rec.metrics.counter("loss_scale_skips").inc()
                rec.event("scale", event="skip", step=step_idx,
                          source="optimizer")
            return self.params

        if grads is None:
            grads = self._master_grads
            if grads is None and self._pending_grads is not None:
                # Non-amp imperative use: backward() called without scale_loss.
                grads = self._pending_grads
        if grads is None:
            raise ValueError("step() called with no gradients; pass grads or "
                             "use backward()/amp.scale_loss first.")

        targets = (self._masters if self._masters is not None
                   else [g["params"] for g in self.param_groups])
        # With a recorder, span the host DISPATCH time of the
        # whole-model update (async) — one call site either way.
        span = (contextlib.nullcontext() if rec is None
                else rec.span("opt_step", step=step_idx))
        with span:
            new_params, self.state = self._run_update(
                self._to_groups(grads), targets, jnp.float32(1.0))

        if self._masters is not None:
            self._masters = new_params
            # master -> model copy (reference _process_optimizer.py:345-356).
            self._set_group_params(self._masters_to_model())
        else:
            self._set_group_params(new_params)
        self._master_grads = None
        self._pending_grads = None
        return self.params

    def zero_grad(self, set_grads_to_None: bool = True):
        """Reference ``zero_grad`` patch (:358-374); grads are explicit here so
        this just clears pending/stashed state."""
        self._pending_grads = None
        self._stashed_grads = None
        self._master_grads = None

    # -- checkpointing ------------------------------------------------------
    def state_dict(self):
        sd = {
            "state": jax.device_get(self.state),
            "defaults": dict(self.defaults),
            "lr": [g.get("lr", self.defaults.get("lr"))
                   for g in self.param_groups],
        }
        if self._masters is not None:
            # Serialize masters in the user-facing pytree form so a
            # bucketed checkpoint loads into a leafwise optimizer and
            # vice versa (optimizer *state* stays mode-specific).
            sd["master_params"] = jax.device_get([
                g["_store"].unpack_jit(m) if isinstance(m, Packed) else m
                for m, g in zip(self._masters, self.param_groups)])
        return sd

    def load_state_dict(self, sd):
        state = sd["state"]
        if not isinstance(state, list):       # single-group legacy format
            state = [state]
        self.state = [jax.tree_util.tree_map(jnp.asarray, s) for s in state]
        lrs = sd.get("lr")
        if lrs is not None:
            if not isinstance(lrs, list):
                lrs = [lrs]
            for g, lr in zip(self.param_groups, lrs):
                g["lr"] = lr
        if sd.get("master_params") is not None:
            masters = sd["master_params"]
            if not isinstance(masters, list):
                masters = [masters]
            masters = [jax.tree_util.tree_map(jnp.asarray, m)
                       for m in masters]
            if self.bucketed:
                # Checkpoints store masters in the user-facing pytree
                # form (see state_dict); re-pack bucket-resident masters.
                masters = [m if isinstance(m, Packed)
                           else g["_store"].pack_jit(m, dtype=jnp.float32)
                           for m, g in zip(masters, self.param_groups)]
            self._masters = masters
            self._set_group_params(self._masters_to_model())
