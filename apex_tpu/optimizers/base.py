"""Stateful optimizer base class — the torch-like imperative API.

The idiomatic JAX path is the functional one (``apex_tpu.optimizers.
functional`` / the optax-style transforms in ``transforms.py``); this class
provides the reference's imperative surface (``opt.step()``,
``opt.zero_grad()``, ``state_dict``) plus the amp handshake that reference
``apex/amp/_process_optimizer.py`` injects with ``types.MethodType``:

* ``_amp_wire`` — master-weight setup (fp32 masters when the model params are
  reduced precision; reference ``:28-90``).
* ``_prepare_amp_backward`` / ``_post_amp_backward`` — stash + unscale
  machinery incl. gradient accumulation via fused axpby (reference
  ``:134-241`` and ``post_backward_models_are_masters`` ``:93-131``).
* ``_arm_skip_step`` — the one-shot skip-step latch armed on overflow
  (reference ``handle.py:126-151`` patches ``step``; the latch restores
  itself after one ``step`` call exactly like the patched function).

The actual parameter update is ONE jitted XLA program per optimizer (the
multi-tensor capability); hyperparameters that may change between steps (lr)
are passed as traced scalars so no recompilation occurs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..amp import policy as _policy
from ..amp._amp_state import maybe_print


class FusedOptimizer:
    """Base: subclasses define ``_init_state(params)`` and ``_update`` (a pure
    function ``(grads, state, params, lr, grad_scale, apply_mask) ->
    (params, state)``)."""

    def __init__(self, params, defaults: Dict[str, Any]):
        self.defaults = dict(defaults)
        self.params = params
        self.master_params = None          # fp32 masters when amp O2-wired
        self.state = self._init_state(params)
        self.loss_scaler = None
        self.properties = None
        self._amp_wired = False
        self._skip_next_step = False
        self._pending_grads = None         # scaled, model-dtype grads
        self._stashed_grads = None         # for grad accumulation
        self._master_grads = None          # unscaled fp32 grads, step() input
        self._jit_update = jax.jit(self._update_with_config)
        # param_groups parity: one group holding the whole tree; lr is
        # mutable between steps without recompilation.
        self.param_groups = [dict(self.defaults, params=self.params)]

    # -- subclass hooks -----------------------------------------------------
    def _init_state(self, params):
        raise NotImplementedError

    def _update(self, grads, state, params, *, lr, grad_scale, apply_mask):
        raise NotImplementedError

    def _update_with_config(self, grads, state, params, lr, grad_scale):
        return self._update(grads, state, params, lr=lr,
                            grad_scale=grad_scale, apply_mask=None)

    # -- main API -----------------------------------------------------------
    @property
    def lr(self):
        return self.param_groups[0].get("lr", self.defaults.get("lr"))

    @lr.setter
    def lr(self, value):
        self.param_groups[0]["lr"] = value

    def value_and_grad(self, loss_fn: Callable, has_aux: bool = False):
        """Return ``fn(*args) -> (loss, grads)`` differentiating the *scaled*
        loss w.r.t. the model params (amp-aware).  Convenience for the
        imperative loop; jit the result for speed."""
        def scaled(params, *args):
            out = loss_fn(params, *args)
            loss = out[0] if has_aux else out
            if self.loss_scaler is not None:
                loss = self.loss_scaler.scale_loss(loss)
            return (loss, out[1]) if has_aux else loss

        vg = jax.value_and_grad(scaled, has_aux=has_aux)

        def fn(*args):
            return vg(self.params, *args)
        return fn

    def backward(self, grads):
        """Deliver gradients of the scaled loss (the ``.backward()`` analog).
        Multiple calls between steps accumulate (reference grad accumulation
        contract)."""
        if self._pending_grads is None:
            self._pending_grads = grads
        else:
            self._pending_grads = jax.tree_util.tree_map(
                jnp.add, self._pending_grads, grads)

    # -- amp handshake ------------------------------------------------------
    def _amp_wire(self, properties, loss_scaler, cast_params=None):
        self.properties = properties
        self.loss_scaler = loss_scaler
        self._amp_wired = True
        if cast_params is not None:
            model_params = cast_params
        else:
            model_params = self.params
        if properties.master_weights:
            # fp32 masters are the update target (reference
            # _process_optimizer.py:28-90: masters swapped into param_groups).
            self.master_params = _policy.make_master(model_params)
            self.state = self._init_state(self.master_params)
        self.params = model_params
        self.param_groups[0]["params"] = self.params

    def _prepare_amp_backward(self):
        """Reference ``_prepare_amp_backward`` (:134-150): stash existing
        grads for accumulation, clear the slate for the new backward."""
        self._stashed_grads = self._master_grads
        self._master_grads = None
        self._pending_grads = None

    def _post_amp_backward(self, loss_scaler):
        """Unscale scaled model-dtype grads into fp32 master grads
        (reference ``:153-194``); with stashed grads use the fused axpby
        accumulation path (``:216-241``)."""
        if self._pending_grads is None:
            return
        if self._stashed_grads is None:
            self._master_grads, _ = loss_scaler.unscale(self._pending_grads)
        else:
            self._master_grads, _ = loss_scaler.unscale_with_stashed(
                self._pending_grads, self._stashed_grads)
            self._stashed_grads = None
        self._pending_grads = None

    def _arm_skip_step(self):
        self._skip_next_step = True

    # -- step ---------------------------------------------------------------
    def step(self, grads=None, closure=None):
        """Apply one update.  ``grads`` defaults to the amp-delivered master
        grads; without amp pass (unscaled) grads directly."""
        if closure is not None:
            closure()
        if self._skip_next_step:
            # One-shot skip; clears itself like the reference's
            # self-restoring patched step (handle.py:126-151).
            self._skip_next_step = False
            self._master_grads = None
            maybe_print("apex_tpu.amp: skipping optimizer step "
                        "(gradient overflow)")
            return self.params

        if grads is None:
            grads = self._master_grads
            if grads is None and self._pending_grads is not None:
                # Non-amp imperative use: backward() called without scale_loss.
                grads = self._pending_grads
        if grads is None:
            raise ValueError("step() called with no gradients; pass grads or "
                             "use backward()/amp.scale_loss first.")

        target = self.master_params if self.master_params is not None else self.params
        lr = jnp.float32(self.param_groups[0].get("lr", self.defaults.get("lr", 0.0)))
        new_params, self.state = self._jit_update(
            grads, self.state, target, lr, jnp.float32(1.0))

        if self.master_params is not None:
            self.master_params = new_params
            # master -> model copy (reference _process_optimizer.py:345-356).
            self.params = _policy.master_to_model(new_params, self.params)
        else:
            self.params = new_params
        self.param_groups[0]["params"] = self.params
        self._master_grads = None
        self._pending_grads = None
        return self.params

    def zero_grad(self, set_grads_to_None: bool = True):
        """Reference ``zero_grad`` patch (:358-374); grads are explicit here so
        this just clears pending/stashed state."""
        self._pending_grads = None
        self._stashed_grads = None
        self._master_grads = None

    # -- checkpointing ------------------------------------------------------
    def state_dict(self):
        sd = {
            "state": jax.device_get(self.state),
            "defaults": dict(self.defaults),
            "lr": self.param_groups[0].get("lr", self.defaults.get("lr")),
        }
        if self.master_params is not None:
            sd["master_params"] = jax.device_get(self.master_params)
        return sd

    def load_state_dict(self, sd):
        self.state = jax.tree_util.tree_map(jnp.asarray, sd["state"])
        if "lr" in sd and sd["lr"] is not None:
            self.param_groups[0]["lr"] = sd["lr"]
        if sd.get("master_params") is not None:
            self.master_params = jax.tree_util.tree_map(
                jnp.asarray, sd["master_params"])
            self.params = _policy.master_to_model(self.master_params, self.params)
