"""Flash attention — Pallas TPU kernels with custom VJP.

Beyond-parity component (the reference has no attention code at all,
SURVEY.md §5 "Long-context"): the hot op of every transformer, built the
TPU way.  The jnp blockwise path (``apex_tpu/ops/attention.py``) is the
numerics oracle and the off-TPU fallback; the kernels here keep the whole
online-softmax recurrence in VMEM so the [T, S] score matrix never touches
HBM in either direction.

Design:

* **forward** — grid ``(batch, heads, q_blocks, kv_blocks)`` with the KV
  block innermost; VMEM scratch carries the running (row-max ``m``,
  denominator ``l``, unnormalized accumulator ``acc``) across KV steps and
  the output + logsumexp are written on the last step.  Saving only
  ``lse = m + log l`` (one fp32 per row) is what makes the backward
  recompute exact — the same memory trick as the reference's fused
  xentropy kernel (``csrc/xentropy_kernel.cu`` saves max_log_sum_exp).
* **backward** — two kernels, both recomputing ``p = exp(s - lse)``:
  ``dq`` iterates KV blocks innermost (accumulating ``ds @ k``), ``dk/dv``
  iterates Q blocks innermost.  Every matmul is expressed in the natural
  ``[bq, bk]`` orientation with leading-dim contractions where the output
  is K-major, so no operand ever needs a VMEM relayout/transpose.
  ``delta = rowsum(do * o)`` is a cheap jnp reduction fused by XLA.
* causal masking skips fully-masked KV blocks via ``pl.when`` predication,
  and sliding-window local attention goes further with a BOUNDED grid:
  only ``ceil(window/bk)+1`` KV blocks per Q block are even visited
  (virtual-negative block ids clamp in the index maps and predicate off),
  so local attention is O(T * window) in both compute and fetches;
  a key-side additive bias ``[batch, kv_len]`` covers padding masks and a
  head-broadcast ``[batch, q_len, kv_len]`` bias covers segment/2-D masks
  and relative-position biases, with its head-summed gradient produced by
  a dedicated third backward kernel (grid head-innermost so the output
  block accumulates residently).  A per-head ``[B,H,T,S]`` bias falls
  back to the jnp path.
* per-row stats (``lse``, ``delta``) travel as ``[B, H, T, 1]`` so kernel
  blocks are ``(bq, 1)`` column vectors — the layout the FusedLayerNorm
  kernel already uses for mean/invvar — avoiding lane-replication waste.

All matmuls run on the MXU with fp32 accumulation
(``preferred_element_type``); ``p`` is cast back to the value dtype before
the PV matmul so bf16 inputs stay on the fast path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only import; absent on CPU-only installs.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..normalization.fused_layer_norm import _use_pallas
from ..pallas_compat import align_vma as _align_vma
from ..pallas_compat import sds_with_vma as _sds
from ..tune.dispatch import kernel_config as _tuned_config
from ..tune.space import pow2_bucket as _pow2

NEG_INF = -1e30

#: config-cache version of this kernel family's blocking scheme
#: (ISSUE 14) — covers the forward AND both backward kernels (they
#: share block_q/block_k); bump when the grid/block semantics change.
TUNE_VERSION = 1
# r4 block-size sweep on the v5e (seq 8k causal fwd+bwd, min-of-3):
# 512x512 18.45 ms, 1024x512 17.50, 512x1024 16.44, 1024x1024 15.75,
# 2048x512 17.78, 256x256 27.99 — bigger blocks amortize the per-block
# mask/softmax epilogue over more MXU work; 1024^2 scores (4 MB fp32)
# still fit VMEM comfortably beside the operands.
_DEFAULT_BLOCK_Q = 1024
_DEFAULT_BLOCK_K = 1024

# Shape dispatch (r5, VERDICT r4 next #2): at short sequence the Pallas
# kernels LOSE to one fused XLA softmax over materialized scores — the
# per-launch overhead and block machinery cannot amortize (BERT seq 128:
# 27.7% of the device step was zero-attributed custom-calls).  Measured
# crossover on the v5e (tools/attention_sweep.py -> ATTENTION_SWEEP.json,
# 15 configs over seq x head_dim x batch*heads x causal): below 1024 the
# jnp path wins or ties within tunnel noise (e.g. causal b16 s512: jnp
# 9.7 ms vs kernel-best 12.4); from 1024 the kernel wins decisively
# (causal b16 s1024: 12.4 vs 21.6; s2048: 18.8 vs 47.7; 1024^2 blocks
# best at every winning shape).  flash_attention with DEFAULT (None)
# block sizes routes sub-crossover shapes to the jnp path, which computes
# the same function; passing block_q/block_k explicitly always forces
# the kernel (the escape hatch, same contract as the bias cap).
_KERNEL_MIN_KV = 1024


def tune_bucket(tq: int, tk: int, d: int, causal: bool, has_bias: bool,
                windowed: bool) -> str:
    """Config-cache shape bucket: sequence lengths round up to powers of
    two (the block sweep's winners are stable within a pow2 band, r4);
    head_dim, causality, the [B,T,S]-bias flag (extra VMEM residents per
    block) and the sliding-window flag (bounded grid wants bq == bk) are
    exact."""
    return (f"q{_pow2(tq)}_k{_pow2(tk)}_d{d}_c{int(causal)}"
            f"_b{int(has_bias)}_w{int(windowed)}")


def _dispatch_to_jnp(tq, tk, defaults_used):
    """True when the defaults-only shape dispatch should take the jnp
    path: caller left both block sizes at their defaults AND the KV
    length is below the measured kernel-win crossover."""
    return defaults_used and tk < _KERNEL_MIN_KV and tq < _KERNEL_MIN_KV


def _pick_block(t: int, preferred: int) -> Optional[int]:
    """Largest block <= preferred that divides t and is a multiple of 128;
    or t itself when t <= preferred and sublane-aligned (t % 8 == 0 — a
    whole-array block equal to the array dim is legal in Mosaic).  None =
    no legal block, caller falls back to the jnp path."""
    if t <= preferred:
        return t if t % 8 == 0 else None
    preferred -= preferred % 128          # honor the multiple-of-128 claim
    for blk in range(preferred, 127, -128):
        if t % blk == 0:
            return blk
    return None


def _causal_block_mask(qi, ki, bq, bk, q_off=0, k_off=0, window=None):
    """Causal (optionally sliding-window) mask on GLOBAL positions:
    ``q_off``/``k_off`` are the global offsets of this call's first
    query/key row (dynamic scalars under ring attention, 0 for
    single-device use).  ``window``: each query sees only the last
    ``window`` keys (itself included) — mistral/longformer-style local
    attention."""
    q_pos = q_off + qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = q_pos >= k_pos
    if window is not None:
        mask = jnp.logical_and(mask, q_pos - k_pos < window)
    return mask


def _block_live(qi, ki, bq, bk, q_off, k_off, window):
    """Whether this (qi, ki) block intersects the causal/window band —
    the block-skip predicate shared by all four kernels.  Blocks past the
    diagonal AND blocks older than the window are skipped entirely, so
    sliding-window attention costs O(T * window), not O(T^2)."""
    run = q_off + qi * bq + bq - 1 >= k_off + ki * bk        # causal skip
    if window is not None:
        # newest key in block still inside the oldest query's window?
        run = jnp.logical_and(
            run, (q_off + qi * bq) - (k_off + ki * bk + bk - 1) < window)
    return run


def _window_span(window, bq, bk, q_offset, k_offset, nk):
    """Static KV-block count per Q block for the BOUNDED sliding-window
    grid, or None to keep the full masked grid.  Bounded requires equal
    block sizes and static zero offsets (the ring path's dynamic offsets
    shift the band per rank); a span covering the whole row buys nothing.
    The bounded grid is what makes `window` O(T * window): a masked-only
    implementation still FETCHES every skipped block."""
    if window is None or bq != bk:
        return None
    if not (isinstance(q_offset, int) and isinstance(k_offset, int)
            and q_offset == 0 and k_offset == 0):
        return None
    span = (window - 2) // bk + 2
    return span if span < nk else None


def _mm(a, b, dims):
    """MXU matmul with fp32 accumulation.  Precision must be explicit: the
    global ``jax_default_matmul_precision=highest`` (set by the test
    conftest) lowers bf16 operands to an fp32 contract_precision Mosaic
    cannot compile ("Bad lhs type"); fp32 operands conversely need HIGHEST
    to match the oracle instead of TPU's default one-pass bf16 multiply."""
    prec = (lax.Precision.HIGHEST
            if a.dtype == jnp.float32 and b.dtype == jnp.float32
            else lax.Precision.DEFAULT)
    return lax.dot_general(a, b, (dims, ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)


# -- forward kernel ------------------------------------------------------------

def _offsets_and_predicates(qi, ki, bq, bk, *, causal, dyn_off, qoff_ref,
                            koff_ref, q_off0, k_off0, window, window_span):
    """Shared causal-control logic: global offsets (SMEM scalars on the
    ring path, Python constants otherwise — r4, the constants let the
    plain path's comparisons fold) and the block-skip ``run`` predicate.
    ``run is True`` statically for non-causal kernels."""
    if not causal:
        return 0, 0, True
    if dyn_off:
        q_off, k_off = qoff_ref[0, 0], koff_ref[0, 0]
    else:
        q_off, k_off = q_off0, k_off0
    run = _block_live(qi, ki, bq, bk, q_off, k_off, window)
    if window_span is not None:
        run = jnp.logical_and(run, ki >= 0)
    return q_off, k_off, run


def _masked_split(run, body, mask_fn):
    """Run ``body(mask_fn())`` under the ``run`` block-skip predicate;
    ``run is True`` statically (non-causal) runs the unmasked body
    directly.

    r4 lesson (measured on chip, seq 8k causal): splitting into an
    unmasked interior branch + masked edge branch under complementary
    ``pl.when``s REGRESSED 17% (17.2 -> 20.1 ms fwd+bwd) — duplicating
    the matmul body across predicated regions defeats Mosaic's loop
    pipelining, which outweighs the saved per-element mask work.  One
    body, always masked on causal paths."""
    if run is True:
        body(None)
        return

    @pl.when(run)
    def _():
        body(mask_fn())


def _opt_refs(refs, has_bias, has_bias2, dyn_off):
    """Split a kernel's trailing refs into (kb, b2, qoff, koff, rest) per
    the operand-assembly flags — the single mirror of the conditional
    operand order both pallas callers build."""
    it = iter(refs)
    kb_ref = next(it) if has_bias else None
    b2_ref = next(it) if has_bias2 else None
    qoff_ref = next(it) if dyn_off else None
    koff_ref = next(it) if dyn_off else None
    return kb_ref, b2_ref, qoff_ref, koff_ref, list(it)


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, sm_scale, causal, has_bias,
                has_bias2, dyn_off, q_off0, k_off0, window,
                window_span=None):
    kb_ref, b2_ref, qoff_ref, koff_ref, rest = _opt_refs(
        refs, has_bias, has_bias2, dyn_off)
    out_ref, lse_ref, m_scr, l_scr, acc_scr = rest

    j = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)
    # Bounded sliding-window grid (window_span set): only span KV blocks
    # per Q block are visited; j walks them ending at the diagonal (ki may
    # be a virtual negative for early rows -> dead step).
    ki = j if window_span is None else qi - (window_span - 1) + j
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: fully-masked KV blocks above the diagonal are skipped (on
    # global positions, so a ring shard entirely in the future runs no
    # block at all).
    q_off, k_off, run = _offsets_and_predicates(
        qi, ki, bq, bk, causal=causal, dyn_off=dyn_off, qoff_ref=qoff_ref,
        koff_ref=koff_ref, q_off0=q_off0, k_off0=k_off0, window=window,
        window_span=window_span)

    def body(mask):
        q = q_ref[0, 0]                                  # [bq, d]
        k = k_ref[0, 0]                                  # [bk, d]
        s = _mm(q, k, ((1,), (1,))) * sm_scale   # [bq, bk]
        if has_bias:
            s = s + kb_ref[0].astype(jnp.float32)
        if has_bias2:
            s = s + b2_ref[0].astype(jnp.float32)        # [bq, bk] block
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]                                # [bq, 1]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = _mm(p.astype(v_ref.dtype), v_ref[0, 0],
                 ((1,), (0,)))                           # [bq, d]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    _masked_split(run, body,
                  lambda: _causal_block_mask(qi, ki, bq, bk, q_off, k_off,
                                             window))

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:]
        safe = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_scr[:] / safe).astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(l == 0.0, NEG_INF,
                                  m_scr[:] + jnp.log(safe))


def _off_arg(offset):
    """Dynamic global-offset scalar as a (1, 1) SMEM operand."""
    return jnp.asarray(offset, jnp.int32).reshape(1, 1)


def _off_spec():
    # *_: the offset scalar is grid-invariant for every kernel regardless
    # of grid rank (the dkv grid is 5-D under GQA, 4-D otherwise).
    if pltpu is None:  # pragma: no cover
        return pl.BlockSpec((1, 1), lambda *_: (0, 0))
    return pl.BlockSpec((1, 1), lambda *_: (0, 0),
                        memory_space=pltpu.SMEM)


def _static_offsets(causal, q_offset, k_offset):
    """(dyn_off, q_off0, k_off0): offsets are baked as Python constants
    whenever they are static ints (the single-device path — r4, no SMEM
    operands / scalar reads in the kernels); traced scalars (the ring
    path) ride SMEM.  Non-causal kernels never read offsets at all."""
    if not causal:
        return False, 0, 0
    if isinstance(q_offset, int) and isinstance(k_offset, int):
        return False, int(q_offset), int(k_offset)
    return True, 0, 0


def _flash_fwd_pallas(q, k, v, kbias, *, sm_scale, causal, block_q, block_k,
                      q_offset=0, k_offset=0, qk_bias=None, window=None,
                      interpret=False):
    """q: [B, H, T, D]; k,v: [B, H_kv, S, D] (head-major) with
    ``H % H_kv == 0`` — grouped-query/multi-query attention shares each KV
    head across ``H / H_kv`` query heads purely through the k/v BlockSpec
    index maps (no repeat/materialization).  kbias: [B, S] or None.
    ``qk_bias``: [B, Tq, Tk] additive bias (broadcast over heads) or None.
    ``q_offset``/``k_offset``: global positions of the first query/key row
    (may be traced scalars — the ring-attention hook).
    Returns (out [B,H,T,D], lse [B,H,T,1] fp32).

    Operands are assembled per configuration (r4): the plain causal path
    carries NO bias dummies and NO offset scalars — what the r3 kernels
    paid for unconditionally (VERDICT r3 next #4)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    grp = h // k.shape[1]                # query heads per KV head (GQA)
    nq, nk = tq // block_q, tk // block_k
    has_bias = kbias is not None
    has_bias2 = qk_bias is not None
    dyn_off, q_off0, k_off0 = _static_offsets(causal, q_offset, k_offset)

    span = _window_span(window, block_q, block_k, q_offset, k_offset, nk)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               has_bias=has_bias, has_bias2=has_bias2,
                               dyn_off=dyn_off, q_off0=q_off0, k_off0=k_off0,
                               window=window, window_span=span)
    if span is None:
        _kc = lambda qi, j: j
    else:          # clamped real block for a possibly-virtual ki
        _kc = lambda qi, j: jnp.maximum(qi - (span - 1) + j, 0)
    _hk = (lambda h: h) if grp == 1 else (lambda h: h // grp)

    ins = [q, k, v]
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, j: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b, h, qi, j: (b, _hk(h), _kc(qi, j), 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b, h, qi, j: (b, _hk(h), _kc(qi, j), 0)),
    ]
    if has_bias:
        ins.append(kbias[:, None, :])
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, h, qi, j: (b, 0, _kc(qi, j))))
    if has_bias2:
        ins.append(qk_bias)
        in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), lambda b, h, qi, j: (b, qi, _kc(qi, j))))
    if dyn_off:
        ins += [_off_arg(q_offset), _off_arg(k_offset)]
        in_specs += [_off_spec(), _off_spec()]
    # Align varying-manual-axes across ALL operands (rank-varying ring
    # offsets vs replicated biases vs sharded activations) so the kernel
    # traces under shard_map's default vma tracking.  Rebind q/k/v to the
    # ALIGNED arrays: the out_shape vma below must carry the union vma
    # (e.g. a sharded bias over replicated activations).
    ins = list(_align_vma(*ins))
    q, k, v = ins[0], ins[1], ins[2]
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, span if span is not None else nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, j: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, j: (b, h, qi, 0)),
        ],
        out_shape=[
            _sds((b, h, tq, d), q.dtype, q, k, v),
            _sds((b, h, tq, 1), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*ins)
    return out, lse


# -- backward kernels ----------------------------------------------------------

def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kb_ref,
                    b2_ref, mask, *, sm_scale, has_bias, has_bias2):
    """Shared bwd recompute: returns (p, ds), both [bq, bk] fp32.
    ``mask`` is None on interior blocks (the r4 mask-free fast path)."""
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    s = _mm(q, k, ((1,), (1,))) * sm_scale       # [bq, bk]
    if has_bias:
        s = s + kb_ref[0].astype(jnp.float32)
    if has_bias2:
        s = s + b2_ref[0].astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0, 0])                           # lse: [bq, 1]
    if mask is not None:
        # A fully-masked row has lse == NEG_INF, making exp(NEG_INF -
        # NEG_INF) = 1 on masked entries; the forward kernel zeroes these,
        # so the recompute must too.
        p = jnp.where(mask, p, 0.0)
    dp = _mm(do_ref[0, 0], v_ref[0, 0], ((1,), (1,)))        # [bq, bk]
    ds = p * (dp - delta_ref[0, 0]) * sm_scale               # delta: [bq, 1]
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *refs, sm_scale, causal, has_bias, has_bias2, dyn_off,
                   q_off0, k_off0, window, window_span=None):
    kb_ref, b2_ref, qoff_ref, koff_ref, rest = _opt_refs(
        refs, has_bias, has_bias2, dyn_off)
    dq_ref, dq_scr = rest
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)
    ki = j if window_span is None else qi - (window_span - 1) + j
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_off, k_off, run = _offsets_and_predicates(
        qi, ki, bq, bk, causal=causal, dyn_off=dyn_off, qoff_ref=qoff_ref,
        koff_ref=koff_ref, q_off0=q_off0, k_off0=k_off0, window=window,
        window_span=window_span)

    def body(mask):
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, kb_ref, b2_ref, mask,
                                sm_scale=sm_scale, has_bias=has_bias,
                                has_bias2=has_bias2)
        dq_scr[:] = dq_scr[:] + _mm(ds.astype(k_ref.dtype), k_ref[0, 0],
                                    ((1,), (0,)))

    _masked_split(run, body,
                  lambda: _causal_block_mask(qi, ki, bq, bk, q_off, k_off,
                                             window))

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, sm_scale, causal, has_bias, has_bias2, dyn_off,
                    q_off0, k_off0, window, window_span=None,
                    n_q_blocks=None, has_hg=False):
    """Grid ``(b, h_kv, ki, hg, qi)`` under GQA: group member ``hg`` (one
    of the ``H/H_kv`` query heads sharing this KV head) sweeps OUTSIDE the
    qi loop, so the (b, h_kv, ki) dk/dv output blocks are revisited only
    on consecutive steps (resident scratch accumulation over qi AND hg),
    while the per-q-head db block flushes each time its qi sweep ends.
    Plain MHA (``has_hg=False``) drops the hg grid dim entirely — grid
    ``(b, h, ki, qi)`` — r4: a singleton grid dim is not free on Mosaic's
    pipeline, and the hg predicates fold away statically."""
    kb_ref, b2_ref, qoff_ref, koff_ref, rest = _opt_refs(
        refs, has_bias, has_bias2, dyn_off)
    if has_bias:
        dk_ref, dv_ref, db_ref, dk_scr, dv_scr, db_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        db_ref = db_scr = None
    if has_hg:
        j = pl.program_id(4)
        nq = pl.num_programs(4)
        hg = pl.program_id(3)
        ng = pl.num_programs(3)
        first_sweep = jnp.logical_and(j == 0, hg == 0)
        last_sweep = lambda: jnp.logical_and(j == nq - 1, hg == ng - 1)
    else:
        j = pl.program_id(3)
        nq = pl.num_programs(3)
        first_sweep = j == 0
        last_sweep = lambda: j == nq - 1
    ki = pl.program_id(2)
    qi = j if window_span is None else ki + j
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(first_sweep)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    if has_bias:
        @pl.when(j == 0)
        def _():
            db_scr[:] = jnp.zeros_like(db_scr)

    q_off, k_off, run = _offsets_and_predicates(
        qi, ki, bq, bk, causal=causal, dyn_off=dyn_off, qoff_ref=qoff_ref,
        koff_ref=koff_ref, q_off0=q_off0, k_off0=k_off0, window=window,
        window_span=window_span)
    if causal and window_span is not None:
        run = jnp.logical_and(run, qi <= n_q_blocks - 1)

    def body(mask):
        p, ds = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, kb_ref, b2_ref, mask,
                                sm_scale=sm_scale, has_bias=has_bias,
                                has_bias2=has_bias2)
        do = do_ref[0, 0]
        # K-major outputs via leading-dim contraction — no transposes.
        dv_scr[:] = dv_scr[:] + _mm(p.astype(do.dtype), do,
                                    ((0,), (0,)))            # [bk, d]
        dk_scr[:] = dk_scr[:] + _mm(ds.astype(q_ref.dtype), q_ref[0, 0],
                                    ((0,), (0,)))            # [bk, d]
        if has_bias:
            # d(loss)/d(bias) column-sum: ds carries an extra sm_scale
            # factor (it is dL/ds * sm_scale for the dq/dk matmuls), which
            # the caller divides back out.
            db_scr[:] = db_scr[:] + jnp.sum(ds, axis=0, keepdims=True)

    _masked_split(run, body,
                  lambda: _causal_block_mask(qi, ki, bq, bk, q_off, k_off,
                                             window))

    @pl.when(last_sweep())
    def _():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)

    if has_bias:
        @pl.when(j == nq - 1)
        def _():
            db_ref[0, 0] = db_scr[:]


def _bwd_db2_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, sm_scale, causal, has_bias, dyn_off, q_off0,
                    k_off0, window, window_span=None):
    """d(loss)/d(qk_bias) summed over heads.  Separate kernel with the
    HEAD axis innermost in the grid: the (b, qi, ki) output block is then
    revisited on consecutive grid steps only, so the VMEM scratch
    accumulates across heads and flushes once — Pallas TPU does not
    re-fetch an output window revisited non-consecutively, which rules out
    accumulating this in the dkv kernel (whose grid has h outermost)."""
    kb_ref, b2_ref, qoff_ref, koff_ref, rest = _opt_refs(
        refs, has_bias, True, dyn_off)
    db2_ref, db2_scr = rest
    hi = pl.program_id(3)
    nh = pl.num_programs(3)
    qi = pl.program_id(1)
    j = pl.program_id(2)
    ki = j if window_span is None else qi - (window_span - 1) + j
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(hi == 0)
    def _():
        db2_scr[:] = jnp.zeros_like(db2_scr)

    q_off, k_off, run = _offsets_and_predicates(
        qi, ki, bq, bk, causal=causal, dyn_off=dyn_off, qoff_ref=qoff_ref,
        koff_ref=koff_ref, q_off0=q_off0, k_off0=k_off0, window=window,
        window_span=window_span)

    def body(mask):
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, kb_ref, b2_ref, mask,
                                sm_scale=sm_scale, has_bias=has_bias,
                                has_bias2=True)
        db2_scr[:] = db2_scr[:] + ds

    _masked_split(run, body,
                  lambda: _causal_block_mask(qi, ki, bq, bk, q_off, k_off,
                                             window))

    @pl.when(hi == nh - 1)
    def _():
        # ds carries the sm_scale factor used by the dq/dk matmuls;
        # divide it back out for the bias gradient.
        db2_ref[0] = db2_scr[:] * (1.0 / sm_scale)


def _flash_bwd_pallas(q, k, v, kbias, out, lse, do, *, sm_scale, causal,
                      block_q, block_k, q_offset=0, k_offset=0,
                      delta=None, qk_bias=None, window=None,
                      interpret=False):
    b, h, tq, d = q.shape
    h_kv = k.shape[1]
    grp = h // h_kv                      # query heads per KV head (GQA)
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    has_bias = kbias is not None
    has_bias2 = qk_bias is not None
    dyn_off, q_off0, k_off0 = _static_offsets(causal, q_offset, k_offset)

    if delta is None:
        # delta = rowsum(do * out) — a cheap fused reduction outside the
        # kernels; ring attention passes it in precomputed (do/out are
        # step-invariant there, so per-step recompute would be waste
        # inside the scan).
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)              # [B, H, Tq, 1]

    span = _window_span(window, block_q, block_k, q_offset, k_offset, nk)
    if span is None:
        _kc = lambda qi, j: j                      # real == grid index
        _qc = lambda ki, j: j
    else:
        _kc = lambda qi, j: jnp.maximum(qi - (span - 1) + j, 0)
        _qc = lambda ki, j: jnp.minimum(ki + j, nq - 1)
    _hk = (lambda h: h) if grp == 1 else (lambda h: h // grp)

    # Conditional operand assembly (r4): the plain causal path ships no
    # bias dummies and no offset scalars.  vma-aligned as in the fwd.
    ins = [q, k, v, do, lse, delta]
    if has_bias:
        ins.append(kbias[:, None, :])
    if has_bias2:
        ins.append(qk_bias)
    if dyn_off:
        ins += [_off_arg(q_offset), _off_arg(k_offset)]
    ins = list(_align_vma(*ins))
    q, k, v = ins[0], ins[1], ins[2]

    def specs(gridargs_to_bqk):
        """Build the common in_specs; ``gridargs_to_bqk`` maps this
        kernel's grid indices to ``(b, qi, ki, h)``."""
        def ix(f):
            return lambda *g: f(*gridargs_to_bqk(*g))
        qix = ix(lambda b, qi, ki, h: (b, h, qi, 0))
        kix = ix(lambda b, qi, ki, h: (b, _hk(h), ki, 0))     # GQA share
        rix = qix
        out = [
            pl.BlockSpec((1, 1, block_q, d), qix),
            pl.BlockSpec((1, 1, block_k, d), kix),
            pl.BlockSpec((1, 1, block_k, d), kix),
            pl.BlockSpec((1, 1, block_q, d), qix),
            pl.BlockSpec((1, 1, block_q, 1), rix),
            pl.BlockSpec((1, 1, block_q, 1), rix),
        ]
        if has_bias:
            out.append(pl.BlockSpec(
                (1, 1, block_k), ix(lambda b, qi, ki, h: (b, 0, ki))))
        if has_bias2:
            out.append(pl.BlockSpec(
                (1, block_q, block_k), ix(lambda b, qi, ki, h: (b, qi, ki))))
        if dyn_off:
            out += [_off_spec(), _off_spec()]
        return out, qix, kix

    flags = dict(sm_scale=sm_scale, causal=causal, has_bias=has_bias,
                 dyn_off=dyn_off, q_off0=q_off0, k_off0=k_off0,
                 window=window)
    in_specs, qix, _ = specs(lambda b, h, qi, j: (b, qi, _kc(qi, j), h))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, has_bias2=has_bias2,
                          window_span=span, **flags),
        grid=(b, h, nq, span if span is not None else nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d), qix),
        out_shape=_sds((b, h, tq, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*ins)

    # dkv grid: (b, h_kv, ki, hg, qi) under GQA — the hg dim walks the grp
    # query heads sharing each KV head; plain MHA drops the singleton hg
    # dim entirely (r4, see kernel doc).
    has_hg = grp > 1
    if has_hg:
        in_specs, _, kix = specs(
            lambda b, hk, ki, hg, j: (b, _qc(ki, j), ki, hk * grp + hg))
        dkv_grid = (b, h_kv, nk, grp, span if span is not None else nq)
        db_ix = lambda b, hk, ki, hg, j: (b, hk * grp + hg, 0, ki)
    else:
        in_specs, _, kix = specs(
            lambda b, hk, ki, j: (b, _qc(ki, j), ki, hk))
        dkv_grid = (b, h_kv, nk, span if span is not None else nq)
        db_ix = lambda b, hk, ki, j: (b, hk, 0, ki)
    out_specs = [pl.BlockSpec((1, 1, block_k, d), kix),
                 pl.BlockSpec((1, 1, block_k, d), kix)]
    out_shape = [_sds((b, h_kv, tk, d), k.dtype, q, k, v, do),
                 _sds((b, h_kv, tk, d), v.dtype, q, k, v, do)]
    scratch = [pltpu.VMEM((block_k, d), jnp.float32),
               pltpu.VMEM((block_k, d), jnp.float32)]
    if has_bias:
        # Per-(batch, q-head) bias-gradient partials; summed over heads
        # (and un-scaled) by the caller.
        out_specs.append(pl.BlockSpec((1, 1, 1, block_k), db_ix))
        out_shape.append(_sds((b, h, 1, tk), jnp.float32, q, k, v, do))
        scratch.append(pltpu.VMEM((1, block_k), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, has_bias2=has_bias2,
                          window_span=span, n_q_blocks=nq, has_hg=has_hg,
                          **flags),
        grid=dkv_grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*ins)
    if has_bias:
        dk, dv, db_part = outs
        dbias = (jnp.sum(db_part[:, :, 0, :], axis=1)
                 / sm_scale).astype(kbias.dtype)             # [B, S]
    else:
        dk, dv = outs
        dbias = None

    dbias2 = None
    if has_bias2:
        # db2 ALWAYS uses the full masked grid: its output is the dense
        # [B, Tq, Tk] bias gradient, and out-of-band blocks must be
        # WRITTEN (as zeros) — a bounded grid would leave them undefined.
        in_specs, _, _ = specs(lambda b, qi, ki, h: (b, qi, ki, h))
        dbias2 = pl.pallas_call(
            functools.partial(_bwd_db2_kernel, window_span=None, **flags),
            grid=(b, nq, nk, h),
            in_specs=in_specs,            # h INNERMOST — see kernel doc
            out_specs=pl.BlockSpec((1, block_q, block_k),
                                   lambda b, qi, ki, h: (b, qi, ki)),
            out_shape=_sds((b, tq, tk), jnp.float32, q, k, v, do),
            scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
            interpret=interpret,
        )(*ins)
        dbias2 = dbias2.astype(qk_bias.dtype)
    return dq, dk, dv, dbias, dbias2


# -- custom VJP over the head-major layout -------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, kbias, qkbias, sm_scale, causal, window, block_q,
           block_k, interpret, q_offset):
    out, _ = _flash_fwd_pallas(q, k, v, kbias, qk_bias=qkbias,
                               sm_scale=sm_scale, causal=causal,
                               window=window, block_q=block_q,
                               block_k=block_k, q_offset=q_offset,
                               interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, kbias, qkbias, sm_scale, causal, window,
                    block_q, block_k, interpret, q_offset):
    out, lse = _flash_fwd_pallas(q, k, v, kbias, qk_bias=qkbias,
                                 sm_scale=sm_scale, causal=causal,
                                 window=window, block_q=block_q,
                                 block_k=block_k, q_offset=q_offset,
                                 interpret=interpret)
    return out, (q, k, v, kbias, qkbias, out, lse)


def _flash_bwd_rule(sm_scale, causal, window, block_q, block_k, interpret,
                    q_offset, res, do):
    q, k, v, kbias, qkbias, out, lse = res
    dq, dk, dv, dbias, dbias2 = _flash_bwd_pallas(
        q, k, v, kbias, out, lse, do, sm_scale=sm_scale, causal=causal,
        window=window, block_q=block_q, block_k=block_k, qk_bias=qkbias,
        q_offset=q_offset, interpret=interpret)
    return dq, dk, dv, dbias, dbias2


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- public API ----------------------------------------------------------------

def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    key_padding_bias=None,
                    bias=None,
                    window: Optional[int] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False):
    """Flash attention.  ``q``: [batch, q_len, heads, head_dim]; ``k,v``:
    [batch, kv_len, kv_heads, head_dim] (the JAX convention of
    ``apex_tpu.ops.attention``); returns q's shape.

    ``kv_heads`` may divide ``heads`` (grouped-query / multi-query
    attention, r3): each KV head serves ``heads / kv_heads`` query heads
    through the kernel's BlockSpec index maps — KV is never repeated or
    materialized per query head, so GQA's KV-cache/bandwidth saving is
    real on the kernel path.  The jnp fallback repeats KV heads instead
    (correct, not bandwidth-saving).
    ``key_padding_bias``: optional additive bias [batch, kv_len] applied to
    every query row (use ``0`` for visible, large-negative for padded keys).
    ``bias``: optional additive bias [batch, q_len, kv_len] broadcast over
    heads — segment masks, 2-D padding masks, relative-position biases
    (r3, VERDICT r2 weak #4).  Differentiable; its gradient (head-summed)
    is computed by a dedicated kernel pass, so only pass a learnable bias
    when you need the grad.  A per-head [B, H, T, S] bias is accepted but
    ALWAYS takes the jnp path (no kernel support).  With a [B,T,S] bias
    the DEFAULT block sizes are capped at 512 (VMEM budget for the extra
    fp32 bias blocks); an explicitly passed block_q/block_k is honored.
    ``window``: sliding-window local attention (mistral/longformer style,
    requires ``causal=True``) — each query sees the last ``window`` keys,
    itself included; out-of-band KV blocks are skipped entirely, so the
    kernel costs O(T * window) instead of O(T^2).
    On TPU (or with ``interpret=True``) runs the Pallas
    kernels; otherwise — or when the sequence doesn't tile — falls back to
    the jnp blockwise path, which computes the same function.

    **Decode-shaped inputs** (ISSUE 11 satellite): ``causal=True`` with
    ``q_len < kv_len`` treats the queries as the SUFFIX of the key
    sequence — query row ``i`` sits at global position
    ``kv_len - q_len + i`` — the KV-cache decode convention (a q_len=1
    call is one fresh token attending every cached key).  A q_len of 1
    (or any length below the kernel block size) dispatches to the
    correctly-masked jnp path; mask dead cache tail entries with
    ``key_padding_bias``.  ``q_len > kv_len`` under causal raises.
    """
    tq, tk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_heads % n_kv or v.shape[2] != n_kv:
        raise ValueError(
            f"kv heads must divide query heads and match between k and v; "
            f"got q heads {n_heads}, k heads {n_kv}, v heads {v.shape[2]}")
    # Decode-shaped causal inputs (ISSUE 11 satellite): with fewer
    # queries than keys, the queries are the SUFFIX of the sequence —
    # the last tq positions (the KV-cache decode convention: one fresh
    # token attending a cache of tk past keys).  Before this fix the
    # masked paths treated query row 0 as global position 0, so a
    # causal q_len=1 call silently attended only key 0.  Suffix
    # alignment makes causal+cross-length a correct masked path on
    # BOTH the kernel and jnp routes (q_offset is a static int, so the
    # kernels bake it as a constant — no SMEM operands).
    q_offset = 0
    if causal and tq != tk:
        if tq > tk:
            raise ValueError(
                f"causal attention needs q_len <= kv_len (queries are "
                f"the suffix of the key sequence); got q_len {tq} > "
                f"kv_len {tk}")
        q_offset = tk - tq
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding-window "
                             "local attention is causal)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if sm_scale is None:
        sm_scale = d ** -0.5
    # Plain Python flags for the tune-cache bucket, computed BEFORE the
    # bias is broadcast/folded below (a per-head [B,H,T,S] bias forces
    # the jnp path, so the consult never sees the distinction).
    tune_has_bias = bias is not None
    tune_windowed = window is not None
    per_head_bias = None
    if bias is not None and bias.ndim == 4:
        # [B, H, T, S] per-head bias: no kernel support — documented jnp
        # fallback below.
        per_head_bias, bias = bias, None
    elif bias is not None and bias.ndim == 3:
        want = (q.shape[0], tq, tk)
        if tuple(bias.shape) != want:
            # [B,1,S]-style broadcastable biases must be materialized: the
            # kernel BlockSpec indexes (b, qi, ki) into the full array and
            # would silently read clamped garbage otherwise.  broadcast_to
            # is transposed to a sum by autodiff, so dbias keeps the
            # caller's shape.
            try:
                bias = jnp.broadcast_to(bias, want)
            except ValueError:
                raise ValueError(
                    f"bias shape {bias.shape} is not broadcastable to "
                    f"[batch, q_len, kv_len] = {want}") from None
    elif bias is not None:
        raise ValueError(
            f"bias must be [batch, q_len, kv_len] (broadcast over heads) "
            f"or per-head [batch, heads, q_len, kv_len]; got {bias.shape}")
    if bias is not None and key_padding_bias is not None:
        # one additive term covers both: fold the key bias in
        bias = bias + key_padding_bias[:, None, :].astype(bias.dtype)
        key_padding_bias = None

    # None sentinels distinguish "caller did not pass blocks" from a
    # caller explicitly passing the default values (code-review r5): the
    # shape dispatch and the bias cap apply ONLY to un-passed defaults.
    defaults_used = block_q is None and block_k is None
    if block_q is None:
        block_q = _DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = _DEFAULT_BLOCK_K
    if bias is not None:
        # The [B,T,S] bias path moves an extra (block_q, block_k) fp32
        # block per grid step in BOTH directions (b2 input fwd/bwd, db2
        # output + scratch) — at the 1024^2 default that is several more
        # 4 MB VMEM residents the r4 block sweep (bias-free) never
        # budgeted.  Cap the bias path at the r3-proven 512^2 — but only
        # when the caller left the defaults; an explicit block_q/block_k
        # is honored as given (ADVICE r4: callers who measured a larger
        # block fitting must be able to opt in).
        if defaults_used:
            block_q = min(block_q, 512)
            block_k = min(block_k, 512)
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    vma_live = False       # under shard_map vma tracking, interpret-mode
    for x in (q, k, v, bias, key_padding_bias):   # emulation cannot run the
        try:               # kernels (the hlo-interpreter block loops index
            vma_live |= bool(jax.typeof(x).vma)   # varying operands with
        except (AttributeError, TypeError):       # unvarying iotas)
            pass                                  # None / vma-less avals
    use_kernel = ((interpret or _use_pallas()) and bq is not None
                  and bk is not None and pltpu is not None
                  and not (interpret and vma_live)
                  and per_head_bias is None
                  and not (not interpret
                           and _dispatch_to_jnp(tq, tk, defaults_used)))
    if not use_kernel:
        from .attention import blockwise_attention
        b4 = per_head_bias
        if key_padding_bias is not None:
            kb4 = key_padding_bias[:, None, None, :]
            b4 = kb4 if b4 is None else b4 + kb4.astype(b4.dtype)
        if bias is not None:
            b4 = bias[:, None, :, :]
        if n_kv != n_heads:      # GQA off the kernel path: repeat KV heads
            k = jnp.repeat(k, n_heads // n_kv, axis=2)
            v = jnp.repeat(v, n_heads // n_kv, axis=2)
        if window is not None:   # sliding window as an additive band bias
            wb = jnp.where(
                ((q_offset + jnp.arange(tq))[:, None]
                 - jnp.arange(tk)[None, :]) < window,
                0.0, NEG_INF).astype(jnp.float32)
            b4 = wb[None, None] if b4 is None else b4 + wb[None, None]
        # Shape-dispatched short-seq case: one whole-array block (the
        # [T,S] scores fit comfortably below the crossover) — a scan over
        # 512-blocks would only add online-softmax carry overhead here.
        bs = tk if tk < _KERNEL_MIN_KV else 512
        return blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                   bias=b4, block_size=bs,
                                   q_offset=q_offset)

    # Dispatch-time autotune consult (ISSUE 14): when the caller left
    # the blocks at their defaults and the kernel path won, the
    # per-device config cache may override the hand-picked v5e sweep
    # constants.  A tuned block that does not tile this exact sequence
    # (cache written from a different length in the same pow2 bucket)
    # falls back to the defaults already computed above.  Explicit
    # block_q/block_k callers — and the jnp path — never consult.
    if defaults_used:
        cfg = _tuned_config(
            "flash_attention", TUNE_VERSION,
            tune_bucket(tq, tk, d, causal, tune_has_bias, tune_windowed),
            params=("block_q", "block_k"))
        if cfg:
            tbq = _pick_block(tq, cfg["block_q"])
            tbk = _pick_block(tk, cfg["block_k"])
            if tbq is not None and tbk is not None:
                bq, bk = tbq, tbk

    qt = q.transpose(0, 2, 1, 3)                         # [B, H, T, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    kb = (None if key_padding_bias is None
          else key_padding_bias.astype(jnp.float32))
    # bias keeps its own dtype ([B,T,S] is quadratic; an eager fp32 copy
    # would double its HBM footprint) — the kernels widen each block.
    out = _flash(qt, kt, vt, kb, bias, float(sm_scale), bool(causal),
                 None if window is None else int(window),
                 int(bq), int(bk), bool(interpret), int(q_offset))
    return out.transpose(0, 2, 1, 3)
