"""apex_tpu.ops — TPU-first compute ops (attention and friends).

Beyond-parity scope: the reference has no attention code at all
(SURVEY.md §5 "Long-context / sequence parallelism: absent"), but a
TPU-native framework needs long-context attention as a first-class op —
it shapes the sharding design (ring/Ulysses sequence parallelism in
``apex_tpu.parallel``).
"""

from .attention import (blockwise_attention, mha_attention,  # noqa: F401
                        dot_product_attention)
from .flash_attention import flash_attention  # noqa: F401
from .conv import (conv2d, conv2d_ref, PallasConv,  # noqa: F401
                   conv_dispatch_stats, reset_conv_dispatch_stats,
                   publish_conv_counters)
from . import losses  # noqa: F401
from .losses import (binary_cross_entropy,  # noqa: F401
                     binary_cross_entropy_with_logits)
