"""Loss functions with reference-parity banned-function semantics.

The reference bans probability-space ``binary_cross_entropy`` under fp16
autocast because ``log(p)`` needs the full float range
(``apex/amp/lists/functional_overrides.py:59-70``); the safe
``binary_cross_entropy_with_logits`` replacement stays allowed.  The jnp
namespace has no probability-space BCE, so this module provides both: the
unsafe one is registered on the default fp16 banned list (see
``amp.autocast``) and raises the reference's error under an fp16 policy;
under the bf16 default it runs in fp32 instead.
"""

from __future__ import annotations

import jax.numpy as jnp


def binary_cross_entropy(probs, targets, weight=None, reduction="mean"):
    """Probability-space BCE: ``-[t*log(p) + (1-t)*log(1-p)]``.

    Numerically fragile in half precision (reference bans it under fp16
    autocast); prefer :func:`binary_cross_entropy_with_logits`.
    """
    p = jnp.asarray(probs)
    t = jnp.asarray(targets, p.dtype)
    eps = jnp.finfo(p.dtype).tiny
    loss = -(t * jnp.log(p + eps) + (1.0 - t) * jnp.log(1.0 - p + eps))
    if weight is not None:
        loss = loss * weight
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def binary_cross_entropy_with_logits(logits, targets, weight=None,
                                     pos_weight=None, reduction="mean"):
    """Logit-space BCE via the stable log-sum-exp form (the reference's safe
    replacement, always autocast-compatible)."""
    x = jnp.asarray(logits, jnp.float32)
    t = jnp.asarray(targets, jnp.float32)
    neg_abs = -jnp.abs(x)
    softplus = jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        log_w = 1.0 + (pos_weight - 1.0) * t
        loss = (1.0 - t) * x + log_w * (softplus + jnp.maximum(-x, 0.0))
    else:
        loss = jnp.maximum(x, 0.0) - x * t + softplus
    if weight is not None:
        loss = loss * weight
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
