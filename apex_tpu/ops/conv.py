"""NHWC implicit-GEMM Pallas convolution with fused BN/ReLU/residual
epilogue (ISSUE 18).

The r05 roofline ledger puts ResNet-50 amp O2 at ~26% MFU with the conv
path owned end to end by XLA; the stage1/stage2 convs are *memory*-bound
(~0.77-0.93 GB per region for only 39-158 GFLOPs).  This module is the
TPU-native analog of the implicit-GEMM formulation cuDNN uses for the
reference's NVIDIA convs: the im2col tile is materialized **in VMEM
only** — never in HBM — by a static shift-and-matmul tap loop, and the
:func:`apex_tpu.normalization.bn_relu_residual` epilogue is fused into
the forward kernel's epilogue so a ``conv -> bn -> relu (+residual)``
chain costs one HBM round-trip per block instead of three.

Kernel scheme (forward)
    grid ``(N, ceil(O/block_n), ceil(OH/boh))`` — the innermost axis
    streams output-row blocks, so the padded input image block
    ``[1, Hp, Wp, C]`` stays VMEM-resident for a whole ``(n, j)`` pass
    and the weight block ``[KH, KW, C, block_n]`` for a whole ``n``
    pass.  Each of the ``KH*KW`` taps is a strided slice of the resident
    image and one MXU matmul-accumulate into an fp32 ``[boh*OW,
    block_n]`` accumulator: exactly an im2col GEMM, with the im2col
    matrix never built.  ``boh = block_m // OW`` output rows per block
    (``block_m`` = the im2col row-tile, the tuned knob next to
    ``block_n``).

Backward (custom VJP)
    *dgrad* reuses the forward machinery on the stride-dilated cotangent
    with spatially rotated, in/out-transposed weights (a stride-1 conv);
    *wgrad* is a dedicated kernel on grid ``(ceil(O/block_n), N)`` whose
    ``[KH*KW, C, block_n]`` output block stays resident across the
    innermost batch axis and accumulates one tap-GEMM per (tap, image).
    Epilogue cotangents (d_mean/d_invstd/d_scale/d_bias/dz and the ReLU
    mask) reuse :func:`fused_bn_act._bwd_ref` on the saved
    pre-activation — per-channel column sums XLA fuses well — so the
    fused path is gradient-exact vs the explicit conv→bn_relu_residual
    chain.

Contract (the repo kernel contract, ISSUE 7/14):

* jnp reference :func:`conv2d_ref` (``lax.conv_general_dilated`` NHWC +
  the bn_act epilogue reference) is both the CPU fallback and the test
  oracle; ``interpret=True`` runs the REAL kernels in CPU tests.
* :data:`TUNE_VERSION` + a ``conv2d`` tune-registry spec
  (``block_m``/``block_n``, VMEM constraint via ``tune/space``,
  ledger-driven priority); the public function consults the per-device
  config cache at trace time when the caller left the blocks ``None``,
  with the hard-coded defaults as the zero-cost fallback.  Block
  partitioning never reorders a single output element's tap/K reduction,
  so tuned configs match the default BITWISE (``exact=True``).
* Shapes the kernel cannot serve — grouped/depthwise convs, blocks that
  cannot fit scoped VMEM (e.g. the C=3 stem conv, whose lane-padded
  image block alone overflows), sub-crossover sizes — fall back to XLA
  per call site; :class:`PallasConv` counts them in
  :func:`conv_dispatch_stats` so coverage loss is visible.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..pallas_compat import align_vma as _align_vma
from ..pallas_compat import sds_with_vma as _sds
from ..tune import space as _space
from ..tune.dispatch import kernel_config as _tuned_config
from ..normalization.fused_bn_act import _bwd_ref as _ep_bwd_ref
from ..normalization.fused_bn_act import _fwd_ref as _ep_fwd_ref
from ..normalization.fused_bn_act import bn_act_epilogue_ref
from ..normalization.fused_layer_norm import _use_pallas

__all__ = ["conv2d", "conv2d_ref", "PallasConv", "conv_dispatch_stats",
           "reset_conv_dispatch_stats", "publish_conv_counters",
           "tune_bucket"]

#: config-cache version of this kernel's blocking scheme (ISSUE 14).
TUNE_VERSION = 1

#: default im2col row-tile (output rows per block = block_m // OW) and
#: output-channel tile — the zero-cost fallback the tune cache refines.
_DEFAULT_BLOCK_M = 512
_DEFAULT_BLOCK_N = 256

# In-context crossover, the fused_bn_act lesson: below a few million
# output elements the custom call is a fusion barrier that costs more
# than the saved HBM sweeps.
_JNP_MAX_ELEMENTS = 2 * 1024 * 1024

_DN_NHWC = ("NHWC", "HWIO", "NHWC")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    a, b = v
    return (int(a), int(b))


def _norm_padding(padding, h: int, w: int, kh: int, kw: int,
                  sh: int, sw: int, dh: int, dw: int):
    """Normalize ``padding`` to the hashable ``((pt, pb), (pl, pr))``
    form (flax conventions: ``"SAME"``/``"VALID"``, an int, a pair of
    ints, or explicit per-dim pairs)."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return ((0, 0), (0, 0))
        if p == "SAME":
            def same(sz, k, s, d):
                out = -(-sz // s)
                total = max(0, (out - 1) * s + (k - 1) * d + 1 - sz)
                return (total // 2, total - total // 2)
            return (same(h, kh, sh, dh), same(w, kw, sw, dw))
        raise ValueError(f"padding must be 'SAME'/'VALID' or explicit "
                         f"pairs; got {padding!r}")
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    pads = tuple(padding)
    if len(pads) == 2 and all(isinstance(p, int) for p in pads):
        return ((pads[0], pads[0]), (pads[1], pads[1]))
    return tuple((int(a), int(b)) for a, b in pads)


def _out_hw(h: int, w: int, padding, kh: int, kw: int, sh: int, sw: int,
            dh: int, dw: int) -> Tuple[int, int]:
    (pt, pb), (pl_, pr) = padding
    oh = (h + pt + pb - (kh - 1) * dh - 1) // sh + 1
    ow = (w + pl_ + pr - (kw - 1) * dw - 1) // sw + 1
    return oh, ow


def _pick_block(total: int, block: int, unit: int) -> int:
    """Block size capped at ``block``, rounded to a ``unit`` multiple
    where the extent allows it (the quant.kernels rule)."""
    b = min(block, max(unit, (total + unit - 1) // unit * unit))
    return min(b, total) if total >= unit else total


def _pick_boh(oh: int, ow: int, block_m: int) -> int:
    """Output rows per block: the im2col row-tile ``block_m`` divided by
    the row width ``OW``, floored at one output row."""
    return max(1, min(oh, block_m // max(1, ow)))


def _pad_up(v: int, m: int) -> int:
    return -(-v // m) * m


# -- VMEM sizing (the tune/space model, 4-D conv edition) ---------------------
#
# Blocks are tiled on their LAST TWO dims ((8, 128) fp32 granularity),
# so the estimate lane-pads the channel axis and sublane-pads the axis
# before it — the C=3 stem conv pays for 128 lanes whether it uses them
# or not, which is exactly why it must fall back.

def _fwd_vmem_bytes(hp: int, wp: int, c: int, kh: int, kw: int, boh: int,
                    ow: int, bo: int, isz: int, has_z: bool,
                    want_preact: bool) -> int:
    x_b = hp * _pad_up(wp, 8) * _pad_up(c, 128) * isz
    w_b = kh * kw * _pad_up(c, 8) * _pad_up(bo, 128) * isz
    acc_b = _pad_up(boh * ow, 8) * _pad_up(bo, 128) * 4
    out_b = boh * _pad_up(ow, 8) * _pad_up(bo, 128) * isz
    total = x_b + w_b + acc_b + out_b
    if has_z:
        total += out_b
    if want_preact:
        total += out_b
    return total


def _fwd_fits(h: int, w: int, padding, c: int, o: int, kh: int, kw: int,
              sh: int, sw: int, dh: int, dw: int, block_m: int,
              block_n: int, isz: int, has_z: bool,
              want_preact: bool) -> bool:
    oh, ow = _out_hw(h, w, padding, kh, kw, sh, sw, dh, dw)
    if oh < 1 or ow < 1:
        return False
    boh = _pick_boh(oh, ow, block_m)
    bo = _pick_block(o, block_n, 128)
    nbh = -(-oh // boh)
    hp = (nbh * boh - 1) * sh + (kh - 1) * dh + (boh - 1) * sh + 1
    wp = (ow - 1) * sw + (kw - 1) * dw + 1
    return _fwd_vmem_bytes(hp, wp, c, kh, kw, boh, ow, bo, isz, has_z,
                           want_preact) <= _space.VMEM_BUDGET_BYTES


def _dgrad_fits(h: int, w: int, oh: int, ow: int, c: int, o: int, kh: int,
                kw: int, sh: int, sw: int, dh: int, dw: int, block_m: int,
                block_n: int, isz: int) -> bool:
    # dgrad is the forward machinery on the stride-dilated cotangent
    # [N, ~H + (KH-1)dh, ~W + (KW-1)dw, O] producing [N, H, W, C]
    hg = (oh - 1) * sh + 1 + (kh - 1) * dh
    wg = (ow - 1) * sw + 1 + (kw - 1) * dw
    boh = _pick_boh(h, w, block_m)
    bc = _pick_block(c, block_n, 128)
    nbh = -(-h // boh)
    hp = nbh * boh + (kh - 1) * dh
    return _fwd_vmem_bytes(max(hp, hg), max(w + (kw - 1) * dw, wg), o,
                           kh, kw, boh, w, bc, isz, False,
                           False) <= _space.VMEM_BUDGET_BYTES


def _wgrad_fits(h: int, w: int, padding, oh: int, ow: int, c: int, o: int,
                kh: int, kw: int, block_n: int, isz: int) -> bool:
    (pt, pb), (pl_, pr) = padding
    hp, wp = h + pt + pb, w + pl_ + pr
    bo = _pick_block(o, block_n, 128)
    x_b = hp * _pad_up(wp, 8) * _pad_up(c, 128) * isz
    g_b = oh * _pad_up(ow, 8) * _pad_up(bo, 128) * isz
    dw_b = kh * kw * _pad_up(c, 8) * _pad_up(bo, 128) * 4
    tmp = _pad_up(oh * ow, 8) * (_pad_up(c, 128) + _pad_up(bo, 128)) * 4
    return x_b + g_b + dw_b + tmp <= _space.VMEM_BUDGET_BYTES


def tune_bucket(n: int, oh: int, ow: int, c: int, o: int, kh: int, kw: int,
                sh: int, sw: int, dh: int, dw: int, isz: int,
                epilogue: bool, has_z: bool) -> str:
    """Config-cache shape bucket: batch and the joint output spatial
    extent round to powers of two (:func:`apex_tpu.tune.space.
    nhwc_bucket` — the block sweep tiles ``OH*OW`` rows, so ``56x56``
    and ``64x49`` share a winner); channels, the filter/stride/dilation
    geometry, itemsize, and the epilogue/residual flags (extra VMEM
    residents per block) are exact."""
    return (f"{_space.nhwc_bucket(n, oh, ow, c)}_o{o}_k{kh}x{kw}"
            f"_s{sh}x{sw}_d{dh}x{dw}_i{isz}_e{int(epilogue)}"
            f"_z{int(has_z)}")


# -- reference math (jnp fallback + oracle) -----------------------------------

def _raw_conv(x, w, stride, padding, dilation, groups, out_dtype):
    # fp32 accumulation via explicit upcast, not preferred_element_type:
    # the conv transpose rule rejects an fp32 cotangent against bf16
    # operands, so a preferred_element_type reference would not be
    # differentiable in low precision — astype transposes cleanly.
    return jax.lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32),
        window_strides=stride, padding=padding, rhs_dilation=dilation,
        dimension_numbers=_DN_NHWC,
        feature_group_count=groups).astype(out_dtype)


def conv2d_ref(x, w, *, stride=(1, 1), padding="SAME", dilation=(1, 1),
               groups=1, mean=None, invstd=None, scale=None, bias=None,
               z=None, relu=False):
    """jnp reference: NHWC ``lax.conv_general_dilated`` (fp32
    accumulation, cast back) followed by the
    :func:`~apex_tpu.normalization.fused_bn_act.bn_act_epilogue_ref`
    epilogue when ``mean``/``invstd`` are given — the CPU fallback and
    the correctness oracle for the Pallas kernels."""
    stride, dilation = _pair(stride), _pair(dilation)
    padding = _norm_padding(padding, x.shape[1], x.shape[2], w.shape[0],
                            w.shape[1], *stride, *dilation)
    y = _raw_conv(x, w, stride, padding, dilation, groups,
                  jnp.result_type(x, w))
    if mean is None:
        return y
    return bn_act_epilogue_ref(y, mean, invstd, scale, bias, z, relu)


# -- pallas kernels -----------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, mean_ref, invstd_ref, s_ref, b_ref, z_ref,
                *out_refs, kh, kw, sh, sw, dh, dw, ow, epilogue, affine,
                has_z, relu, want_preact):
    out_ref = out_refs[0]
    _, boh, _, bo = out_ref.shape
    c = x_ref.shape[3]
    i = pl.program_id(2)
    row0 = i * boh * sh
    span = (boh - 1) * sh + 1
    acc = jnp.zeros((boh * ow, bo), jnp.float32)
    for ikh in range(kh):            # static tap loop: KH*KW shifted
        for ikw in range(kw):        # strided slices + MXU matmuls
            xs = x_ref[0, pl.ds(row0 + ikh * dh, span), :, :]
            xs = xs[::sh, ikw * dw: ikw * dw + (ow - 1) * sw + 1: sw, :]
            acc = acc + jax.lax.dot_general(
                xs.reshape(boh * ow, c), w_ref[ikh, ikw],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    res = acc.astype(out_ref.dtype)
    if want_preact:
        out_refs[1][0] = res.reshape(boh, ow, bo)
    if epilogue:
        # Same cast sequence as the explicit chain (conv result cast to
        # the activation dtype, epilogue re-upcasts) so fused == chain
        # bitwise, not merely to tolerance.
        of = (res.astype(jnp.float32) - mean_ref[:]) * invstd_ref[:]
        if affine:
            of = of * s_ref[:] + b_ref[:]
        if has_z:
            of = of + z_ref[0].reshape(boh * ow, bo).astype(jnp.float32)
        if relu:
            of = jnp.maximum(of, 0.0)
        res = of.astype(out_ref.dtype)
    out_ref[0] = res.reshape(boh, ow, bo)


def _vec(v, o):
    return jnp.reshape(jnp.asarray(v, jnp.float32), (1, o))


def _im2col_conv(xp, w, stride, dilation, oh, ow, mean, invstd, scale,
                 bias, z, relu, want_preact, blocks, interpret, out_dtype):
    """The forward pallas_call on an already conv-padded input ``xp``
    (used directly by the forward, and by dgrad on the stride-dilated
    cotangent with rotated weights)."""
    n, hp, wp, c = xp.shape
    kh, kw, _, o = w.shape
    sh, sw = stride
    dh, dw = dilation
    bm = blocks[0] or _DEFAULT_BLOCK_M
    bo = _pick_block(o, blocks[1] or _DEFAULT_BLOCK_N, 128)
    boh = _pick_boh(oh, ow, bm)
    nbh = -(-oh // boh)
    nbo = -(-o // bo)
    # Alignment padding: the last oh-block's taps read past the conv
    # extent; grow the zero margin so no in-kernel slice is ever
    # clamped (clamping would SHIFT the slice and corrupt the final
    # block's in-bounds rows, not just the masked tail).
    hp_need = ((nbh * boh - 1) * sh + (kh - 1) * dh + (boh - 1) * sh + 1)
    wp_need = (ow - 1) * sw + (kw - 1) * dw + 1
    if hp < hp_need or wp < wp_need:
        xp = jnp.pad(xp, ((0, 0), (0, max(0, hp_need - hp)),
                          (0, max(0, wp_need - wp)), (0, 0)))
        hp, wp = xp.shape[1], xp.shape[2]
    epilogue = mean is not None
    affine = scale is not None
    has_z = z is not None
    mean2 = _vec(mean if epilogue else jnp.zeros((o,)), o)
    invstd2 = _vec(invstd if epilogue else jnp.zeros((o,)), o)
    s2 = _vec(scale if affine else jnp.zeros((o,)), o)
    b2 = _vec(bias if affine else jnp.zeros((o,)), o)
    zz = z if has_z else jnp.zeros((1, 1, 1, o), out_dtype)
    vec = pl.BlockSpec((1, bo), lambda b, j, i: (0, j))
    x_spec = pl.BlockSpec((1, hp, wp, c), lambda b, j, i: (b, 0, 0, 0))
    w_spec = pl.BlockSpec((kh, kw, c, bo), lambda b, j, i: (0, 0, 0, j))
    out_spec = pl.BlockSpec((1, boh, ow, bo), lambda b, j, i: (b, i, 0, j))
    z_spec = out_spec if has_z else pl.BlockSpec(
        (1, 1, 1, bo), lambda b, j, i: (0, 0, 0, j))
    kernel = functools.partial(_fwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                               dh=dh, dw=dw, ow=ow, epilogue=epilogue,
                               affine=affine, has_z=has_z, relu=relu,
                               want_preact=want_preact)
    operands = _align_vma(xp, w, mean2, invstd2, s2, b2, zz)
    out_shape = _sds((n, oh, ow, o), out_dtype, *operands)
    res = pl.pallas_call(
        kernel,
        grid=(n, nbo, nbh),
        in_specs=[x_spec, w_spec, vec, vec, vec, vec, z_spec],
        out_specs=[out_spec, out_spec] if want_preact else out_spec,
        out_shape=[out_shape, out_shape] if want_preact else out_shape,
        interpret=interpret,
    )(*operands)
    if want_preact:
        return res[0], res[1]
    return res, None


def _pallas_fwd(x, w, stride, padding, dilation, mean, invstd, scale,
                bias, z, relu, want_preact, blocks, interpret, out_dtype):
    (pt, pb), (pl_, pr) = padding
    oh, ow = _out_hw(x.shape[1], x.shape[2], padding, w.shape[0],
                     w.shape[1], *stride, *dilation)
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    return _im2col_conv(xp, w, stride, dilation, oh, ow, mean, invstd,
                        scale, bias, z, relu, want_preact, blocks,
                        interpret, out_dtype)


def _pallas_dgrad(dy, w, stride, padding, dilation, hw, blocks, interpret):
    """dx via the forward machinery: stride-dilate the cotangent, pad to
    the 'full' extent, convolve at stride 1 with the spatially rotated,
    in/out-transposed weights."""
    n, oh, ow, o = dy.shape
    kh, kw, c, _ = w.shape
    sh, sw = stride
    dh, dw = dilation
    (pt, pb), (pl_, pr) = padding
    h, w_in = hw
    lo_h, hi_h = (kh - 1) * dh - pt, h + pt - (oh - 1) * sh - 1
    lo_w, hi_w = (kw - 1) * dw - pl_, w_in + pl_ - (ow - 1) * sw - 1
    gd = jax.lax.pad(dy, jnp.zeros((), dy.dtype),
                     ((0, 0, 0), (lo_h, hi_h, sh - 1),
                      (lo_w, hi_w, sw - 1), (0, 0, 0)))
    w_rot = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))
    dx, _ = _im2col_conv(gd, w_rot, (1, 1), (dh, dw), h, w_in, None,
                         None, None, None, None, False, False, blocks,
                         interpret, dy.dtype)
    return dx


def _wgrad_kernel(x_ref, g_ref, dw_ref, *, kh, kw, sh, sw, dh, dw, oh, ow):
    b = pl.program_id(1)
    c = x_ref.shape[3]
    bo = g_ref.shape[3]

    @pl.when(b == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    g2 = g_ref[0].reshape(oh * ow, bo)
    xv = x_ref[0]
    for ikh in range(kh):
        for ikw in range(kw):
            xs = xv[ikh * dh: ikh * dh + (oh - 1) * sh + 1: sh,
                    ikw * dw: ikw * dw + (ow - 1) * sw + 1: sw, :]
            t = jax.lax.dot_general(
                xs.reshape(oh * ow, c), g2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dw_ref[ikh * kw + ikw] = dw_ref[ikh * kw + ikw] + t


def _pallas_wgrad(x, dy, stride, padding, dilation, w_shape, blocks,
                  interpret, w_dtype):
    kh, kw, c, o = w_shape
    sh, sw = stride
    dh, dw = dilation
    n, oh, ow, _ = dy.shape
    (pt, pb), (pl_, pr) = padding
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    bo = _pick_block(o, blocks[1] or _DEFAULT_BLOCK_N, 128)
    nbo = -(-o // bo)
    kernel = functools.partial(_wgrad_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                               dh=dh, dw=dw, oh=oh, ow=ow)
    operands = _align_vma(xp, dy)
    dwf = pl.pallas_call(
        kernel,
        grid=(nbo, n),     # n innermost: the dw block stays resident
        in_specs=[pl.BlockSpec((1, hp, wp, c), lambda j, b: (b, 0, 0, 0)),
                  pl.BlockSpec((1, oh, ow, bo), lambda j, b: (b, 0, 0, j))],
        out_specs=pl.BlockSpec((kh * kw, c, bo), lambda j, b: (0, 0, j)),
        out_shape=_sds((kh * kw, c, o), jnp.float32, *operands),
        interpret=interpret,
    )(*operands)
    return dwf.reshape(kh, kw, c, o).astype(w_dtype)


# -- custom VJP ---------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12,
                                                    13, 14))
def _conv(x, w, mean, invstd, scale, bias, z, groups, relu, stride,
          padding, dilation, use_pallas, interpret, blocks):
    if use_pallas:
        out, _ = _pallas_fwd(x, w, stride, padding, dilation, mean,
                             invstd, scale, bias, z, relu, False, blocks,
                             interpret, x.dtype)
        return out
    y = _raw_conv(x, w, stride, padding, dilation, groups, x.dtype)
    if mean is None:
        return y
    return _ep_fwd_ref(y, mean, invstd, scale, bias, z, relu)


def _conv_fwd(x, w, mean, invstd, scale, bias, z, groups, relu, stride,
              padding, dilation, use_pallas, interpret, blocks):
    epilogue = mean is not None
    if use_pallas:
        out, y = _pallas_fwd(x, w, stride, padding, dilation, mean,
                             invstd, scale, bias, z, relu, epilogue,
                             blocks, interpret, x.dtype)
    else:
        y = _raw_conv(x, w, stride, padding, dilation, groups, x.dtype)
        out = (_ep_fwd_ref(y, mean, invstd, scale, bias, z, relu)
               if epilogue else y)
    # the pre-activation is a residual only when the epilogue consumed
    # it (its ReLU mask + per-channel cotangents); a plain conv's
    # backward needs only (x, w).
    return out, (x, w, mean, invstd, scale, bias, z,
                 y if epilogue else None)


def _conv_bwd(groups, relu, stride, padding, dilation, use_pallas,
              interpret, blocks, res, g):
    x, w, mean, invstd, scale, bias, z, y = res
    epilogue = mean is not None
    if epilogue:
        # fused_bn_act's reference backward on the saved pre-activation:
        # dy (activation-sized, ReLU-masked) in one shot plus the
        # per-channel column sums — gradient-exact vs the explicit
        # conv -> bn_relu_residual chain by construction.
        dy, d_mean, d_invstd, d_scale, d_bias, dz = _ep_bwd_ref(
            g, y, mean, invstd, scale, bias, z, relu)
    else:
        dy, d_mean, d_invstd, d_scale, d_bias, dz = (g, None, None,
                                                     None, None, None)
    n, h, w_in, c = x.shape
    kh, kw, _, o = w.shape
    oh, ow = dy.shape[1], dy.shape[2]
    isz = jnp.dtype(x.dtype).itemsize
    bm = blocks[0] or _DEFAULT_BLOCK_M
    bn = blocks[1] or _DEFAULT_BLOCK_N
    pallas_dx = use_pallas and _dgrad_fits(
        h, w_in, oh, ow, c, o, kh, kw, *stride, *dilation, bm, bn, isz)
    pallas_dw = use_pallas and _wgrad_fits(
        h, w_in, padding, oh, ow, c, o, kh, kw, bn, isz)
    jdx = jdw = None
    if not (pallas_dx and pallas_dw):
        _, vjp = jax.vjp(
            lambda xx, ww: _raw_conv(xx, ww, stride, padding, dilation,
                                     groups, x.dtype), x, w)
        jdx, jdw = vjp(dy)
    dx = (_pallas_dgrad(dy, w, stride, padding, dilation, (h, w_in),
                        blocks, interpret) if pallas_dx else jdx)
    dw = (_pallas_wgrad(x, dy, stride, padding, dilation, w.shape,
                        blocks, interpret, w.dtype) if pallas_dw else jdw)
    return dx.astype(x.dtype), dw, d_mean, d_invstd, d_scale, d_bias, dz


_conv.defvjp(_conv_fwd, _conv_bwd)


# -- dispatch + public op -----------------------------------------------------

def _dispatch_pallas(impl: Optional[str], n_out: int, fits: bool) -> bool:
    if impl not in (None, "pallas", "jnp"):
        raise ValueError(
            f"impl must be None, 'pallas', or 'jnp'; got {impl!r}")
    if not _use_pallas() or not fits:
        return False
    if impl is not None:
        return impl == "pallas"
    return n_out >= _JNP_MAX_ELEMENTS


def conv2d(x, w, *, stride=(1, 1), padding="SAME", dilation=(1, 1),
           groups: int = 1, mean=None, invstd=None, scale=None, bias=None,
           z=None, relu: bool = False, impl: Optional[str] = None,
           interpret: bool = False, block_m: Optional[int] = None,
           block_n: Optional[int] = None):
    """NHWC 2-D convolution with an optional fused BN/ReLU/residual
    epilogue: ``relu((conv(x, w) - mean) * invstd * scale + bias + z)``.

    ``x``: ``[N, H, W, C]``; ``w``: ``[KH, KW, C // groups, O]`` (the
    flax/``lax.conv_general_dilated`` HWIO layout).  ``stride``/
    ``dilation`` are ints or pairs; ``padding`` is ``"SAME"``,
    ``"VALID"``, an int, or explicit ``((pt, pb), (pl, pr))`` pairs.
    Accumulation is fp32; the result is cast to the operands' dtype.

    The epilogue (active when ``mean``/``invstd`` are given) is the
    :func:`~apex_tpu.normalization.bn_relu_residual` contract with the
    conv output as its input — per-channel fp32 ``mean``/``invstd`` and
    optional affine ``scale``/``bias``, an optional residual ``z`` of
    the output's shape added before the ReLU — fused into the conv
    kernel's epilogue so the chain costs one HBM round-trip per block.
    All epilogue operands are differentiable; statistics computed
    outside (XLA reductions / SyncBatchNorm psums) receive exact
    cotangents, and the fused path is gradient-exact vs the explicit
    ``conv2d`` → ``bn_relu_residual`` chain.

    ``impl``: ``None`` picks pallas-vs-jnp by size (pallas only on TPU,
    and only when the kernel can serve the shape — ``groups == 1`` and
    the blocks fit scoped VMEM); ``"pallas"``/``"jnp"`` force a path.
    ``interpret=True`` runs the real kernels in interpreter mode (CPU
    tier-parity tests).  ``block_m`` (im2col row tile) / ``block_n``
    (output-channel tile): explicit kernel blocks; left ``None`` the
    per-device config cache (:mod:`apex_tpu.tune`) is consulted at
    trace time with the hard-coded defaults as zero-cost fallback.
    """
    stride, dilation = _pair(stride), _pair(dilation)
    if x.ndim != 4 or w.ndim != 4:
        raise ValueError(f"conv2d wants NHWC x and HWIO w; got "
                         f"{x.shape} / {w.shape}")
    n, h, w_in, cin = x.shape
    kh, kw, wc, o = w.shape
    if wc * groups != cin:
        raise ValueError(f"w in-channels {wc} x groups {groups} != input "
                         f"channels {cin}")
    if (mean is None) != (invstd is None):
        raise ValueError("mean and invstd must be given together")
    if mean is None and (scale is not None or z is not None or relu):
        raise ValueError("scale/bias, z and relu belong to the fused "
                         "epilogue — pass mean/invstd to enable it")
    if (scale is None) != (bias is None):
        raise ValueError("scale and bias must be given together")
    dt = jnp.result_type(x, w)
    x = x.astype(dt)
    w = w.astype(dt)
    padding = _norm_padding(padding, h, w_in, kh, kw, *stride, *dilation)
    oh, ow = _out_hw(h, w_in, padding, kh, kw, *stride, *dilation)
    epilogue = mean is not None
    if epilogue:
        mean = jnp.ravel(jnp.asarray(mean, jnp.float32))
        invstd = jnp.ravel(jnp.asarray(invstd, jnp.float32))
        if scale is not None:
            scale = jnp.ravel(jnp.asarray(scale, jnp.float32))
            bias = jnp.ravel(jnp.asarray(bias, jnp.float32))
        if z is not None:
            if z.shape != (n, oh, ow, o):
                raise ValueError(f"z must have the output shape "
                                 f"{(n, oh, ow, o)}; got {z.shape}")
            z = z.astype(dt)
    isz = jnp.dtype(dt).itemsize
    capable = groups == 1
    fits = capable and _fwd_fits(
        h, w_in, padding, cin, o, kh, kw, *stride, *dilation,
        block_m or _DEFAULT_BLOCK_M, block_n or _DEFAULT_BLOCK_N, isz,
        z is not None, epilogue)
    use_pallas = _dispatch_pallas(impl, n * oh * ow * o, fits)
    if interpret and impl != "jnp" and capable:
        use_pallas = True
    if use_pallas and block_m is None and block_n is None:
        cfg = _tuned_config(
            "conv2d", TUNE_VERSION,
            tune_bucket(n, oh, ow, cin, o, kh, kw, *stride, *dilation,
                        isz, epilogue, z is not None),
            params=("block_m", "block_n"))
        if cfg and _fwd_fits(h, w_in, padding, cin, o, kh, kw, *stride,
                             *dilation, cfg["block_m"], cfg["block_n"],
                             isz, z is not None, epilogue):
            block_m, block_n = cfg["block_m"], cfg["block_n"]
    return _conv(x, w, mean, invstd, scale, bias, z, int(groups),
                 bool(relu), stride, padding, dilation, use_pallas,
                 bool(interpret), (block_m, block_n))


# -- flax module + per-site dispatch stats ------------------------------------

_DISPATCH_COUNTS: Dict[str, int] = {"pallas": 0, "fallback": 0}
_FALLBACK_REASONS: Dict[str, int] = {}


def conv_dispatch_stats() -> Dict[str, Any]:
    """Trace-time :class:`PallasConv` dispatch counters: how many conv
    call sites routed to the Pallas kernel vs fell back to XLA, and why
    (``groups`` / ``rank`` / ``vmem`` / ``small``).  Counts accumulate
    per trace (init, apply, and grad traces each count their sites)."""
    return {"pallas_sites": _DISPATCH_COUNTS["pallas"],
            "fallback_sites": _DISPATCH_COUNTS["fallback"],
            "fallback_reasons": dict(_FALLBACK_REASONS)}


def reset_conv_dispatch_stats() -> None:
    _DISPATCH_COUNTS["pallas"] = _DISPATCH_COUNTS["fallback"] = 0
    _FALLBACK_REASONS.clear()


def publish_conv_counters(registry) -> Dict[str, int]:
    """Export the dispatch counters into a telemetry
    :class:`~apex_tpu.telemetry.MetricsRegistry` as monotonic
    ``conv_pallas_sites`` / ``conv_fallback_sites`` /
    ``conv_fallback_<reason>`` counters (ISSUE 20 satellite: the dark
    counts, on the Prometheus surface instead of only a stats dict).

    Delta-published — each call bumps every counter by how much its
    module-global count grew since the LAST publish, so periodic calls
    (an exporter hook, an example's exit path) stay monotonic even
    though :func:`reset_conv_dispatch_stats` may never run.  Returns
    the raw stats dict for the caller's own print line."""
    stats = conv_dispatch_stats()
    flat: Dict[str, int] = {
        "conv_pallas_sites": stats["pallas_sites"],
        "conv_fallback_sites": stats["fallback_sites"],
    }
    for reason, n in stats["fallback_reasons"].items():
        flat[f"conv_fallback_{reason}"] = int(n)
    for name, total in flat.items():
        c = registry.counter(name)
        delta = total - (c.value or 0)
        if delta > 0:
            c.inc(delta)
    return stats


def _site_reason(x_shape, w_shape, padding, stride, dilation,
                 groups: int, isz: int) -> Optional[str]:
    """Why this call site cannot use the kernel on ANY backend (None =
    pallas-capable; the TPU-vs-CPU gate stays inside :func:`conv2d`)."""
    if len(x_shape) != 4:
        return "rank"
    if groups != 1:
        return "groups"
    n, h, w_in, cin = x_shape
    kh, kw, _, o = w_shape
    oh, ow = _out_hw(h, w_in, padding, kh, kw, *stride, *dilation)
    if not _fwd_fits(h, w_in, padding, cin, o, kh, kw, *stride,
                     *dilation, _DEFAULT_BLOCK_M, _DEFAULT_BLOCK_N, isz,
                     False, False):
        return "vmem"
    if n * oh * ow * o < _JNP_MAX_ELEMENTS:
        return "small"
    return None


class PallasConv(nn.Module):
    """Drop-in ``nn.Conv`` stand-in routing through :func:`conv2d`.

    Same parameter pytree as ``nn.Conv`` (an HWIO ``kernel`` plus an
    optional ``bias``, identical initializers), so swapping it in via
    the ResNet ``conv_cls=`` hook changes no checkpoint or init — with
    the flag off (``conv_cls=None`` → ``nn.Conv``) the model is
    bit-identical to before.  Call sites the kernel cannot serve
    (grouped/depthwise, VMEM-overflow like the C=3 stem, sub-crossover
    sizes) fall back to the XLA conv per site and are counted in
    :func:`conv_dispatch_stats`.  ``precision`` is accepted for
    signature parity but ignored (the kernel always accumulates fp32).
    """
    features: int
    kernel_size: Sequence[int]
    strides: Union[None, int, Sequence[int]] = 1
    padding: Any = "SAME"
    kernel_dilation: Union[None, int, Sequence[int]] = 1
    feature_group_count: int = 1
    use_bias: bool = True
    dtype: Any = None
    param_dtype: Any = jnp.float32
    precision: Any = None
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        kh, kw = (self.kernel_size if not isinstance(self.kernel_size, int)
                  else (self.kernel_size, self.kernel_size))
        groups = self.feature_group_count
        cin = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (kh, kw, cin // groups, self.features),
                            self.param_dtype)
        bias = (self.param("bias", self.bias_init, (self.features,),
                           self.param_dtype) if self.use_bias else None)
        if self.dtype is not None:
            x = x.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
            bias = bias.astype(self.dtype) if bias is not None else None
        stride = _pair(self.strides if self.strides is not None else 1)
        dilation = _pair(self.kernel_dilation
                         if self.kernel_dilation is not None else 1)
        padding = _norm_padding(self.padding, x.shape[1], x.shape[2],
                                kh, kw, *stride, *dilation)
        isz = jnp.dtype(jnp.result_type(x, kernel)).itemsize
        reason = _site_reason(x.shape, kernel.shape, padding, stride,
                              dilation, groups, isz)
        if reason is None:
            _DISPATCH_COUNTS["pallas"] += 1
            y = conv2d(x, kernel, stride=stride, padding=padding,
                       dilation=dilation)
        else:
            _DISPATCH_COUNTS["fallback"] += 1
            _FALLBACK_REASONS[reason] = _FALLBACK_REASONS.get(reason,
                                                              0) + 1
            y = _raw_conv(x, kernel, stride, padding, dilation, groups,
                          jnp.result_type(x, kernel))
        if bias is not None:
            y = y + jnp.reshape(bias, (1, 1, 1, -1))
        return y
