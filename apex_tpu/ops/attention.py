"""Blockwise (flash-style) attention with online softmax.

The memory-efficient attention core: never materializes the [T, S] score
matrix; streams KV blocks through a ``lax.scan`` carrying running
(max, denominator, accumulator) — the standard online-softmax recurrence.
Under XLA this compiles to a tight loop whose matmuls hit the MXU; wrapped
in ``jax.checkpoint`` the backward recomputes per-block, giving O(T) memory.

This is also the *local* op of ring attention
(``apex_tpu/parallel/ring_attention.py``): each ring step feeds one rotated
KV shard through the same recurrence, so single-device and ring results
agree to numerical precision.

Shapes follow the JAX convention ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_scores(q, k, sm_scale):
    # [B, H, Tq, Tk] scores for one KV block; fp32 accumulation on the MXU.
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * sm_scale


def _causal_mask(q_offset, k_offset, tq, tk):
    qi = q_offset + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    ki = k_offset + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return qi >= ki


def attention_block_update(q, k, v, m_prev, l_prev, acc_prev, *,
                           sm_scale, causal=False, q_offset=0, k_offset=0,
                           bias=None):
    """One online-softmax update with a KV block.

    Carry: ``m`` running row max [B,H,Tq], ``l`` running denominator
    [B,H,Tq], ``acc`` unnormalized output [B,Tq,H,D].  Returns the updated
    carry.  ``q_offset``/``k_offset`` are the global positions of the first
    query/key in these blocks (needed for causal masking across ring steps /
    scan blocks); either may be a traced scalar.
    """
    s = _block_scores(q, k, sm_scale)                       # [B,H,Tq,Tk]
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = _causal_mask(q_offset, k_offset, q.shape[1], k.shape[1])
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))        # [B,H,Tq]
    # Guard fully-masked rows: keep exp finite.
    p = jnp.exp(s - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    alpha = jnp.exp(m_prev - m_new)                         # [B,H,Tq]
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc_prev * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _init_carry(batch, tq, heads, dim):
    m = jnp.full((batch, heads, tq), NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, tq), jnp.float32)
    acc = jnp.zeros((batch, tq, heads, dim), jnp.float32)
    return m, l, acc


def finalize_attention(m, l, acc, dtype):
    """Normalize the accumulator; fully-masked rows produce zeros."""
    l_t = l.transpose(0, 2, 1)[..., None]                   # [B,Tq,H,1]
    safe = jnp.where(l_t == 0.0, 1.0, l_t)
    return (acc / safe).astype(dtype)


def blockwise_attention(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        block_size: int = 512,
                        q_offset=0, k_offset=0,
                        bias=None):
    """Flash-style attention over KV blocks.  [B,T,H,D] in and out."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if sm_scale is None:
        sm_scale = d ** -0.5
    blk = min(block_size, tk)
    n_blocks = tk // blk
    rem = tk - n_blocks * blk       # trailing partial block (static)
    carry = _init_carry(b, tq, h, d)

    if n_blocks == 1 and rem == 0:
        m, l, acc = attention_block_update(
            q, k, v, *carry, sm_scale=sm_scale, causal=causal,
            q_offset=q_offset, k_offset=k_offset, bias=bias)
        return finalize_attention(m, l, acc, q.dtype)

    if n_blocks > 0:
        tk_main = n_blocks * blk
        k_blocks = k[:, :tk_main].reshape(
            b, n_blocks, blk, h, d).transpose(1, 0, 2, 3, 4)
        v_blocks = v[:, :tk_main].reshape(
            b, n_blocks, blk, h, d).transpose(1, 0, 2, 3, 4)
        if bias is not None:
            bias_blocks = bias[..., :tk_main].reshape(
                *bias.shape[:-1], n_blocks, blk)
            bias_blocks = jnp.moveaxis(bias_blocks, -2, 0)
        else:
            bias_blocks = None

        @functools.partial(jax.checkpoint, prevent_cse=False)
        def step(carry, inputs):
            i, kb, vb = inputs[0], inputs[1], inputs[2]
            bb = inputs[3] if bias_blocks is not None else None
            m, l, acc = carry
            m, l, acc = attention_block_update(
                q, kb, vb, m, l, acc, sm_scale=sm_scale, causal=causal,
                q_offset=q_offset, k_offset=k_offset + i * blk, bias=bb)
            return (m, l, acc), None

        idx = jnp.arange(n_blocks)
        xs = (idx, k_blocks, v_blocks)
        if bias_blocks is not None:
            xs = xs + (bias_blocks,)
        carry, _ = lax.scan(step, carry, xs)

    if rem:
        # Remainder block — still O(blk)-sized scores, never the full [T,S].
        m, l, acc = attention_block_update(
            q, k[:, -rem:], v[:, -rem:], *carry, sm_scale=sm_scale,
            causal=causal, q_offset=q_offset,
            k_offset=k_offset + n_blocks * blk,
            bias=None if bias is None else bias[..., -rem:])
        carry = (m, l, acc)

    return finalize_attention(*carry, q.dtype)


def dot_product_attention(q, k, v, *, causal: bool = False,
                          sm_scale: Optional[float] = None, bias=None):
    """Reference (non-blockwise) attention — the numerics oracle."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = _block_scores(q, k, sm_scale)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = _causal_mask(0, 0, q.shape[1], k.shape[1])
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def mha_attention(q, k, v, **kw):
    """Alias choosing the blockwise path (public name)."""
    return blockwise_attention(q, k, v, **kw)
