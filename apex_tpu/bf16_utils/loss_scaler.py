"""Legacy loss scalers (the pre-amp manual API).

Re-design of reference ``apex/fp16_utils/loss_scaler.py``:

* ``LossScaler`` — static scale, overflow check is a no-op (:10-44).
* ``DynamicLossScaler`` — init 2**32, halve on overflow, double after 1000
  clean iterations (:46-131).

Overflow detection is a device-side all-finite reduction (the reference's
``_has_inf_or_nan`` does a per-param CPU float sum, :94-113 — on TPU that
would be a host sync per tensor; we reduce on device and sync once).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..amp.loss_scaler import all_finite


class LossScaler:
    """Static loss scaler (reference loss_scaler.py:10-44)."""

    def __init__(self, scale=1.0):
        self.cur_scale = float(scale)

    def has_overflow(self, params_or_grads):
        return False

    def _has_inf_or_nan(self, x):
        return False

    def update_scale(self, overflow):
        pass

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(lambda g: g * self.cur_scale, grads)

    def backward(self, loss_grad_fn, *args):
        """Return grads of ``loss * scale`` given a grad fn of the raw loss."""
        grads = loss_grad_fn(*args)
        return self.scale_gradient(grads)


class DynamicLossScaler:
    """Dynamic loss scaler (reference loss_scaler.py:46-131): init 2**32,
    ``scale_factor`` 2, ``scale_window`` 1000."""

    def __init__(self, init_scale=2.**32, scale_factor=2., scale_window=1000):
        self.cur_scale = float(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, params_or_grads) -> bool:
        """ONE device→host sync for the whole tree."""
        return not bool(jax.device_get(all_finite(params_or_grads)))  # jaxlint: disable=J001 -- legacy imperative API: the caller branches on overflow in Python (reference loss_scaler.py)

    def _has_inf_or_nan(self, x) -> bool:
        return not bool(jax.device_get(jnp.all(jnp.isfinite(x))))  # jaxlint: disable=J001 -- reference-parity per-tensor overflow probe; the batched path is has_overflow()

    def update_scale(self, overflow: bool):
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(lambda g: g * self.cur_scale, grads)
