"""Manual precision-conversion helpers over parameter pytrees.

Re-design of reference ``apex/fp16_utils/fp16util.py:7-187``.  There,
"convert the network" mutates ``nn.Module`` objects in place; here models
are (apply_fn, params) pairs, so every helper is a pure function over a
pytree or a thin wrapper returning a new apply_fn.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..amp import policy as _policy
from ..multi_tensor import multi_tensor_l2norm, multi_tensor_scale


def _is_float(x):
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def to_bf16(value):
    """Cast every floating leaf to bfloat16 (reference ``tofp16`` module,
    fp16util.py:7-15 — a module that halves its input)."""
    return _policy.to_type(jnp.bfloat16, value)


#: fp16 name kept for drop-in reference compatibility; on TPU "half" = bf16.
to_half = to_bf16


def BN_convert_float(params, norm_predicate=None):
    """Return ``params`` with normalization-layer leaves cast back to fp32
    (reference ``BN_convert_float`` fp16util.py:17-32: BatchNorm modules with
    affine params revert to float for cuDNN; here the constraint is numeric
    only — norm scale/bias stay fp32 for stable statistics)."""
    pred = norm_predicate or _policy.default_norm_predicate
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = [x.astype(jnp.float32)
           if _is_float(x) and pred(_policy._path_str(path)) else x
           for path, x in flat]
    return jax.tree_util.tree_unflatten(treedef, out)


def convert_module(params, dtype):
    """Cast every floating leaf to ``dtype`` (reference ``convert_module``
    fp16util.py:34-52, minus the buffer special cases that don't exist in a
    pytree world)."""
    return _policy.to_type(dtype, params)


def convert_network(params, dtype, norm_predicate=None):
    """Cast the model to ``dtype`` keeping norm affine params fp32 —
    reference ``convert_network`` fp16util.py:74-86, the exact routine amp O2
    uses (``_initialize.py:173-176``)."""
    return _policy.convert_params(params, dtype, keep_norm_fp32=True,
                                  norm_predicate=norm_predicate)


def network_to_half(apply_fn: Callable, params) -> Tuple[Callable, Any]:
    """Return ``(bf16_apply_fn, bf16_params)``: inputs are cast to bf16 on the
    way in and the computation runs in bf16 (reference ``network_to_half``
    fp16util.py:54-61 = ``Sequential(tofp16(), network.half())``)."""
    new_params = convert_network(params, jnp.bfloat16)

    def bf16_apply(p, *args, **kwargs):
        args = _policy.to_type(jnp.bfloat16, args)
        return apply_fn(p, *args, **kwargs)

    return bf16_apply, new_params


class BF16Model:
    """Callable bundling a bf16-converted network (reference ``FP16Model``
    fp16util.py:88-102)."""

    def __init__(self, apply_fn: Callable, params):
        self.apply_fn, self.params = network_to_half(apply_fn, params)

    def __call__(self, *args, **kwargs):
        return self.apply_fn(self.params, *args, **kwargs)


FP16Model = BF16Model


def prep_param_lists(params, flat_master: bool = False):
    """Return ``(model_params, master_params)`` — fp32 master copies of the
    model's (possibly bf16) params (reference ``prep_param_lists``
    fp16util.py:104-134).

    With ``flat_master=True`` the master is ONE flat fp32 vector (reference
    flattens via ``_flatten_dense_tensors``); here we concatenate raveled
    leaves — XLA fuses the unflatten-copy back, so the flat form costs
    nothing extra on TPU and gives O(1)-launch full-model ops.
    """
    if flat_master:
        leaves = [x.astype(jnp.float32).ravel()
                  for x in jax.tree_util.tree_leaves(params) if _is_float(x)]
        master = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
        return params, master
    master = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32) if _is_float(x) else x, params)
    return params, master


def _unflatten_like(flat, tree):
    """Split a flat vector back into the float-leaf structure of ``tree``."""
    flat_leaves, treedef = jax.tree_util.tree_flatten(tree)
    out, off = [], 0
    for x in flat_leaves:
        if _is_float(x):
            n = x.size
            out.append(flat[off:off + n].reshape(x.shape))
            off += n
        else:
            out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def model_grads_to_master_grads(model_grads, flat_master: bool = False):
    """bf16 model grads → fp32 master grads (reference fp16util.py:136-156).
    Returns the fp32 grad pytree (or flat vector)."""
    if flat_master:
        leaves = [g.astype(jnp.float32).ravel()
                  for g in jax.tree_util.tree_leaves(model_grads)
                  if _is_float(g)]
        return jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
    out, _ = multi_tensor_scale(model_grads, 1.0, out_dtype=jnp.float32)
    return out


def master_params_to_model_params(model_params, master_params,
                                  flat_master: bool = False):
    """fp32 masters → model-dtype params (reference fp16util.py:158-173);
    returns the updated model param pytree."""
    if flat_master:
        master_tree = _unflatten_like(master_params, model_params)
    else:
        master_tree = master_params
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype) if _is_float(p) else p,
        master_tree, model_params)


def clip_grad_norm(grads, max_norm, norm_type: float = 2.0):
    """Global-norm clip over the grad pytree; returns ``(clipped_grads,
    total_norm)``.  Reference aliases ``torch.nn.utils.clip_grad_norm``
    (fp16util.py:180-187); FP16_Optimizer.clip_master_grads uses it."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if _is_float(g)]
    if norm_type == 2.0:
        total = multi_tensor_l2norm(grads)
    elif norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in leaves])) ** (1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    clipped = jax.tree_util.tree_map(
        lambda g: (g * scale).astype(g.dtype) if _is_float(g) else g, grads)
    return clipped, total
