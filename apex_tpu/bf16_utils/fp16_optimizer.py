"""FP16_Optimizer — the general legacy master-weight wrapper.

Re-design of reference ``apex/fp16_utils/fp16_optimizer.py:13-643``: wraps
any ``apex_tpu.optimizers.FusedOptimizer`` (or a functional optimizer pair)
with fp32 master weights, manual loss scaling, overflow skip-step, and
gradient clipping.

Reference flow preserved:

* ``backward(grads)``  — deliver grads of the *scaled* loss; fused
  scale-and-copy into fp32 master grads with device-side overflow flag
  (reference ``backward`` :462-524 + ``update_master_grads`` :525-580).
* ``step()``           — skip on overflow, update dynamic scale
  (reference :361-422).
* ``clip_master_grads(max_norm)`` (reference :424-446).
* ``state_dict``/``load_state_dict`` incl. scaler state (reference :448-512).

The TPU-first difference: masters are the single fp32 source of truth and
model params are a cast view produced after each step — no flat-buffer
machinery, XLA fuses the whole update into one program.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..amp.loss_scaler import all_finite
from .bf16util import (clip_grad_norm, master_params_to_model_params,
                       model_grads_to_master_grads, prep_param_lists)
from .loss_scaler import DynamicLossScaler, LossScaler


class FP16_Optimizer:
    def __init__(self, init_optimizer,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = True):
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.first_closure_call_this_step = True
        self.verbose = verbose

        # fp32 masters shadow the (possibly bf16) model params.
        self.model_params, self.master_params = prep_param_lists(
            init_optimizer.params)
        # The wrapped optimizer updates the masters.
        self.optimizer.params = self.master_params
        self.optimizer.state = [
            self.optimizer._init_state(p, g) for p, g in
            zip(self.optimizer._to_groups(self.master_params),
                self.optimizer.param_groups)]
        self._master_grads = None

    # -- loss / backward ----------------------------------------------------
    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    def scale_loss(self, loss):
        """Multiply the loss by the current scale (use inside your grad fn);
        the reference's ``backward(loss)`` does ``loss*scale`` then
        ``.backward()`` (:473-495)."""
        return jnp.asarray(loss, jnp.float32) * self.loss_scaler.loss_scale

    def backward(self, model_grads, update_master_grads: bool = True):
        """Deliver grads of the scaled loss w.r.t. the *model* params."""
        self._model_grads = model_grads
        if update_master_grads:
            self.update_master_grads()

    def update_master_grads(self):
        """Unscale model grads into fp32 master grads; set ``self.overflow``
        (reference ``update_master_grads`` :525-580 — fused
        multi_tensor_scale path when available)."""
        grads = self._model_grads
        self.overflow = self.loss_scaler.has_overflow(grads) \
            if isinstance(self.loss_scaler, DynamicLossScaler) else False
        inv = 1.0 / self.loss_scaler.loss_scale
        master_grads = model_grads_to_master_grads(grads)
        self._master_grads = jax.tree_util.tree_map(
            lambda g: g * inv, master_grads)

    def clip_master_grads(self, max_norm, norm_type=2.0):
        """Clip fp32 master grads by global norm; returns the pre-clip norm
        (reference :424-446)."""
        if self._master_grads is None:
            return 0.0
        self._master_grads, total = clip_grad_norm(
            self._master_grads, max_norm, norm_type)
        return float(jax.device_get(total))  # jaxlint: disable=J001 -- reference API returns the norm as a Python float for LR-schedule consumers

    # -- step ---------------------------------------------------------------
    def step(self, closure=None):
        if closure is not None:
            closure()
        if self.overflow:
            if self.verbose:
                print("OVERFLOW! Skipping step. Reducing loss scale to "
                      f"{self.loss_scaler.loss_scale / self.loss_scaler.scale_factor}")
            self.loss_scaler.update_scale(True)
            self._master_grads = None
            return
        if isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler.update_scale(False)
        self.optimizer.step(grads=self._master_grads)
        self.master_params = self.optimizer.params
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params)
        self._master_grads = None

    def zero_grad(self, set_grads_to_None: bool = True):
        self._master_grads = None
        self._model_grads = None

    # -- checkpointing ------------------------------------------------------
    def state_dict(self):
        sd = {
            "loss_scaler_scale": self.loss_scaler.loss_scale,
            "dynamic": isinstance(self.loss_scaler, DynamicLossScaler),
            "overflow": self.overflow,
            "first_closure_call_this_step": self.first_closure_call_this_step,
            "optimizer_state_dict": self.optimizer.state_dict(),
            "master_params": jax.device_get(self.master_params),
        }
        if sd["dynamic"]:
            sd["cur_iter"] = self.loss_scaler.cur_iter
            sd["last_overflow_iter"] = self.loss_scaler.last_overflow_iter
        return sd

    def load_state_dict(self, sd):
        if sd["dynamic"] and isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler.cur_scale = sd["loss_scaler_scale"]
            self.loss_scaler.cur_iter = sd["cur_iter"]
            self.loss_scaler.last_overflow_iter = sd["last_overflow_iter"]
        elif not sd["dynamic"]:
            self.loss_scaler.cur_scale = sd["loss_scaler_scale"]
        self.overflow = sd["overflow"]
        self.first_closure_call_this_step = sd["first_closure_call_this_step"]
        self.optimizer.load_state_dict(sd["optimizer_state_dict"])
        self.master_params = jax.tree_util.tree_map(
            jnp.asarray, sd["master_params"])
        self.optimizer.params = self.master_params
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params)

    # Reference property passthroughs (:586-643).
    @property
    def state(self):
        return self.optimizer.state

    @property
    def param_groups(self):
        return self.optimizer.param_groups
