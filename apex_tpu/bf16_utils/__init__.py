"""apex_tpu.bf16_utils — the legacy manual mixed-precision API.

TPU-native re-design of reference ``apex/fp16_utils/`` (fp16util.py,
fp16_optimizer.py, loss_scaler.py).  On TPU the reduced precision is
bfloat16, so this package is named ``bf16_utils``; ``apex_tpu.fp16_utils``
is an alias so reference user code imports keep working.
"""

from .bf16util import (  # noqa: F401
    to_bf16, to_half, BN_convert_float, network_to_half, convert_module,
    convert_network, BF16Model, FP16Model, prep_param_lists,
    model_grads_to_master_grads, master_params_to_model_params,
    clip_grad_norm,
)
from .loss_scaler import LossScaler, DynamicLossScaler   # noqa: F401
from .fp16_optimizer import FP16_Optimizer               # noqa: F401
