"""Input pipeline: multi-worker host input engine + async device staging.

The reference's imagenet example leans on NVIDIA DALI / pinned-memory
``data_prefetcher`` (examples/imagenet/main_amp.py:262-310: CUDA-stream
prefetch overlapping H2D copies with compute).  The TPU-native
equivalent, rebuilt as a worker-pool pipeline (ISSUE 3 — PR 2 closed the
device-side dispatch gap; this module closes the host input gap that
moved the bottleneck here):

* ``workers`` threads each assemble WHOLE batches ahead (pull a task
  from the shared source under a lock, run the heavy ``transform`` —
  decode / augment / stack — in parallel, no per-batch map barrier);
* a dedicated staging thread ``jax.device_put``s finished host batches
  in order (or completion order under ``ordered=False``) so the H2D DMA
  of batch N+1 overlaps the device work on batch N (the
  ``record_stream`` trick is XLA's job) — double-buffered: up to
  ``depth`` staged device batches wait ahead of the consumer while up
  to ``workers + depth`` host batches wait ahead of the stager;
* bounded queues apply back-pressure end to end;
* :class:`LoaderStats` counts queue depth, producer stall, and consumer
  wait, so "the input engine is the bottleneck" is an attributed number
  (``loader_stall_pct``) exported to ``bench.py`` and the prof ledger
  instead of a steady-vs-best-window mystery.

The heavy per-pixel work stays native C++ (:mod:`apex_tpu.native`):
normalize (:func:`normalize_images`), the fused crop/flip/normalize
augmentation epilogue (:func:`augment_images`), and counter-based
synthetic generation (:func:`synthetic_imagenet`).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import (Callable, Iterator, NamedTuple, Optional, Sequence,
                    Tuple, Union)

import jax
import numpy as np

from . import native
from . import telemetry as _telemetry

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

_THREAD_NAME = "apex-tpu-prefetch"


def normalize_images(u8_batch: np.ndarray,
                     mean: Sequence[float] = IMAGENET_MEAN,
                     std: Sequence[float] = IMAGENET_STD) -> np.ndarray:
    """uint8 NHWC -> normalized float32 NHWC via the native runtime."""
    return native.u8_to_f32_nhwc(u8_batch, mean, std)


def augment_images(u8_batch: np.ndarray, out_size: int,
                   rng: np.random.RandomState,
                   flip: bool = True,
                   mean: Sequence[float] = IMAGENET_MEAN,
                   std: Sequence[float] = IMAGENET_STD) -> np.ndarray:
    """Random-crop + random-horizontal-flip + normalize, fused into ONE
    native pass (:func:`apex_tpu.native.crop_flip_normalize`) — the
    train-time augmentation epilogue the reference delegates to DALI.
    Only the tiny per-image offsets/flip draws run in Python."""
    n, h, w, _ = u8_batch.shape
    offsets = np.stack([rng.randint(0, h - out_size + 1, n),
                        rng.randint(0, w - out_size + 1, n)],
                       axis=1).astype(np.int32)
    flips = (rng.rand(n) < 0.5).astype(np.uint8) if flip \
        else np.zeros(n, np.uint8)
    return native.crop_flip_normalize(u8_batch, out_size, offsets, flips,
                                      mean, std)


class LoaderError:
    """Producer-side exception in transit to the consumer.

    A dedicated wrapper class, NOT a ``("__error__", e)`` tuple: a
    legitimate 2-tuple batch whose first leaf is a numpy array made the
    old string comparison warn (elementwise ``==``) and could collide
    outright (ISSUE 3 satellite)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class LoaderStats:
    """Thread-safe input-engine counters (all seconds unless noted).

    * ``produce_s``     — worker time inside ``transform`` (sum over
      workers; can exceed wall time when workers > 1);
    * ``producer_stall_s`` — worker time blocked on back-pressure (the
      consumer/stager is the bottleneck — a HEALTHY pipeline stalls
      here);
    * ``stage_s``       — staging-thread time in ``jax.device_put``
      dispatch;
    * ``consumer_wait_s`` — consumer time blocked on an empty delivery
      queue (the LOADER is the bottleneck — this is the time the train
      loop loses to input);
    * ``batches``, ``mean_queue_depth`` — delivery count and the mean
      staged-queue depth observed at delivery.

    ``snapshot()["loader_stall_pct"]`` = consumer wait as a percent of
    wall time since the first delivery — the per-example number
    ``bench.py`` reports and the steady-vs-best-window gap decomposes
    against.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self.batches = 0
        self.staged = 0
        self.produce_s = 0.0
        self.producer_stall_s = 0.0
        self.stage_s = 0.0
        self.consumer_wait_s = 0.0
        self._depth_sum = 0
        self._depth_samples = 0

    def _add(self, field: str, dt: float) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + dt)

    def _start(self) -> None:
        # Clock starts when the consumer STARTS consuming (so the
        # pipeline-fill wait for the first batch counts as stall time
        # against a matching elapsed window — stall can't exceed 100%).
        with self._lock:
            if self._t0 is None:
                self._t0 = time.perf_counter()

    def _delivered(self, qdepth: int) -> None:
        with self._lock:
            self.batches += 1
            self._depth_sum += qdepth
            self._depth_samples += 1

    def _staged_one(self) -> None:
        # Staged, not delivered: the stager runs up to ``depth`` ahead
        # and keeps staging batches the consumer may abandon — staging
        # BANDWIDTH must divide stage_s by THIS count, not ``batches``.
        with self._lock:
            self.staged += 1

    def as_dict(self) -> dict:
        """ONE consistent read of every counter plus derived percentages,
        taken under the stats lock — the single snapshot both
        :func:`format_loader_line` and the telemetry recorder consume
        (ISSUE 5 satellite: field-by-field reads could tear under the
        worker pool — e.g. a ``consumer_wait_s`` from one delivery paired
        with an ``elapsed_s`` from the next)."""
        with self._lock:
            elapsed = (time.perf_counter() - self._t0) if self._t0 else 0.0
            depth = (self._depth_sum / self._depth_samples
                     if self._depth_samples else 0.0)
            return {
                "batches": self.batches,
                "staged": self.staged,
                "elapsed_s": round(elapsed, 3),
                "produce_s": round(self.produce_s, 3),
                "producer_stall_s": round(self.producer_stall_s, 3),
                "stage_s": round(self.stage_s, 3),
                "consumer_wait_s": round(self.consumer_wait_s, 3),
                "mean_queue_depth": round(depth, 2),
                "loader_stall_pct": (
                    round(100.0 * self.consumer_wait_s / elapsed, 2)
                    if elapsed > 0 else 0.0),
            }

    def snapshot(self) -> dict:
        """Alias of :meth:`as_dict` (the historical name; both return the
        same single consistent read)."""
        return self.as_dict()


def format_loader_line(stats: dict) -> str:
    """The one-line loader report the examples print and ``bench.py``
    parses (keep the ``loader: stall X%`` prefix stable)."""
    return (f"loader: stall {stats['loader_stall_pct']:.2f}% "
            f"wait {stats['consumer_wait_s']:.2f}s "
            f"produce {stats['produce_s']:.2f}s "
            f"stage {stats['stage_s']:.2f}s "
            f"depth {stats['mean_queue_depth']:.1f} "
            f"over {stats['batches']} batches")


class PrefetchLoader:
    """Wrap any iterable of host batches with a worker-pool prefetch
    pipeline + N-deep async device staging (the ``data_prefetcher`` /
    DALI-worker analog).

    * ``workers`` threads pull items off the shared source iterator
      (serialized by a lock — keep the source cheap and put the heavy
      decode/augment/stack in ``transform``, which runs in parallel);
    * finished host batches enter a reorder buffer; a staging thread
      ``jax.device_put``s them (to ``device``, which may be a
      ``Sharding``) and feeds a bounded queue of ``depth`` staged
      device batches;
    * ``ordered=True`` (default) delivers in source order; ``False``
      delivers in completion order (lower latency when batch cost is
      skewed — a slow decode no longer convoys the fast ones).

    Error contract: a producer-side exception (source or transform) is
    delivered IN PLACE of its batch as a :class:`LoaderError` and
    re-raised in the consumer after every earlier batch (ordered mode),
    preserving the original exception object.

    Shutdown contract: abandoning iteration (``break``, dropping the
    iterator) trips the stop event in the generator's ``finally`` — all
    threads exit and staged device batches are dropped.  :meth:`close`
    does the same explicitly (and joins the threads) for deterministic
    teardown; the loader is also a context manager."""

    def __init__(self, it, depth: int = 2,
                 transform: Optional[Callable] = None,
                 device=None, workers: int = 1, ordered: bool = True,
                 telemetry=None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self._it = it
        self._depth = max(1, depth)
        self._transform = transform
        self._device = device
        self._workers = workers
        self._ordered = ordered
        self.stats = LoaderStats()
        self._live: list = []  # (stop Event, [Thread], Queue, sentinel)
        # Telemetry (ISSUE 5): explicit Recorder, or None to defer to the
        # active one per event.  Events ride the loader's own threads;
        # with no recorder installed every site is one global read.
        self._telemetry = telemetry

    def _rec(self):
        return (self._telemetry if self._telemetry is not None
                else _telemetry.get_recorder())

    def _emit_loader_snapshot(self, phase: str) -> None:
        """One ``loader`` event carrying the SAME consistent
        ``LoaderStats.as_dict()`` snapshot the examples print — the
        analyzer's stall attribution therefore agrees with
        ``format_loader_line`` by construction."""
        rec = self._rec()
        if rec is not None:
            stats = self.stats.as_dict()
            rec.event("loader", phase=phase, stats=stats)
            # live gauge for the Prometheus exporter (ISSUE 10): the
            # same number the examples print and bench parses.
            rec.metrics.gauge("loader_stall_pct").set(
                stats["loader_stall_pct"])

    def close(self) -> None:
        """Release every pipeline this loader started: set the stop
        events, drain the queues (dropping any staged device batches so
        their HBM frees), and join the threads."""
        live, self._live = self._live, []
        if live:
            self._emit_loader_snapshot("close")
        for stop, threads, q, sentinel in live:
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            for t in threads:
                t.join(timeout=5)
            # A put that was already in flight when the drain above ran
            # can land between drain and thread exit — sweep once more
            # now the producers are provably done.
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # The stager's own end-of-stream put is suppressed once stop
            # is set, so re-arm the sentinel: a consumer blocked in (or
            # returning to) ``q.get()`` sees StopIteration instead of
            # hanging on an empty queue with dead producers.
            try:
                q.put_nowait(sentinel)
            except queue.Full:
                pass

    def state_dict(self) -> dict:
        """Resume state of this loader (ISSUE 9): ``delivered`` counts
        the batches the CONSUMER actually received — the prefetch
        pipeline runs ahead of it, so the source's own cursor includes
        in-flight batches that were pulled but never trained on.  When
        the source implements the resume protocol
        (:class:`DirectoryImagenet`), ``source`` carries its
        ``state_dict(consumed=delivered)`` — i.e. the source state
        rewound to the delivery boundary; rebuild the stream, ``resume``
        it with that dict, and wrap it in a fresh loader.

        Requires ``ordered=True``: under completion-order delivery the
        delivered batches are NOT a prefix of the source order, so no
        integer cursor can rewind to the delivery boundary — resuming
        from one would skip undelivered early batches and replay
        delivered ones.  Raises instead of silently losing data."""
        if not self._ordered:
            raise ValueError(
                "PrefetchLoader.state_dict() needs ordered=True: "
                "completion-order delivery has no prefix cursor, so a "
                "delivered-count resume would skip in-flight batches "
                "and replay delivered ones — run resumable jobs with "
                "ordered delivery")
        delivered = self.stats.batches
        out = {"delivered": int(delivered)}
        sd = getattr(self._it, "state_dict", None)
        if sd is not None:
            try:
                out["source"] = sd(consumed=delivered)
            except TypeError:       # source counts items itself
                out["source"] = sd()
        return out

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __iter__(self) -> Iterator:
        depth, workers = self._depth, self._workers
        transform, ordered = self._transform, self._ordered
        stats = self.stats
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        _SENTINEL = object()
        stop = threading.Event()
        src = iter(self._it)
        src_lock = threading.Lock()
        cond = threading.Condition()
        # Shared pipeline state, all guarded by ``cond``:
        #   seq      — next sequence number the source will hand out
        #   done     — seq count at exhaustion (None while streaming)
        #   ready    — {seq: host batch | LoaderError} awaiting staging
        #   staged_n — batches the stager has popped from ``ready``
        st = {"seq": 0, "done": None, "ready": {}, "staged_n": 0}
        # Workers may run at most this far ahead of the stager: W
        # in-flight + a stage-ready cushion — with the ``depth`` staged
        # device batches in ``q`` this bounds end-to-end buffering.
        lookahead = workers + depth

        def _put(item) -> bool:
            # Bounded put that gives up when the consumer is gone, so an
            # abandoned iterator can't pin threads + device batches.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            while not stop.is_set():
                with cond:
                    while (st["seq"] - st["staged_n"] >= lookahead
                           and not stop.is_set()):
                        t0 = time.perf_counter()
                        cond.wait(0.1)
                        stats._add("producer_stall_s",
                                   time.perf_counter() - t0)
                    if stop.is_set():
                        return
                with src_lock:
                    with cond:
                        if st["done"] is not None:
                            return
                    seq = st["seq"]
                    try:
                        item = next(src)
                    except StopIteration:
                        with cond:
                            st["done"] = seq
                            cond.notify_all()
                        return
                    except BaseException as e:
                        with cond:
                            st["ready"][seq] = LoaderError(e)
                            st["done"] = seq + 1
                            st["seq"] = seq + 1
                            cond.notify_all()
                        return
                    st["seq"] = seq + 1
                out = item
                if transform is not None:
                    t0 = time.perf_counter()
                    try:
                        out = transform(item)
                    except BaseException as e:
                        out = LoaderError(e)
                    stats._add("produce_s", time.perf_counter() - t0)
                with cond:
                    st["ready"][seq] = out
                    cond.notify_all()

        def stage():
            while not stop.is_set():
                item, got, exhausted, seq_no = None, False, False, None
                with cond:
                    while not stop.is_set():
                        ready = st["ready"]
                        if ordered:
                            if st["staged_n"] in ready:
                                item, got = ready.pop(st["staged_n"]), True
                                break
                        elif ready:
                            item, got = ready.pop(min(ready)), True
                            break
                        if st["done"] is not None \
                                and st["staged_n"] >= st["done"]:
                            exhausted = True
                            break
                        cond.wait(0.1)
                    if stop.is_set():
                        return
                    if got:
                        seq_no = st["staged_n"]
                        st["staged_n"] += 1
                        cond.notify_all()
                if exhausted:       # put OUTSIDE cond: it can block on a
                    _put(_SENTINEL)  # full queue and must not convoy the
                    return           # workers' cond waits
                if isinstance(item, LoaderError):
                    _put(item)
                    _put(_SENTINEL)
                    return
                t0 = time.perf_counter()
                # The one sanctioned per-batch host->device staging
                # point: every downstream consumer gets batches already
                # on device, asynchronously, ``depth`` ahead.  A staging
                # failure (device OOM, unsupported leaf) must travel the
                # error channel — an unhandled exception here would kill
                # the thread and leave the consumer blocked in q.get().
                try:
                    item = jax.tree_util.tree_map(
                        lambda x: jax.device_put(x, self._device)  # jaxlint: disable=J007 -- this IS the loader's async staging thread, where per-batch device_put belongs
                        if hasattr(x, "shape") else x, item)
                except BaseException as e:
                    _put(LoaderError(e))
                    _put(_SENTINEL)
                    return
                dt = time.perf_counter() - t0
                stats._add("stage_s", dt)
                stats._staged_one()
                rec = self._rec()
                if rec is not None:
                    # Runs on the staging thread — never on the hot loop.
                    rec.event("stage", seq=seq_no, dur=round(dt, 6))
                    rec.metrics.histogram("stage_s").observe(dt)
                if not _put(item):
                    return

        threads = [threading.Thread(target=work, daemon=True,
                                    name=f"{_THREAD_NAME}-w{i}")
                   for i in range(workers)]
        threads.append(threading.Thread(target=stage, daemon=True,
                                        name=_THREAD_NAME))
        for t in threads:
            t.start()
        handle = (stop, threads, q, _SENTINEL)
        self._live.append(handle)
        try:
            while True:
                stats._start()
                t0 = time.perf_counter()
                item = q.get()
                dt = time.perf_counter() - t0
                stats._add("consumer_wait_s", dt)
                if item is _SENTINEL:
                    self._emit_loader_snapshot("exhausted")
                    break
                if isinstance(item, LoaderError):
                    raise item.exc
                qdepth = q.qsize()
                stats._delivered(qdepth)
                rec = self._rec()
                if rec is not None:
                    rec.event("loader_wait", dur=round(dt, 6),
                              qdepth=qdepth)
                    rec.metrics.histogram("loader_wait_s").observe(dt)
                    rec.metrics.gauge("loader_queue_depth").set(qdepth)
                yield item
        finally:
            # GeneratorExit (break / del) lands here: release the pipeline.
            stop.set()
            with cond:
                cond.notify_all()
            while True:               # drain so the stager's put unblocks
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            if handle in self._live:
                self._live.remove(handle)


class BatchFiles(NamedTuple):
    """A lightweight batch descriptor: the files of one batch, undecoded.

    Yielded by :func:`directory_imagenet` with ``decode=False`` so the
    generator stays cheap under the :class:`PrefetchLoader` source lock
    and the heavy decode runs in the worker pool via
    :func:`load_batch` (typically inside a ``transform``).  ``seq`` is
    the batch's global sequence number (monotonic ACROSS epochs): mix it
    into any per-batch augmentation seed so a batch led by the same file
    in two epochs still draws fresh crops/flips.  ``seq`` equals the
    producing stream's cursor, so a resumed run
    (:meth:`DirectoryImagenet.resume`) re-yields the SAME descriptor —
    augment draws replay bit-identically (ISSUE 9)."""
    paths: Tuple[str, ...]
    labels: np.ndarray            # int32 [batch]
    image_size: int
    seq: int = 0


def _load_image(path: str, image_size: int) -> np.ndarray:
    if path.endswith(".npy"):
        img = np.load(path)
    else:
        from PIL import Image   # optional dep; gate at use time
        img = np.asarray(Image.open(path).convert("RGB"))
    if img.shape[:2] != (image_size, image_size):
        # nearest-neighbor resize without extra deps
        ys = (np.linspace(0, img.shape[0] - 1, image_size)).astype(int)
        xs = (np.linspace(0, img.shape[1] - 1, image_size)).astype(int)
        img = img[ys][:, xs]
    return img.astype(np.uint8)


def load_batch(task: BatchFiles) -> Tuple[np.ndarray, np.ndarray]:
    """Decode one :class:`BatchFiles` task into ``(uint8 NHWC batch,
    int32 labels)`` — the worker-pool half of the ``decode=False``
    protocol (PIL releases the GIL during decode, so N workers decode N
    batches concurrently)."""
    imgs = np.stack([_load_image(p, task.image_size) for p in task.paths])
    return imgs, task.labels


class DirectoryImagenet:
    """Resumable batch stream over an ImageNet-style directory —
    the class behind :func:`directory_imagenet` (ISSUE 9: deterministic
    full-run resume needs the input stream to be a *cursor* over a
    deterministic schedule, not an anonymous generator).

    Everything that determines the batch sequence is derived from the
    constructor arguments plus one integer — ``cursor``, the count of
    batches this host has already yielded.  Epoch index, the per-epoch
    shuffle (``RandomState(seed + epoch)``), the host-shard slice, and
    the global ``seq`` (== cursor, the augment-seed input) all fall out
    of it, so :meth:`state_dict` / :meth:`resume` round-trip a
    kill-and-resume run onto the bit-identical remaining stream — and
    :meth:`skip` fast-forwards by index math alone, no decode.

    Iteration semantics match the historical generator exactly: the
    object is its own single-pass iterator (``next()`` and ``for``
    share one position), ``close()`` releases the decode pool.
    """

    def __init__(self, root: str, batch_size: int, image_size: int = 224,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True, workers: int = 8,
                 epochs: Optional[int] = 1, decode: bool = True,
                 host_shard: Union[None, bool, Tuple[int, int]] = None):
        import os

        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class subdirectories under {root}")
        class_idx = {c: i for i, c in enumerate(classes)}
        samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                if f.lower().endswith((".npy", ".jpg", ".jpeg", ".png")):
                    samples.append((os.path.join(cdir, f), class_idx[c]))
        if not samples:
            raise ValueError(f"no samples under {root}")
        if host_shard is True:
            from .parallel.multiproc import process_identity
            host_shard = process_identity()
        if host_shard is not None:
            index, count = host_shard
            if not 0 <= index < count:
                raise ValueError(
                    f"host_shard index {index} not in [0, {count})")
        else:
            index, count = 0, 1
        self._samples = samples
        self.batch_size = int(batch_size)
        self.image_size = int(image_size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.drop_last = bool(drop_last)
        self.workers = int(workers)
        self.decode = bool(decode)
        self.host_shard = (index, count)
        self.epochs = epochs
        stop = (len(samples) - batch_size + 1) if drop_last \
            else len(samples)
        starts = range(0, stop, batch_size)
        # Truncate to a multiple of ``count`` batches so every host gets
        # EXACTLY the same number per epoch (SPMD lockstep: one extra
        # step on some hosts deadlocks the collectives at the epoch
        # boundary), then slice this host's every-count-th batch.
        usable = len(starts) - len(starts) % count
        self._local_starts = list(starts)[index:usable:count]
        #: local batches already yielded (this host's stream position;
        #: also the BatchFiles.seq of the NEXT batch).
        self.cursor = 0
        self._epoch_cached: Optional[int] = None
        self._epoch_samples = None
        self._pool = None
        self._closed = False

    # -- resume protocol ----------------------------------------------------
    @property
    def batches_per_epoch(self) -> int:
        return len(self._local_starts)

    def state_dict(self, consumed: Optional[int] = None) -> dict:
        """The stream's resume state.  ``consumed`` overrides the cursor
        with the count of batches the TRAINING LOOP has consumed — under
        a :class:`PrefetchLoader` the stream runs ahead by the prefetch
        depth, and resuming from the stream's own cursor would skip the
        in-flight batches that were pulled but never trained on."""
        cursor = self.cursor if consumed is None else int(consumed)
        return {"cursor": cursor, "seed": self.seed,
                "shuffle": self.shuffle,
                "batch_size": self.batch_size,
                "host_shard": list(self.host_shard),
                "batches_per_epoch": self.batches_per_epoch,
                "n_samples": len(self._samples)}

    def resume(self, state: dict) -> "DirectoryImagenet":
        """Position this stream at ``state``'s cursor.  The recorded
        schedule parameters must match this stream's — a resume against
        a different dataset/seed/shard layout would silently replay the
        WRONG batches, so it raises instead."""
        for key, mine in (("seed", self.seed), ("shuffle", self.shuffle),
                          ("batch_size", self.batch_size),
                          ("host_shard", list(self.host_shard)),
                          ("batches_per_epoch", self.batches_per_epoch),
                          ("n_samples", len(self._samples))):
            if key in state and state[key] != mine:
                raise ValueError(
                    f"loader resume mismatch: checkpoint {key}="
                    f"{state[key]!r}, stream has {mine!r} — the resumed "
                    f"stream must be built with the same dataset and "
                    f"schedule arguments as the saved run")
        self.cursor = int(state["cursor"])
        return self

    def skip(self, n_batches: int) -> "DirectoryImagenet":
        """Fast-forward ``n_batches`` (index math only — no decode)."""
        self.cursor += int(n_batches)
        return self

    # -- iteration ----------------------------------------------------------
    def _epoch_order(self, epoch: int):
        if self._epoch_cached != epoch:
            if self.shuffle:
                order = np.random.RandomState(
                    self.seed + epoch).permutation(len(self._samples))
                self._epoch_samples = [self._samples[i] for i in order]
            else:
                self._epoch_samples = self._samples
            self._epoch_cached = epoch
        return self._epoch_samples

    def _release_pool(self, wait: bool) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=wait)
            self._pool = None

    def close(self) -> None:
        """Release the decode pool (matches the old generator's
        ``close()``); iteration after close yields nothing."""
        self._closed = True
        self._release_pool(wait=False)

    def __iter__(self) -> "DirectoryImagenet":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        bpe = self.batches_per_epoch
        if bpe == 0 or (self.epochs is not None
                        and self.cursor >= self.epochs * bpe):
            # Exhaustion releases the decode threads like the old
            # generator's ExitStack did (long-lived jobs build a fresh
            # stream per epoch — idle pools must not accumulate); the
            # object stays usable: resume()/skip() back into range
            # lazily rebuilds the pool.
            self._release_pool(wait=True)
            raise StopIteration
        epoch, pos = divmod(self.cursor, bpe)
        epoch_samples = self._epoch_order(epoch)
        i = self._local_starts[pos]
        batch = epoch_samples[i:i + self.batch_size]
        labels = np.asarray([l for _, l in batch], np.int32)
        seq = self.cursor
        self.cursor += 1
        if not self.decode:
            return BatchFiles(tuple(p for p, _ in batch), labels,
                              self.image_size, seq)
        paths = (p for p, _ in batch)
        if self.workers > 1 and self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=self.workers)
        if self._pool is not None:
            imgs = np.stack(list(self._pool.map(
                lambda p: _load_image(p, self.image_size), paths)))
        else:
            imgs = np.stack([_load_image(p, self.image_size)
                             for p in paths])
        return imgs, labels


def directory_imagenet(root: str, batch_size: int, image_size: int = 224,
                       shuffle: bool = True, seed: int = 0,
                       drop_last: bool = True, workers: int = 8,
                       epochs: Optional[int] = 1, decode: bool = True,
                       host_shard: Union[None, bool,
                                         Tuple[int, int]] = None
                       ) -> DirectoryImagenet:
    """Stream batches from an ImageNet-style directory:
    ``root/<class_name>/*.{npy,jpg,jpeg,png}``.  ``.npy`` files must hold
    HWC uint8; JPEG/PNG files decode via PIL.  Returns a
    :class:`DirectoryImagenet` — iterate it like the historical
    generator, or drive the resume protocol
    (``state_dict()``/``resume()``/``skip()``) for deterministic
    kill-and-resume (ISSUE 9).

    * ``epochs`` — iterate the dataset this many times (``None`` =
      forever) with a fresh shuffle each epoch (``RandomState(seed +
      epoch)`` — deterministic, distinct per epoch); ``drop_last``
      applies per epoch, so every epoch yields the same number of
      full batches (ISSUE 3 satellite: the old generator was single-pass,
      shuffled once at construction).
    * ``decode=True`` — yields decoded ``(uint8 NHWC, int32 labels)``
      batches (``workers`` PIL threads per batch).  ``decode=False`` —
      yields cheap :class:`BatchFiles` descriptors instead; pair with
      :func:`load_batch` in a :class:`PrefetchLoader` ``transform`` so
      whole batches decode in parallel with no per-batch barrier.
    * ``host_shard`` — per-host sharded loading for the multichip path:
      ``(index, count)`` keeps every ``count``-th batch starting at
      ``index``; ``True`` derives them from ``jax.process_index() /
      jax.process_count()``.  Sharding is at BATCH granularity over the
      shared per-epoch shuffle (same seed on every host), so hosts see
      disjoint data and EXACTLY equal batch counts per epoch (a trailing
      remainder of < ``count`` batches is dropped on every host — the
      multi-host extension of ``drop_last``; one extra step on some
      hosts would deadlock the collectives).

    Honest scope note: the JPEG path is functional, not a DALI-class
    decode engine (the reference leans on DALI for full-rate ImageNet,
    ``examples/imagenet/main_amp.py:262-310``); the benchmarked input
    paths are ``.npy`` and :func:`synthetic_imagenet`."""
    return DirectoryImagenet(root, batch_size, image_size=image_size,
                             shuffle=shuffle, seed=seed,
                             drop_last=drop_last, workers=workers,
                             epochs=epochs, decode=decode,
                             host_shard=host_shard)


def synthetic_imagenet(batch_size: int, image_size: int = 224,
                       num_classes: int = 1000, steps: int = 100,
                       seed: int = 0):
    """Synthetic uint8 image stream (benchmarks / tests).

    Backed by the native counter-based generator
    (:func:`apex_tpu.native.synth_bytes`) — ~memory-bandwidth fill with
    zero GIL time, identical bytes on the numpy fallback tier — instead
    of Python-side ``np.random`` (ISSUE 3: the GIL-bound producer burn).
    Deterministic in ``(seed, step)``; labels come from the same
    splitmix lattice."""
    nbytes = batch_size * image_size * image_size * 3
    mask = 0xFFFFFFFFFFFFFFFF
    for step in range(steps):
        # Disjoint counter ranges per (seed, step): the label block
        # rides at the end of the image block.  Python-int arithmetic
        # mod 2**64 (numpy uint64 scalars warn on wrap).
        base = (seed * 0x9E3779B97F4A7C15
                + step * (nbytes // 8 + batch_size + 2)) & mask
        raw = native.synth_bytes(nbytes, base)
        imgs = raw.reshape(batch_size, image_size, image_size, 3)
        lab_base = (base + nbytes // 8 + 1) & mask
        with np.errstate(over="ignore"):
            lattice = (np.uint64(lab_base)
                       + np.arange(batch_size, dtype=np.uint64))
            labels = (native._splitmix64(lattice)
                      % np.uint64(num_classes)).astype(np.int32)
        yield imgs, labels
