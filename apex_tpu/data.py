"""Input pipeline: threaded host-side prefetch + native decode epilogue.

The reference's imagenet example leans on NVIDIA DALI / pinned-memory
``data_prefetcher`` (examples/imagenet/main_amp.py:262-310: CUDA-stream
prefetch overlapping H2D copies with compute).  The TPU-native equivalent:

* a background thread pool runs the batch producer (disk/decode/augment —
  the normalize epilogue in native C++, :func:`apex_tpu.native.
  u8_to_f32_nhwc`);
* finished host batches are ``jax.device_put`` eagerly so the H2D DMA
  overlaps the running step (the ``record_stream`` trick is XLA's job);
* a bounded queue applies back-pressure.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional, Sequence

import jax
import numpy as np

from . import native

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def normalize_images(u8_batch: np.ndarray,
                     mean: Sequence[float] = IMAGENET_MEAN,
                     std: Sequence[float] = IMAGENET_STD) -> np.ndarray:
    """uint8 NHWC -> normalized float32 NHWC via the native runtime."""
    return native.u8_to_f32_nhwc(u8_batch, mean, std)


class PrefetchLoader:
    """Wrap any iterable of host batches with N-deep device prefetch
    (the ``data_prefetcher`` analog).

    Shutdown contract: abandoning iteration (``break``, dropping the
    iterator) trips the stop event in the generator's ``finally`` —
    the producer thread exits and the queued device batches are
    dropped.  :meth:`close` does the same explicitly (and joins the
    threads) for deterministic teardown; the loader is also a context
    manager."""

    def __init__(self, it, depth: int = 2,
                 transform: Optional[Callable] = None,
                 device=None):
        self._it = it
        self._depth = depth
        self._transform = transform
        self._device = device
        self._live: list = []  # (stop Event, Thread, Queue, sentinel)

    def close(self) -> None:
        """Release every producer this loader started: set the stop
        events, drain the queues (dropping any staged device batches so
        their HBM frees), and join the threads."""
        live, self._live = self._live, []
        for stop, t, q, sentinel in live:
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)
            # A put that was already in flight when the drain above ran
            # can land between drain and thread exit — sweep once more
            # now the producer is provably done.
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # The producer's own end-of-stream put is suppressed once
            # stop is set, so re-arm the sentinel: a consumer blocked in
            # (or returning to) ``q.get()`` sees StopIteration instead
            # of hanging on an empty queue with a dead producer.
            try:
                q.put_nowait(sentinel)
            except queue.Full:
                pass

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __iter__(self) -> Iterator:
        q: "queue.Queue" = queue.Queue(maxsize=self._depth)
        _SENTINEL = object()
        stop = threading.Event()

        def _put(item) -> bool:
            # Bounded put that gives up when the consumer is gone, so an
            # abandoned iterator can't pin the thread + device batches.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for batch in self._it:
                    if stop.is_set():
                        return
                    if self._transform is not None:
                        batch = self._transform(batch)
                    batch = jax.tree_util.tree_map(
                        lambda x: jax.device_put(x, self._device)
                        if hasattr(x, "shape") else x, batch)
                    if not _put(batch):
                        return
            except BaseException as e:   # surface producer errors
                _put(("__error__", e))
            finally:
                _put(_SENTINEL)

        t = threading.Thread(target=produce, daemon=True,
                             name="apex-tpu-prefetch")
        t.start()
        handle = (stop, t, q, _SENTINEL)
        self._live.append(handle)
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "__error__":
                    raise item[1]
                yield item
        finally:
            # GeneratorExit (break / del) lands here: release the producer.
            stop.set()
            while True:               # drain so the thread's put unblocks
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            if handle in self._live:
                self._live.remove(handle)


def directory_imagenet(root: str, batch_size: int, image_size: int = 224,
                       shuffle: bool = True, seed: int = 0,
                       drop_last: bool = True, workers: int = 8):
    """Stream (uint8 NHWC batch, labels) from an ImageNet-style directory:
    ``root/<class_name>/*.{npy,jpg,jpeg,png}``.  ``.npy`` files must hold
    HWC uint8; JPEG/PNG files decode via PIL (``workers`` decoder threads
    per batch — PIL releases the GIL during decode).  The heavy epilogue
    (normalize) stays in :func:`normalize_images` (native C++).

    Honest scope note: the JPEG path is functional, not a DALI-class
    decode engine (the reference leans on DALI for full-rate ImageNet,
    ``examples/imagenet/main_amp.py:262-310``); the benchmarked input
    paths are ``.npy`` and :func:`synthetic_imagenet`.

    ``drop_last=True`` (default) discards a trailing partial batch — the
    static-shape-friendly choice for jit'd train steps; pass
    ``drop_last=False`` to also yield the final short batch."""
    import contextlib
    import os
    from concurrent.futures import ThreadPoolExecutor

    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise ValueError(f"no class subdirectories under {root}")
    class_idx = {c: i for i, c in enumerate(classes)}
    samples = []
    for c in classes:
        cdir = os.path.join(root, c)
        for f in os.listdir(cdir):
            if f.lower().endswith((".npy", ".jpg", ".jpeg", ".png")):
                samples.append((os.path.join(cdir, f), class_idx[c]))
    if not samples:
        raise ValueError(f"no samples under {root}")
    rng = np.random.RandomState(seed)
    if shuffle:
        rng.shuffle(samples)

    def load(path):
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            from PIL import Image   # optional dep; gate at use time
            img = np.asarray(Image.open(path).convert("RGB"))
        if img.shape[:2] != (image_size, image_size):
            # nearest-neighbor resize without extra deps
            ys = (np.linspace(0, img.shape[0] - 1, image_size)).astype(int)
            xs = (np.linspace(0, img.shape[1] - 1, image_size)).astype(int)
            img = img[ys][:, xs]
        return img.astype(np.uint8)

    stop = (len(samples) - batch_size + 1) if drop_last else len(samples)
    with contextlib.ExitStack() as stack:
        if workers > 1:
            pool = stack.enter_context(ThreadPoolExecutor(max_workers=workers))
            mapper = pool.map
        else:
            mapper = map
        for i in range(0, stop, batch_size):
            batch = samples[i:i + batch_size]
            imgs = np.stack(list(mapper(load, (p for p, _ in batch))))
            labels = np.asarray([l for _, l in batch], np.int32)
            yield imgs, labels


def synthetic_imagenet(batch_size: int, image_size: int = 224,
                       num_classes: int = 1000, steps: int = 100,
                       seed: int = 0):
    """Synthetic uint8 image stream (benchmarks / tests)."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        imgs = rng.randint(0, 256, (batch_size, image_size, image_size, 3),
                           dtype=np.uint8)
        labels = rng.randint(0, num_classes, (batch_size,))
        yield imgs, labels
