"""Warm-start engine: persistent compilation cache + AOT warmup (ISSUE 7).

Two cold-start taxes keep the examples' steady state from beginning at
step 1 (r05: imagenet 1530 img/s steady vs 2492 best-window, and the
``--prof`` best-window probes each pay fresh compiles):

* the **first-run compile** — tens of seconds of XLA backend work that
  re-runs on every process start even though nothing changed;
* the **step-0 trace+compile inside the timed loop** — the
  :class:`~apex_tpu.runtime.StepPipeline` device loop compiles on its
  first dispatch (and re-specializes on call 1 when the donated state
  returns with the mesh sharding), so the steady clock must exclude the
  first two calls.

This module removes both:

* :func:`enable` turns on jax's **persistent compilation cache** (an
  on-disk executable store keyed by HLO fingerprint): the second process
  start deserializes instead of recompiling — cold compiles are paid
  once per (program, jaxlib), not once per run.
* :func:`warmup` **AOT-compiles** a pipeline's device loop for the
  declared ``(K, shape)`` signatures BEFORE step 0 —
  ``jit(...).lower(shapes).compile()`` on abstract
  ``ShapeDtypeStruct``s, so no real data, no real step, no state
  mutation.  :meth:`StepPipeline.warmup
  <apex_tpu.runtime.StepPipeline.warmup>` stores the compiled
  executable and dispatches straight to it, bypassing the jit tracing
  machinery entirely: with a warm cache there are ZERO compiles (and
  zero traces) after step 0, which
  :func:`apex_tpu.prof.assert_trace_count` can pin.

Usage::

    import apex_tpu.cache
    apex_tpu.cache.enable("~/.cache/apex_tpu_xla")   # once, at startup

    pipe = runtime.StepPipeline(step_fn, k, ...)
    pipe.warmup(state, window)          # AOT: compile before step 0
    for window, n in windows:
        state, metrics = pipe.step_window(state, window, n)   # no compiles
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax

__all__ = ["enable", "is_enabled", "cache_dir", "abstractify",
           "signature", "warmup"]

_STATE = {"dir": None}


def enable(path: str, *,
           min_entry_size_bytes: int = -1,
           min_compile_time_secs: float = 0.0) -> str:
    """Enable jax's persistent compilation cache at ``path``.

    Creates the directory, points ``jax_compilation_cache_dir`` at it
    and drops the size/compile-time floors (both default to "cache
    everything": a train-step executable is always worth keeping; the
    defaults exist to keep tiny one-off programs out of shared caches).
    Falls back to the legacy ``initialize_cache`` API on old jax.
    Idempotent; returns the resolved directory.
    """
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if _STATE["dir"] not in (None, path):
            # The backend binds its store on first use; re-pointing the
            # config alone would silently keep writing to the old dir.
            try:
                from jax._src import compilation_cache as _cci
                _cci.reset_cache()
            except Exception:                    # pragma: no cover
                pass
    except AttributeError:                       # pragma: no cover
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.initialize_cache(path)
    # Cache-everything floors; individually best-effort (older jaxlibs
    # lack one or both knobs, and the defaults there already cache
    # training-sized programs).
    for name, val in (
            ("jax_persistent_cache_min_entry_size_bytes",
             min_entry_size_bytes),
            ("jax_persistent_cache_min_compile_time_secs",
             min_compile_time_secs)):
        try:
            jax.config.update(name, val)
        except (AttributeError, ValueError):     # pragma: no cover
            pass
    # The kernel autotuner's per-device config cache (ISSUE 14) lives
    # beside the compiled-executable store: one cache directory holds
    # both halves of warm start — programs AND the block configs the
    # programs were built with.
    try:
        from .tune import store as _tune_store
        _tune_store.set_default_dir(path)
    except Exception:                            # pragma: no cover
        pass
    _STATE["dir"] = path
    return path


def is_enabled() -> bool:
    return _STATE["dir"] is not None


def cache_dir() -> Optional[str]:
    """The directory :func:`enable` installed (None when disabled)."""
    return _STATE["dir"]


def abstractify(tree):
    """Pytree of ``ShapeDtypeStruct``s mirroring ``tree``'s arrays —
    shape, dtype AND sharding (jit specializes on all three; dropping
    the sharding would AOT-compile a program the real dispatch then
    can't use).  Non-array leaves (plain ints/bools) pass through and
    specialize the compile exactly like a real call."""
    def one(leaf):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            return leaf        # caller-declared template (sharding kept)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            # Pin only COMMITTED placements (device_put with an explicit
            # sharding — e.g. a mesh-staged batch window).  Uncommitted
            # arrays (fresh init output on the default device) must stay
            # unconstrained: pinning their incidental single-device
            # sharding next to a mesh-sharded window is a device-set
            # conflict at lower(), and the partitioner's free choice is
            # exactly what the real call gets.
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and getattr(leaf, "committed", False):
                try:
                    return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                                sharding=sharding)
                except TypeError:                # pragma: no cover
                    pass                         # old jax: no kwarg
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map(one, tree)


def signature(tree, limit: int = 16, *,
              static: Tuple = ()) -> Tuple[str, ...]:
    """Shape/dtype signature of a pytree's leading leaves — the AOT
    executable lookup key (matches the retrace-event signature the
    runtime emits, so telemetry and warmup agree on what "same window"
    means).

    ``static`` appends static parameters — ints/strs that specialize
    the compile but are not array leaves (ISSUE 11 satellite: the
    serving engine's sequence-length buckets) — so per-bucket
    executables key cleanly into one AOT table: two calls whose array
    signatures collide but whose bucket differs get distinct keys, and
    a bucket never warmed is a clean lookup MISS (the caller's jit
    fallback path), not a wrong-executable dispatch."""
    leaves = jax.tree_util.tree_leaves(tree)
    sig = tuple(f"{getattr(l, 'dtype', type(l).__name__)}"
                f"{list(getattr(l, 'shape', ()))}"
                for l in leaves[:limit])
    if static:
        sig = sig + tuple(f"static:{v!r}" for v in static)
    return sig


def warmup(jitted, *args) -> Any:
    """AOT-compile ``jitted`` (a ``jax.jit`` callable) for ``args``'
    signature: ``lower().compile()`` over :func:`abstractify`-ed
    arguments.  Nothing executes and nothing is donated — ``args`` may
    be live training state.  Returns the compiled executable; call it
    with concrete arrays of the same signature to bypass tracing
    entirely.  With the persistent cache :func:`enable`-d, the backend
    compile inside is itself a disk hit on the second process start.
    """
    return jitted.lower(*abstractify(args)).compile()
