// apex_tpu native runtime — host-side hot loops, C ABI for ctypes.
//
// TPU-native equivalent of the reference's native runtime layer
// (csrc/flatten_unflatten.cpp: apex_C flatten/unflatten backing DDP's flat
// comm buffers).  On TPU the *device* flat buffers dissolve into XLA, but
// the host side keeps two hot loops worth native code:
//
//  * flatten/unflatten of parameter sets for checkpoint/restore and
//    host<->device staging (multi-threaded memcpy, saturates DRAM b/w);
//  * the input-pipeline decode epilogue: uint8 HWC image -> normalized
//    float32/bfloat16 NHWC batch (the data-loader bottleneck the reference
//    delegates to DALI in examples/imagenet).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread (see native.py).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

// Run fn(i) for i in [0, n) over up to `threads` workers.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int nt = static_cast<int>(
      std::max<int64_t>(1, std::min<int64_t>(threads, n)));
  if (nt == 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt);
  std::int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([=]() { for (int64_t i = lo; i < hi; ++i) fn(i); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Pack n buffers (byte sizes in `sizes`) into contiguous dst.
// Offsets are the prefix sums; copies run in parallel per tensor.
void apex_flatten(const void** srcs, const int64_t* sizes, int64_t n,
                  void* dst, int threads) {
  std::vector<int64_t> offs(n);
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// Inverse of apex_flatten.
void apex_unflatten(const void* src, const int64_t* sizes, int64_t n,
                    void** dsts, int threads) {
  std::vector<int64_t> offs(n);
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], static_cast<const char*>(src) + offs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// uint8 NHWC images -> float32 NHWC, (x/255 - mean[c]) / std[c].
// n_img images of h*w*c bytes each; parallel over images.
void apex_u8_to_f32_nhwc(const uint8_t* src, float* dst, int64_t n_img,
                         int64_t hw, int64_t c, const float* mean,
                         const float* stddev, int threads) {
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    bias[ch] = -mean[ch] / stddev[ch];
  }
  parallel_for(n_img, threads, [&](int64_t i) {
    const uint8_t* s = src + i * hw * c;
    float* d = dst + i * hw * c;
    for (int64_t p = 0; p < hw; ++p) {
      for (int64_t ch = 0; ch < c; ++ch) {
        d[p * c + ch] = s[p * c + ch] * scale[ch] + bias[ch];
      }
    }
  });
}

// Simple checksum used by tests to verify the library loaded correctly.
int64_t apex_runtime_abi_version() { return 1; }

}  // extern "C"
