// apex_tpu native runtime — host-side hot loops, C ABI for ctypes.
//
// TPU-native equivalent of the reference's native runtime layer
// (csrc/flatten_unflatten.cpp: apex_C flatten/unflatten backing DDP's flat
// comm buffers).  On TPU the *device* flat buffers dissolve into XLA, but
// the host side keeps two hot loops worth native code:
//
//  * flatten/unflatten of parameter sets for checkpoint/restore and
//    host<->device staging (multi-threaded memcpy, saturates DRAM b/w);
//  * the input-pipeline decode epilogue: uint8 HWC image -> normalized
//    float32/bfloat16 NHWC batch (the data-loader bottleneck the reference
//    delegates to DALI in examples/imagenet);
//  * the fused augmentation epilogue (crop + horizontal flip + normalize
//    in ONE pass over the pixels — three numpy passes otherwise);
//  * a counter-based synthetic-batch generator (splitmix64 per 8-byte
//    block): benchmark input generation without burning the GIL on
//    Python-side np.random (ISSUE 3 — the imagenet synthetic pool).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread (see native.py).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

// Run fn(i) for i in [0, n) over up to `threads` workers.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int nt = static_cast<int>(
      std::max<int64_t>(1, std::min<int64_t>(threads, n)));
  if (nt == 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt);
  std::int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([=]() { for (int64_t i = lo; i < hi; ++i) fn(i); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Pack n buffers (byte sizes in `sizes`) into contiguous dst.
// Offsets are the prefix sums; copies run in parallel per tensor.
void apex_flatten(const void** srcs, const int64_t* sizes, int64_t n,
                  void* dst, int threads) {
  std::vector<int64_t> offs(n);
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// Inverse of apex_flatten.
void apex_unflatten(const void* src, const int64_t* sizes, int64_t n,
                    void** dsts, int threads) {
  std::vector<int64_t> offs(n);
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], static_cast<const char*>(src) + offs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// uint8 NHWC images -> float32 NHWC, (x/255 - mean[c]) / std[c].
// n_img images of h*w*c bytes each; parallel over images.
void apex_u8_to_f32_nhwc(const uint8_t* src, float* dst, int64_t n_img,
                         int64_t hw, int64_t c, const float* mean,
                         const float* stddev, int threads) {
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    bias[ch] = -mean[ch] / stddev[ch];
  }
  parallel_for(n_img, threads, [&](int64_t i) {
    const uint8_t* s = src + i * hw * c;
    float* d = dst + i * hw * c;
    for (int64_t p = 0; p < hw; ++p) {
      for (int64_t ch = 0; ch < c; ++ch) {
        d[p * c + ch] = s[p * c + ch] * scale[ch] + bias[ch];
      }
    }
  });
}

// Counter-based synthetic byte stream: block i of 8 bytes is
// splitmix64(seed + i), so generation is embarrassingly parallel, and
// the numpy fallback (same recurrence on a uint64 lattice) produces
// bit-identical output — the two-tier install contract for synthetic
// data.  Little-endian byte order (x86/ARM hosts; asserted in native.py).
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

void apex_synth_u8(uint8_t* dst, int64_t nbytes, uint64_t seed,
                   int threads) {
  int64_t blocks = (nbytes + 7) / 8;
  // Chunk blocks so parallel_for's per-index lambda call doesn't
  // dominate; each task fills a contiguous ~64 KB span.
  const int64_t kSpan = 8192;  // blocks per task (64 KB)
  int64_t tasks = (blocks + kSpan - 1) / kSpan;
  parallel_for(tasks, threads, [&](int64_t t) {
    int64_t lo = t * kSpan, hi = std::min(blocks, lo + kSpan);
    for (int64_t i = lo; i < hi; ++i) {
      uint64_t v = splitmix64(seed + static_cast<uint64_t>(i));
      int64_t off = i * 8;
      int64_t n = std::min<int64_t>(8, nbytes - off);
      std::memcpy(dst + off, &v, static_cast<size_t>(n));
    }
  });
}

// Fused augmentation epilogue: per-image crop window (oy, ox) of
// oh x ow out of h x w, optional horizontal flip, then the normalize
// affine — ONE pass over the output pixels instead of crop + flip +
// normalize as separate host passes (what DALI fuses on GPU for the
// reference's imagenet pipeline).  offs is [n, 2] (oy, ox); flips is
// [n] (0/1).  Parallel over images.
void apex_crop_flip_norm_u8_f32(const uint8_t* src, float* dst, int64_t n,
                                int64_t h, int64_t w, int64_t c,
                                int64_t oh, int64_t ow,
                                const int32_t* offs, const uint8_t* flips,
                                const float* mean, const float* stddev,
                                int threads) {
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    bias[ch] = -mean[ch] / stddev[ch];
  }
  parallel_for(n, threads, [&](int64_t i) {
    int64_t oy = offs[2 * i], ox = offs[2 * i + 1];
    bool flip = flips[i] != 0;
    const uint8_t* img = src + i * h * w * c;
    float* out = dst + i * oh * ow * c;
    for (int64_t y = 0; y < oh; ++y) {
      const uint8_t* row = img + ((oy + y) * w + ox) * c;
      float* drow = out + y * ow * c;
      for (int64_t x = 0; x < ow; ++x) {
        const uint8_t* px = row + (flip ? (ow - 1 - x) : x) * c;
        for (int64_t ch = 0; ch < c; ++ch) {
          drow[x * c + ch] = px[ch] * scale[ch] + bias[ch];
        }
      }
    }
  });
}

// Simple checksum used by tests to verify the library loaded correctly.
// v2: adds apex_synth_u8 + apex_crop_flip_norm_u8_f32 (ISSUE 3).
int64_t apex_runtime_abi_version() { return 2; }

}  // extern "C"
