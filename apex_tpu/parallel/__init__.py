"""apex_tpu.parallel — distributed data parallelism & friends (SURVEY.md §2.2).

Public surface mirrors ``apex/parallel/__init__.py``: ``DistributedDataParallel``,
``Reducer``, ``SyncBatchNorm``, ``LARC``, ``convert_syncbn_model``,
``create_syncbn_process_group`` — re-designed over ``jax.sharding.Mesh`` +
XLA collectives instead of NCCL hooks/buckets/streams.
"""

from typing import Optional

import dataclasses

import flax.linen as nn
import jax

from .distributed import (DistributedDataParallel, Reducer,  # noqa: F401
                          reduce_gradients, broadcast_params,
                          import_shard_map)
from .sync_batchnorm import (SyncBatchNorm, welford_parallel,  # noqa: F401
                             adopt_batchnorm_stats)
from .LARC import LARC, larc_transform, larc_gradients       # noqa: F401
from .ring_attention import (ring_attention,  # noqa: F401
                             ring_flash_attention, ulysses_attention)
from .tensor_parallel import (column_parallel_dense,  # noqa: F401
                              row_parallel_dense, tp_mlp,
                              tp_self_attention, shard_column, shard_row)
from .pipeline import (spmd_pipeline, spmd_pipeline_interleaved,  # noqa: F401
                       stack_interleaved_stage_params,  # noqa: F401
                       stack_stage_params)  # noqa: F401
from .expert_parallel import moe_layer, MoEAux  # noqa: F401
from .zero import zero1, zero1_partition_spec, Zero1State  # noqa: F401
from .mesh import (MeshPlan, MeshTrainStep,  # noqa: F401
                   make_mesh_train_step, zero_sharded, MeshZeroState)
from .multiproc import (initialize, is_coordinator,  # noqa: F401
                        process_identity)


def convert_syncbn_model(module: nn.Module, axis_name: str = "data",
                         process_group=None, channel_last: bool = True):
    """Recursively replace ``nn.BatchNorm`` definitions inside a flax module
    tree with ``SyncBatchNorm`` (reference ``apex/parallel/__init__.py:20-52``
    — which walks ``named_children`` preserving affine/running state; flax
    modules are immutable dataclasses, so this rebuilds the definition tree;
    parameters/batch_stats keep their pytree paths, so existing state dicts
    remain loadable, the analog of the reference copying running stats).

    Works for modules whose submodules are dataclass fields, or entries in
    list/tuple/dict fields.  InstanceNorm-style usage (BatchNorm with
    ``use_running_average`` fixed False and no axis) is left untouched only
    if it subclasses BatchNorm differently — matching the reference's
    InstanceNorm skip.
    """
    def convert(obj):
        if isinstance(obj, nn.BatchNorm):
            return SyncBatchNorm(
                eps=obj.epsilon,
                momentum=1.0 - obj.momentum,  # flax momentum is the EMA decay
                affine=obj.use_scale or obj.use_bias,
                axis_name=axis_name,
                process_group=process_group,
                channel_last=channel_last,
                use_running_average=obj.use_running_average,
            )
        if isinstance(obj, nn.Module) and dataclasses.is_dataclass(obj):
            changes = {}
            for f in dataclasses.fields(obj):
                if not f.init:
                    continue
                try:
                    v = getattr(obj, f.name)
                except AttributeError:
                    continue
                nv = convert_container(v)
                if nv is not v:
                    changes[f.name] = nv
            if changes:
                return obj.clone(**changes)
            return obj
        return obj

    def convert_container(v):
        if isinstance(v, nn.Module):
            return convert(v)
        if isinstance(v, (list, tuple)):
            items = [convert_container(x) for x in v]
            if any(a is not b for a, b in zip(items, v)):
                return type(v)(items)
            return v
        if isinstance(v, dict):
            items = {k: convert_container(x) for k, x in v.items()}
            if any(items[k] is not v[k] for k in v):
                return items
            return v
        return v

    return convert(module)


def create_syncbn_process_group(group_size: int, world_size: Optional[int] = None):
    """Partition the world into BN sub-groups of ``group_size`` ranks.

    Reference ``apex/parallel/__init__.py:55-96`` (every rank must create all
    groups — here the returned ``axis_index_groups`` list is inherently
    global).  Returns a list of rank lists usable as
    ``SyncBatchNorm(process_group=...)`` / ``psum(axis_index_groups=...)``.
    ``group_size=0`` means "use the whole world" → None.
    """
    if group_size == 0:
        return None
    if world_size is None:
        world_size = jax.device_count()
    if world_size < group_size:
        raise ValueError("world_size < group_size")
    if world_size % group_size != 0:
        raise ValueError("world_size must be divisible by group_size")
    return [list(range(g * group_size, (g + 1) * group_size))
            for g in range(world_size // group_size)]
