"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Beyond-parity scope (the reference has no attention or sequence parallelism
— SURVEY.md §2.10/§5); first-class here because long-context training is a
core TPU workload and shapes the mesh design.

Two strategies over a mesh axis ``sp`` holding sequence shards:

* **Ring attention** (:func:`ring_attention`) — Q stays resident; KV shards
  rotate around the ring via ``lax.ppermute`` while each device accumulates
  the online-softmax recurrence (``ops.attention.attention_block_update``).
  Communication rides ICI neighbor links (a ``ppermute`` ring), overlapping
  with the per-block matmuls; memory is O(T/n) per device.  Causal masking
  uses each block's global offsets, so rotated blocks mask correctly.

* **Ulysses** (:func:`ulysses_attention`) — two ``all_to_all``s re-shard
  from sequence-sharded to head-sharded, run *local* full attention, and
  shard back.  Cheaper at moderate T (2 collectives instead of n-1
  permutes) but caps parallelism at num_heads.

Both are pure functions designed for use inside ``shard_map`` and agree
with single-device blockwise attention to numerical precision (see
tests/test_ring_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (attention_block_update, _init_carry,
                             finalize_attention, blockwise_attention)


def ring_attention(q, k, v, axis_name: str, *,
                   causal: bool = False,
                   sm_scale: Optional[float] = None,
                   block_size: int = 512):
    """Ring attention over sequence shards (inside shard_map).

    ``q``/``k``/``v``: local shards [B, T/n, H, D] where the global sequence
    is split contiguously over ``axis_name`` in rank order.  Returns the
    local output shard [B, T/n, H, D].
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if sm_scale is None:
        sm_scale = d ** -0.5
    q_offset = idx * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Sub-block the local shard when it exceeds block_size, bounding the
    # per-step score matrix at [B,H,T/n,block_size].
    blk = min(block_size, t_local)
    n_sub = t_local // blk
    rem = t_local - n_sub * blk

    def _consume_shard(kb, vb, m, l, acc, k_offset):
        if n_sub <= 1 and rem == 0:
            return attention_block_update(
                q, kb, vb, m, l, acc, sm_scale=sm_scale, causal=causal,
                q_offset=q_offset, k_offset=k_offset)
        for s in range(n_sub):
            m, l, acc = attention_block_update(
                q, kb[:, s * blk:(s + 1) * blk], vb[:, s * blk:(s + 1) * blk],
                m, l, acc, sm_scale=sm_scale, causal=causal,
                q_offset=q_offset, k_offset=k_offset + s * blk)
        if rem:
            m, l, acc = attention_block_update(
                q, kb[:, -rem:], vb[:, -rem:], m, l, acc,
                sm_scale=sm_scale, causal=causal, q_offset=q_offset,
                k_offset=k_offset + n_sub * blk)
        return m, l, acc

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def step(carry, r):
        kv, m, l, acc = carry
        kb, vb = kv
        # This KV block originated at rank (idx - r) mod n.
        k_offset = ((idx - r) % n) * t_local
        m, l, acc = _consume_shard(kb, vb, m, l, acc, k_offset)
        # Rotate for the next step (skipped result on the last iteration
        # costs nothing: XLA dead-code-eliminates... but ppermute is a
        # collective every rank must execute, so keep it unconditional).
        kv = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), kv)
        return (kv, m, l, acc), None

    m0, l0, acc0 = _init_carry(b, t_local, h, d)
    # The zeros carry is axis-unvarying but the body produces values varying
    # over every manual axis q varies over (sp, plus e.g. data on a 2-D
    # mesh); align the vma types up front (shard_map scan requirement).
    try:
        target_vma = tuple(jax.typeof(q).vma | {axis_name})
    except AttributeError:          # vma tracking off / pmap trace
        target_vma = (axis_name,)
    m0, l0, acc0 = jax.tree_util.tree_map(
        lambda x: lax.pcast(x, target_vma, to="varying"), (m0, l0, acc0))
    (_, m, l, acc), _ = lax.scan(step, ((k, v), m0, l0, acc0),
                                 jnp.arange(n))
    return finalize_attention(m, l, acc, q.dtype)


def ulysses_attention(q, k, v, axis_name: str, *,
                      causal: bool = False,
                      sm_scale: Optional[float] = None,
                      block_size: int = 512):
    """Ulysses-style all-to-all sequence parallelism (inside shard_map).

    Local shards [B, T/n, H, D] → all_to_all → [B, T, H/n, D] → local
    blockwise attention over the FULL sequence → all_to_all back.
    Requires ``H % n == 0``.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num_heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # split heads (axis 2) across ranks, gather sequence (axis 1)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = blockwise_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                              block_size=block_size)
    return heads_to_seq(out)
