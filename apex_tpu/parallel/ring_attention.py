"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Beyond-parity scope (the reference has no attention or sequence parallelism
— SURVEY.md §2.10/§5); first-class here because long-context training is a
core TPU workload and shapes the mesh design.

Two strategies over a mesh axis ``sp`` holding sequence shards:

* **Ring attention** (:func:`ring_attention`) — Q stays resident; KV shards
  rotate around the ring via ``lax.ppermute`` while each device accumulates
  the online-softmax recurrence (``ops.attention.attention_block_update``).
  Communication rides ICI neighbor links (a ``ppermute`` ring), overlapping
  with the per-block matmuls; memory is O(T/n) per device.  Causal masking
  uses each block's global offsets, so rotated blocks mask correctly.

* **Ulysses** (:func:`ulysses_attention`) — two ``all_to_all``s re-shard
  from sequence-sharded to head-sharded, run *local* full attention, and
  shard back.  Cheaper at moderate T (2 collectives instead of n-1
  permutes) but caps parallelism at num_heads.

Both are pure functions designed for use inside ``shard_map`` and agree
with single-device blockwise attention to numerical precision (see
tests/test_ring_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (attention_block_update, _init_carry,
                             finalize_attention, blockwise_attention)
from .distributed import _axis_size


def ring_attention(q, k, v, axis_name: str, *,
                   causal: bool = False,
                   sm_scale: Optional[float] = None,
                   block_size: int = 512):
    """Ring attention over sequence shards (inside shard_map).

    ``q``/``k``/``v``: local shards [B, T/n, H, D] where the global sequence
    is split contiguously over ``axis_name`` in rank order.  Returns the
    local output shard [B, T/n, H, D].
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if sm_scale is None:
        sm_scale = d ** -0.5
    q_offset = idx * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Sub-block the local shard when it exceeds block_size, bounding the
    # per-step score matrix at [B,H,T/n,block_size].
    blk = min(block_size, t_local)
    n_sub = t_local // blk
    rem = t_local - n_sub * blk

    def _consume_shard(kb, vb, m, l, acc, k_offset):
        if n_sub <= 1 and rem == 0:
            return attention_block_update(
                q, kb, vb, m, l, acc, sm_scale=sm_scale, causal=causal,
                q_offset=q_offset, k_offset=k_offset)
        for s in range(n_sub):
            m, l, acc = attention_block_update(
                q, kb[:, s * blk:(s + 1) * blk], vb[:, s * blk:(s + 1) * blk],
                m, l, acc, sm_scale=sm_scale, causal=causal,
                q_offset=q_offset, k_offset=k_offset + s * blk)
        if rem:
            m, l, acc = attention_block_update(
                q, kb[:, -rem:], vb[:, -rem:], m, l, acc,
                sm_scale=sm_scale, causal=causal, q_offset=q_offset,
                k_offset=k_offset + n_sub * blk)
        return m, l, acc

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def step(carry, r):
        kv, m, l, acc = carry
        kb, vb = kv
        # This KV block originated at rank (idx - r) mod n.
        k_offset = ((idx - r) % n) * t_local
        m, l, acc = _consume_shard(kb, vb, m, l, acc, k_offset)
        # Rotate for the next step (skipped result on the last iteration
        # costs nothing: XLA dead-code-eliminates... but ppermute is a
        # collective every rank must execute, so keep it unconditional).
        kv = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), kv)
        return (kv, m, l, acc), None

    m0, l0, acc0 = _init_carry(b, t_local, h, d)
    # The zeros carry is axis-unvarying but the body produces values varying
    # over every manual axis q varies over (sp, plus e.g. data on a 2-D
    # mesh); align the vma types up front (shard_map scan requirement).
    if _vma_tracking_live(axis_name):
        target_vma = tuple(jax.typeof(q).vma | {axis_name})
        m0, l0, acc0 = jax.tree_util.tree_map(
            lambda x: lax.pcast(x, target_vma, to="varying"), (m0, l0, acc0))
    (_, m, l, acc), _ = lax.scan(step, ((k, v), m0, l0, acc0),
                                 jnp.arange(n))
    return finalize_attention(m, l, acc, q.dtype)


from .distributed import vma_tracking_live as _vma_tracking_live


def _logaddexp(a, b):
    m = jnp.maximum(a, b)
    return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q,
                         block_k, interpret):
    """Forward: per-shard Pallas flash partials (normalized out_i + lse_i)
    merged across ring steps by logsumexp weights.  Head-major in/out."""
    from ..ops.flash_attention import _flash_fwd_pallas

    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    q_off = idx * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    lse0 = jnp.full((b, h, t_local, 1), -1e30, jnp.float32)
    out0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    if _vma_tracking_live(axis_name):
        target_vma = tuple(jax.typeof(q).vma | {axis_name})
        lse0, out0 = jax.tree_util.tree_map(
            lambda x: lax.pcast(x, target_vma, to="varying"), (lse0, out0))

    def step(carry, r):
        (kc, vc), lse_run, out_run = carry
        j = (idx - r) % n
        out_i, lse_i = _flash_fwd_pallas(
            q, kc, vc, None, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k,
            q_offset=q_off, k_offset=j * t_local, interpret=interpret)
        new_lse = _logaddexp(lse_run, lse_i)
        out_run = (out_run * jnp.exp(lse_run - new_lse)
                   + out_i.astype(jnp.float32) * jnp.exp(lse_i - new_lse))
        kc, vc = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), (kc, vc))
        return ((kc, vc), new_lse, out_run), None

    (_, lse, out), _ = lax.scan(step, ((k, v), lse0, out0), jnp.arange(n))
    return out.astype(q.dtype), lse


def _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name, causal, sm_scale,
                         block_q, block_k, interpret):
    """Backward: re-rotate KV; per shard run the flash backward kernels
    with the GLOBAL lse (so recomputed p are the true global softmax
    probabilities); dq accumulates locally, dk/dv accumulate in buffers
    that rotate WITH their kv shard and arrive home after the full cycle."""
    from ..ops.flash_attention import _flash_bwd_pallas

    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    q_off = idx * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    dk0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    dv0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    if _vma_tracking_live(axis_name):
        target_vma = tuple(jax.typeof(q).vma | {axis_name})
        dq0, dk0, dv0 = jax.tree_util.tree_map(
            lambda x: lax.pcast(x, target_vma, to="varying"), (dq0, dk0, dv0))

    # do/out are step-invariant: compute delta once, outside the scan.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def step(carry, r):
        (kc, vc, dkc, dvc), dq = carry
        j = (idx - r) % n
        dq_i, dk_i, dv_i, _, _ = _flash_bwd_pallas(
            q, kc, vc, None, out, lse, do, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k,
            q_offset=q_off, k_offset=j * t_local, delta=delta,
            interpret=interpret)
        dq = dq + dq_i.astype(jnp.float32)
        dkc = dkc + dk_i.astype(jnp.float32)
        dvc = dvc + dv_i.astype(jnp.float32)
        kc, vc, dkc, dvc = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), (kc, vc, dkc, dvc))
        return ((kc, vc, dkc, dvc), dq), None

    ((_, _, dk, dv), dq), _ = lax.scan(
        step, ((k, v, dk0, dv0), dq0), jnp.arange(n))
    # n rotations = identity: dk/dv are home with every rank's contribution.
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                  block_q, block_k, interpret)
    return out


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, sm_scale, block_q,
                         block_k, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                    block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis_name, causal, sm_scale, block_q, block_k,
                         interpret, res, do):
    q, k, v, out, lse = res
    return _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name, causal,
                                sm_scale, block_q, block_k, interpret)


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_flash_attention(q, k, v, axis_name: str, *,
                         causal: bool = False,
                         sm_scale: Optional[float] = None,
                         block_q: int = 512,
                         block_k: int = 512,
                         interpret: bool = False):
    """Ring attention with the Pallas flash kernels as the local op.

    Same contract as :func:`ring_attention` (call inside shard_map with
    contiguous sequence shards [B, T/n, H, D] over ``axis_name``) but each
    ring step runs the MXU flash kernel and saves only one fp32 logsumexp
    per row; the backward re-rotates KV and runs the flash backward
    kernels against the *global* lse, so gradients are exact.  Falls back
    to the jnp :func:`ring_attention` off-TPU or when the shard length
    doesn't block-align.  Runs under ``shard_map``'s DEFAULT vma tracking
    (r3: the kernels pcast-align their rank-varying offset operands —
    ``pallas_compat.align_vma`` — so ``check_vma=False`` is no longer
    required for the Mosaic fast path; only ``interpret=True`` emulation
    still needs the jnp route there, a jax hlo-interpreter limitation —
    its internal block loops index varying operands with unvarying iotas).
    """
    from ..ops.flash_attention import _pick_block, _use_pallas, pltpu

    t_local, d = q.shape[1], q.shape[3]
    if sm_scale is None:
        sm_scale = d ** -0.5
    bq = _pick_block(t_local, block_q)
    bk = _pick_block(t_local, block_k)
    use_kernel = ((interpret or _use_pallas()) and bq is not None
                  and bk is not None and pltpu is not None
                  and not (interpret and _vma_tracking_live(axis_name)))
    if not use_kernel:
        return ring_attention(q, k, v, axis_name, causal=causal,
                              sm_scale=sm_scale)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _ring_flash(qt, kt, vt, axis_name, bool(causal), float(sm_scale),
                      int(bq), int(bk), bool(interpret))
    return out.transpose(0, 2, 1, 3)


def ulysses_attention(q, k, v, axis_name: str, *,
                      causal: bool = False,
                      sm_scale: Optional[float] = None,
                      block_size: int = 512):
    """Ulysses-style all-to-all sequence parallelism (inside shard_map).

    Local shards [B, T/n, H, D] → all_to_all → [B, T, H/n, D] → local
    blockwise attention over the FULL sequence → all_to_all back.
    Requires ``H % n == 0``.
    """
    n = _axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num_heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # split heads (axis 2) across ranks, gather sequence (axis 1)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = blockwise_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                              block_size=block_size)
    return heads_to_seq(out)
