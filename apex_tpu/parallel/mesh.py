"""Unified N-D sharding frontend: declare a DP×FSDP×TP mesh once,
derive every placement from it (ISSUE 12 tentpole).

The parallel/ pillar grew as independent wrappers — DDP psum, zero1,
pipeline, tensor_parallel, expert_parallel, ring_attention — each
hard-coding its own axis name and device layout.  :class:`MeshPlan` is
the single declaration they compose on (the NamedSharding/PartitionSpec
helper idiom of SNIPPETS.md [2], pjit-style references [1]/[3]):

* **axis names, sizes, device order** are stated ONCE
  (``MeshPlan(dp=2, fsdp=4)``), per-process under multi-host
  (``jax.devices()`` spans every process after
  :func:`apex_tpu.parallel.multiproc.initialize`);
* **parameter / optimizer-state / data placements** are derived —
  ``plan.batch_sharding()``, ``plan.state_spec(state)``,
  ``plan.named(...)`` — never re-declared per call site;
* **ZeRO-style state partitioning** layers over the flat-bucket store
  (:class:`~apex_tpu.multi_tensor.BucketStore`):

  ========  =======================================================
  level     what is sharded over the ``fsdp`` axis
  ========  =======================================================
  1 / 2     optimizer state; gradients are reduce-scattered (the
            ZeRO-2 wire schedule — ``zero1`` already moves grads as
            per-chunk scatters, so stages 1 and 2 coincide in SPMD)
  3         params AND optimizer state: params live as sharded flat
            buckets; the full tree exists only INSIDE the step
  ========  =======================================================

The ZeRO-3 trick is autodiff-native: the stored params are sharded flat
buckets, and a ``param_view`` (:func:`apex_tpu.training.make_train_step`)
all-gathers + unpacks them INSIDE the differentiated loss.  The
transpose of that gather **is** the reduce-scatter (``reduce_scatter``
HLO — the same primitive ``lax.psum_scatter`` lowers to), so the
backward emits exactly ZeRO's grad schedule with no hand-written VJP;
with a chunked store (``max_bucket_elems``) the per-bucket gathers and
scatters close their data dependencies bucket-by-bucket and XLA's
latency-hiding scheduler overlaps them with the surrounding compute —
the same reverse-topological machinery
:func:`apex_tpu.parallel.reduce_gradients` uses for chunked psums.

Wired end to end with the pre-built hard parts:

* **elastic reshard** — ZeRO-3 params and moments are exactly the flat
  padded buckets ``apex_tpu.checkpoint`` reshards N→M on read; save
  with ``bucket_layout=plan.bucket_layout(store)`` and restore onto a
  different mesh (``tests/test_checkpoint.py``);
* **AOT warmup** — :meth:`MeshTrainStep.init` device_puts every leaf
  with a COMMITTED NamedSharding, so ``cache.abstractify`` pins the
  placements and :meth:`StepPipeline.warmup
  <apex_tpu.runtime.StepPipeline.warmup>` compiles the sharded step
  before step 0 (zero steady-state retraces);
* **fleet attribution** — every collective is noted per mesh AXIS
  (dp/fsdp/tp), so ``prof.fleet``'s wait-vs-wire split and the
  timeline byte totals attribute traffic per axis.

Usage::

    from apex_tpu.parallel import mesh

    plan = mesh.MeshPlan(dp=2, fsdp=4)            # 8 devices, 2-D
    ms = mesh.make_mesh_train_step(loss_fn, training.adam(1e-3), plan,
                                   zero=3, opt_level="O2")
    state = ms.init(params)                       # sharded + committed
    step = ms.jit_step(state)                     # shard_map + jit
    state, metrics = step(state, plan.device_put_batch(batch))

    # or through the pipelined runtime:
    pipe = runtime.StepPipeline(ms.step_fn, k=8,
                                wrap=ms.pipeline_wrap(state))
    pipe.warmup(state, window)                    # AOT, sharded
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..multi_tensor.buckets import (BucketStore, Packed, cached_store,
                                    padded_shard_len)
from .distributed import _note_collective, import_shard_map
from .zero import Zero1State, _shard_one

__all__ = ["MeshPlan", "MeshTrainStep", "make_mesh_train_step",
           "zero_sharded", "MeshZeroState"]


class MeshPlan:
    """One declaration of a DP×FSDP×TP device mesh.

    ``dp`` replicas see different data and hold full state; ``fsdp``
    replicas see different data and SHARD state (the ZeRO axis); ``tp``
    replicas see the same data and shard tensors inside the model (use
    ``plan.tp_axis`` with :mod:`apex_tpu.parallel.tensor_parallel`).
    Sizes must multiply to ``len(devices)``.

    ``devices`` defaults to ``jax.devices()`` — under multi-host
    (:func:`apex_tpu.parallel.multiproc.initialize`) that is the GLOBAL
    device list in a process-consistent order, so every process
    constructs the same mesh and owns its local slice of it.
    """

    def __init__(self, *, dp: int = 1, fsdp: int = 1, tp: int = 1,
                 devices: Optional[Sequence] = None,
                 axis_names: Tuple[str, str, str] = ("dp", "fsdp", "tp")):
        if len(tuple(axis_names)) != 3:
            raise ValueError(f"axis_names must name (dp, fsdp, tp), got "
                             f"{axis_names!r}")
        if min(dp, fsdp, tp) < 1:
            raise ValueError(
                f"axis sizes must be >= 1, got dp={dp} fsdp={fsdp} tp={tp}")
        if devices is None:
            devices = jax.devices()
        devices = np.asarray(devices, dtype=object)
        if devices.size != dp * fsdp * tp:
            raise ValueError(
                f"MeshPlan needs dp*fsdp*tp == len(devices): "
                f"{dp}*{fsdp}*{tp} != {devices.size} — size the plan to "
                f"the device count (jax.device_count()={jax.device_count()})")
        self.axis_names = tuple(axis_names)
        self.dp, self.fsdp, self.tp = int(dp), int(fsdp), int(tp)
        self.mesh = Mesh(devices.reshape(self.dp, self.fsdp, self.tp),
                         self.axis_names)

    @classmethod
    def auto(cls, *, fsdp: Optional[int] = None, tp: int = 1,
             devices: Optional[Sequence] = None, **kw) -> "MeshPlan":
        """Fill ``dp`` from the device count: ``fsdp`` defaults to all
        devices not claimed by ``tp`` (pure FSDP, the memory-optimal
        default), ``dp`` to the remainder."""
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        if fsdp is None:
            if n % tp:
                raise ValueError(f"{n} devices not divisible by tp={tp}")
            fsdp = n // tp
        if n % (fsdp * tp):
            raise ValueError(
                f"{n} devices not divisible by fsdp*tp={fsdp * tp}")
        return cls(dp=n // (fsdp * tp), fsdp=fsdp, tp=tp,
                   devices=devices, **kw)

    # -- axis names ----------------------------------------------------------
    @property
    def dp_axis(self) -> str:
        return self.axis_names[0]

    @property
    def fsdp_axis(self) -> str:
        return self.axis_names[1]

    @property
    def tp_axis(self) -> str:
        return self.axis_names[2]

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes whose replicas consume DIFFERENT data (batch sharding +
        gradient reduction span their product)."""
        return (self.dp_axis, self.fsdp_axis)

    @property
    def all_axes(self) -> Tuple[str, ...]:
        """Every mesh axis — the step's ``axis_name`` (overflow
        agreement and metric pmean span the full mesh)."""
        return self.axis_names

    @property
    def world_size(self) -> int:
        return self.dp * self.fsdp * self.tp

    @property
    def data_world(self) -> int:
        """Number of distinct data shards (the gradient-mean divisor)."""
        return self.dp * self.fsdp

    def __repr__(self):
        return (f"MeshPlan({self.dp_axis}={self.dp} x "
                f"{self.fsdp_axis}={self.fsdp} x {self.tp_axis}={self.tp} "
                f"over {self.world_size} device(s))")

    # -- derived placements --------------------------------------------------
    def named(self, *spec) -> NamedSharding:
        """``NamedSharding(mesh, P(*spec))`` — the one constructor every
        placement below derives from."""
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self.named()

    @property
    def batch_spec(self) -> P:
        """Per-step batch: leading (batch) dim sharded over dp×fsdp,
        replicated over tp."""
        return P(self.data_axes)

    def batch_sharding(self) -> NamedSharding:
        return self.named(self.data_axes)

    def window_sharding(self) -> NamedSharding:
        """A ``[K, batch, ...]`` staged window: leading K axis unsharded
        (the device-loop axis), batch axis over dp×fsdp — pass as
        ``stage_windows(..., device=plan.window_sharding())``."""
        return self.named(None, self.data_axes)

    @property
    def flat_spec(self) -> P:
        """A ZeRO flat bucket (1-D, padded to divide): sharded over the
        fsdp axis."""
        return P(self.fsdp_axis)

    def flat_sharding(self) -> NamedSharding:
        return self.named(self.fsdp_axis)

    def device_put_batch(self, batch):
        """Place one host batch onto the mesh (committed — the AOT
        warmup pins this placement).  Multi-host callers feed their
        per-process shard; single-process callers the global batch."""
        sh = self.batch_sharding()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.host_local_array_to_global_array(
                batch, self.mesh, self.batch_spec)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)

    def device_put_window(self, window):
        """Stage a ``[K, batch, ...]`` stacked window (the
        :func:`apex_tpu.runtime.window_batches` shape): leading K axis
        unsharded, batch axis over dp×fsdp.  Multi-host callers feed
        their per-process window; single-process the global one."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.host_local_array_to_global_array(
                window, self.mesh, P(None, *self.batch_spec))
        sh = self.window_sharding()
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), window)

    def shard_map(self, fn, in_specs, out_specs):
        """``shard_map`` over this plan's mesh (version-portable)."""
        return import_shard_map()(fn, mesh=self.mesh, in_specs=in_specs,
                                  out_specs=out_specs)

    # -- ledger --------------------------------------------------------------
    def state_bytes(self, tree) -> dict:
        """Placement ledger of a (state) pytree: global bytes vs the
        bytes ONE device actually holds under the committed shardings —
        the ZeRO memory claim as an auditable number
        (``bench.py`` gates ZeRO-3 at ~1/shard_count).  Leaves without
        a sharding count as replicated."""
        glob = per_dev = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if not hasattr(leaf, "dtype") or not hasattr(leaf, "shape"):
                continue
            itemsize = jnp.dtype(leaf.dtype).itemsize
            nbytes = itemsize * int(math.prod(leaf.shape) if leaf.shape else 1)  # jaxlint: disable=J008 -- static shape arithmetic (aval metadata), no device round-trip
            glob += nbytes
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and leaf.shape:
                try:
                    shard_shape = sharding.shard_shape(tuple(leaf.shape))
                    per_dev += itemsize * int(math.prod(shard_shape))  # jaxlint: disable=J008 -- static shape/sharding arithmetic, no device round-trip
                    continue
                except Exception:
                    pass
            per_dev += nbytes
        return {"global_bytes": glob, "bytes_per_device": per_dev,
                "ratio": round(per_dev / glob, 4) if glob else None}

    def bucket_layout(self, store: BucketStore) -> dict:
        """Checkpoint-manifest bucket descriptor for THIS plan's shard
        count (:func:`apex_tpu.checkpoint.bucket_layout`) — what
        elastic N→M reshard-on-read re-slices against."""
        return store.shard_layout(self.fsdp)


# -- ZeRO over the plan -------------------------------------------------------

class MeshZeroState(NamedTuple):
    """Optimizer state of :func:`zero_sharded`: one inner state per
    flat bucket, each sharded over the plan's fsdp axis."""
    inner: Tuple[Any, ...]


def _pad_bucket(b, num_shards: int):
    return jnp.pad(b, (0, padded_shard_len(b.size, num_shards) - b.size))


def _require_elementwise(tx) -> None:
    if not getattr(tx, "elementwise", False):
        raise ValueError(
            "zero_sharded requires an optimizer that declares "
            "elementwise=True (adam/sgd qualify) — per-tensor-norm "
            "optimizers compute wrong trust ratios on flat chunks; see "
            "parallel.zero.zero1 for the full contract")


def zero_sharded(tx, plan: MeshPlan, *, level: int = 2,
                 decay_flags=None, **store_kw):
    """ZeRO state partitioning over ``plan``'s fsdp axis, flat-bucket
    substrate.  Returns a :class:`~apex_tpu.training.FunctionalOptimizer`.

    * ``level`` 1/2 — params replicated (plain pytree), optimizer state
      sharded; gradients reduce-scattered over fsdp and psummed over dp
      (stages 1 and 2 coincide in SPMD: grads already move as per-chunk
      scatters, never materializing a full per-rank copy past backward).
    * ``level`` 3 — params themselves stored as fsdp-sharded flat
      buckets (:class:`~apex_tpu.multi_tensor.Packed`); build the step
      through :func:`make_mesh_train_step`, which installs the
      gather-in-loss ``param_view`` whose transpose IS the grad
      reduce-scatter.

    Both run inside ``shard_map`` with ``reduce_grads=False`` (the
    optimizer owns every reduction) and ``axis_name=plan.all_axes``
    (the step still needs the mesh-wide overflow agreement).
    ``store_kw`` (``max_bucket_elems``, ``decay_mask``) configure the
    underlying :class:`~apex_tpu.multi_tensor.BucketStore` for levels
    1/2 (which pack the tree themselves); level 3 consumes pre-packed
    buckets, so the caller passes their store's ``decay_flags``
    instead."""
    from ..training import FunctionalOptimizer

    _require_elementwise(tx)
    if level not in (1, 2, 3):
        raise ValueError(f"zero level must be 1, 2, or 3, got {level}")
    if level < 3:
        return _zero12_tx(tx, plan, FunctionalOptimizer, store_kw)
    return _zero3_tx(tx, plan, FunctionalOptimizer, decay_flags=decay_flags)


def _zero12_tx(tx, plan: MeshPlan, FunctionalOptimizer, store_kw):
    """Replicated params, sharded state — the zero1 bucketed machinery
    generalized to the 2-D data mesh (dp psum on the scattered chunk,
    mean over the full data world)."""
    cell = {}

    def _store(params) -> BucketStore:
        return cached_store(cell, params, **store_kw)

    def init(params):
        packed = _store(params).pack(params)
        inner = tuple(tx.init(_pad_bucket(b, plan.fsdp))
                      for b in packed.data)
        return Zero1State(inner=inner)

    def update(grads, state, params, *, apply_mask=None, **kw):
        store = _store(params)
        idx = lax.axis_index(plan.fsdp_axis)
        packed_p = store.pack(params)
        packed_g = store.pack(grads, cast=True)
        new_data = list(packed_p.data)
        new_inner = list(state.inner)
        # Reverse-topological issue order: the deepest layers' scatter
        # starts while earlier layers still differentiate (ISSUE 7
        # machinery, reused for the mesh schedule).
        for bi in store.reverse_topological_order():
            bkw = (kw if store.decay_flags[bi]
                   else {**kw, "weight_decay": 0.0})
            flat_new, ni = _shard_one(
                packed_p.data[bi],
                packed_g.data[bi].astype(packed_p.data[bi].dtype),
                state.inner[bi], tx, plan.fsdp, idx, plan.fsdp,
                plan.fsdp_axis, apply_mask, bkw,
                pre_axes=(plan.dp_axis,), denom=plan.data_world)
            new_data[bi] = flat_new
            new_inner[bi] = ni
        out = Packed(data=tuple(new_data), rest=packed_p.rest)
        return store.unpack(out), Zero1State(inner=tuple(new_inner))

    return FunctionalOptimizer(init=init, update=update)


def _zero3_tx(tx, plan: MeshPlan, FunctionalOptimizer, decay_flags=None):
    """Sharded params AND state: ``init`` takes the PACKED padded
    params; ``update`` receives per-chunk gradients already summed over
    fsdp (the ``param_view`` gather's transpose) and finishes the mean
    with the dp psum.  ``decay_flags`` are the packing store's
    per-bucket weight-decay flags (the no-decay buckets a ``decay_mask``
    split off get ``weight_decay=0.0``, same contract as the bucketed
    optimizers)."""

    def init(packed: Packed):
        if not isinstance(packed, Packed):
            raise TypeError(
                "zero level 3 stores params as fsdp-sharded flat buckets "
                "— build the step with make_mesh_train_step(..., zero=3), "
                "whose init packs the tree for you")
        return MeshZeroState(inner=tuple(tx.init(b) for b in packed.data))

    def update(grads: Packed, state, params: Packed, *,
               apply_mask=None, **kw):
        new_data = list(params.data)
        new_inner = list(state.inner)
        for bi in range(len(params.data)):
            g = grads.data[bi].astype(params.data[bi].dtype)
            if plan.dp > 1:
                _note_collective(
                    "psum", plan.dp_axis,
                    g.size * jnp.dtype(g.dtype).itemsize, 1, dtype=g.dtype)
                g = lax.psum(g, plan.dp_axis)
            g = g / plan.data_world
            bkw = (kw if decay_flags is None or decay_flags[bi]
                   else {**kw, "weight_decay": 0.0})
            new_p, ni = tx.update(g, state.inner[bi], params.data[bi],
                                  apply_mask=apply_mask, **bkw)
            new_data[bi] = new_p
            new_inner[bi] = ni
        return (Packed(data=tuple(new_data), rest=params.rest),
                MeshZeroState(inner=tuple(new_inner)))

    return FunctionalOptimizer(init=init, update=update,
                               elementwise=True)


# -- the step frontend --------------------------------------------------------

def _gather_view(store: BucketStore, plan: MeshPlan,
                 gather_dtype=None) -> Callable:
    """The ZeRO-3 ``param_view``: per-bucket all-gather over fsdp +
    unpack back to the template tree.  Runs INSIDE the differentiated
    loss, so its transpose (slice-pad + ``reduce_scatter``) is the grad
    schedule.  Per-invocation bytes are noted per bucket on the fsdp
    axis — once for the forward gather, once for the backward scatter
    the transpose will emit.

    ``gather_dtype`` (the ROADMAP mesh-round-2 bf16-gather): cast each
    fsdp-sharded flat bucket to the wire dtype BEFORE the gather and
    back after, halving wire bytes both ways — the transpose of the
    downcast is the upcast, so the backward reduce-scatters bf16 grad
    chunks and hands the optimizer fp32 again.  The fp32 MASTERS are
    untouched (only the in-step view quantizes); ``None`` keeps the
    bitwise fp32 path.  Only float buckets wider than the wire dtype
    cast — an already-narrow bucket ships as-is."""
    wire = None if gather_dtype is None else jnp.dtype(gather_dtype)

    def view(packed: Packed):
        full = []
        for bi, b in enumerate(store.buckets):
            buf = packed.data[bi]
            cast = (wire is not None
                    and jnp.issubdtype(buf.dtype, jnp.floating)
                    and jnp.dtype(buf.dtype).itemsize > wire.itemsize)
            sent = buf.astype(wire) if cast else buf
            nbytes = (sent.size * plan.fsdp
                      * jnp.dtype(sent.dtype).itemsize)
            _note_collective("all_gather", plan.fsdp_axis, nbytes, 1,
                             dtype=sent.dtype)
            _note_collective("reduce_scatter", plan.fsdp_axis, nbytes, 1,
                             dtype=sent.dtype)
            g = lax.all_gather(sent, plan.fsdp_axis, tiled=True)
            g = g.astype(buf.dtype) if cast else g
            full.append(g[:b.size])
        return store.unpack(Packed(data=tuple(full), rest=packed.rest))
    return view


class MeshTrainStep(NamedTuple):
    """Everything :func:`make_mesh_train_step` derived from one plan.

    ``step_fn`` is the per-step function for ``shard_map`` (feed it to
    :class:`~apex_tpu.runtime.StepPipeline` with ``wrap=
    ms.pipeline_wrap()``); ``init`` places every leaf with a COMMITTED
    NamedSharding so AOT warmup pins the layout."""
    plan: MeshPlan
    zero: int
    init: Callable               # (params, model_state=None) -> TrainState
    step_fn: Callable            # (state, batch) -> (state, metrics)
    state_spec: Callable         # (state) -> TrainState of PartitionSpecs
    gather_params: Callable      # (state) -> full replicated param tree
    store: Optional[BucketStore]  # zero-3 bucket index map (else None)

    def wrap(self, fn, state):
        """``shard_map`` wrap of a loop function ``(state, window,
        valid) -> (state, metrics)`` (the StepPipeline contract): state
        by its derived spec, window batch-sharded with the leading K
        axis unsharded, valid mask and metrics replicated."""
        plan = self.plan
        spec = self.state_spec(state)
        return plan.shard_map(
            fn, in_specs=(spec, _tree_of(P(None, *plan.batch_spec)), P()),
            out_specs=(spec, P()))

    def pipeline_wrap(self, state):
        """The ``wrap=`` argument for :class:`StepPipeline`."""
        return lambda fn: self.wrap(fn, state)

    def jit_step(self, state, *, donate: bool = True):
        """One jitted sharded step ``(state, batch) -> (state,
        metrics)`` — the non-pipelined path."""
        plan = self.plan
        spec = self.state_spec(state)

        def stepped(s, b):
            return self.step_fn(s, b)

        mapped = plan.shard_map(stepped,
                                in_specs=(spec, _tree_of(plan.batch_spec)),
                                out_specs=(spec, P()))
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def _tree_of(spec):
    # shard_map treats a bare PartitionSpec as a prefix for the whole
    # subtree — the batch pytree needs no per-leaf enumeration.
    return spec


def make_mesh_train_step(loss_fn: Callable, tx, plan: MeshPlan, *,
                         zero: int = 2,
                         opt_level: str = "O2",
                         max_bucket_elems: Optional[int] = None,
                         decay_mask=None,
                         gather_dtype=None,
                         has_model_state: bool = False,
                         **train_kw) -> MeshTrainStep:
    """Build a sharded training step from one :class:`MeshPlan`.

    ``loss_fn`` takes the FULL parameter tree (as always);
    ``tx`` is an elementwise :class:`~apex_tpu.training.
    FunctionalOptimizer` (``training.adam``/``training.sgd``); ``zero``
    picks the state-partitioning level (table in the module docstring).
    Extra ``train_kw`` pass through to
    :func:`~apex_tpu.training.make_train_step` (loss_scale,
    accum_steps, scale_window, ...).

    ZeRO-3 restriction: ``opt_level`` must keep fp32 storage (O0/O1/O2/O4
    — master weights are the flat buckets); O3's bf16 storage would
    need per-bucket keep-norm splits and is rejected loudly.

    ``gather_dtype`` (ZeRO-3 only): wire dtype for the ``param_view``
    all-gather / grad reduce-scatter — ``jnp.bfloat16`` halves the
    per-step FSDP wire bytes while the stored fp32 masters stay exact
    (the compute cast was shipping bf16 into the matmuls anyway; the
    bf16 wire moves the rounding one op earlier).  ``None`` (default)
    keeps the bitwise fp32 wire.
    """
    from .. import training

    if zero not in (1, 2, 3):
        raise ValueError(f"zero level must be 1, 2, or 3, got {zero}")
    if zero == 3 and opt_level not in ("O0", "O1", "O2", "O4"):
        raise ValueError(
            f"zero=3 stores params as fp32 flat buckets (the masters); "
            f"opt_level {opt_level!r} stores reduced precision — use "
            f"O0/O1/O2/O4, or zero<=2 for O3")

    store_kw = {}
    if max_bucket_elems is not None:
        store_kw["max_bucket_elems"] = max_bucket_elems
    if decay_mask is not None:
        store_kw["decay_mask"] = decay_mask

    if zero != 3 and gather_dtype is not None:
        raise ValueError(
            "gather_dtype shapes the ZeRO-3 param_view wire; zero<3 "
            "replicates params and never gathers them — drop the "
            "argument or use zero=3")

    if zero < 3:
        z_tx = zero_sharded(tx, plan, level=zero, **store_kw)
        init_fn, step_fn = training.make_train_step(
            loss_fn, z_tx, opt_level=opt_level,
            axis_name=plan.all_axes, reduce_grads=False,
            has_model_state=has_model_state, **train_kw)

        def init(params, model_state=None):
            return _place_state(init_fn(params, model_state), plan, zero)

        def state_spec(state):
            return _derive_spec(state, plan, zero)

        def gather_params(state):
            return state.params

        return MeshTrainStep(plan=plan, zero=zero, init=init,
                             step_fn=step_fn, state_spec=state_spec,
                             gather_params=gather_params, store=None)

    # -- zero 3 --------------------------------------------------------------
    _require_elementwise(tx)
    cell: dict = {}              # cached_store signature -> BucketStore
    z3_holder: dict = {}         # id(store) -> (init_fn, step_fn)

    def _build(params_template):
        store = cached_store(cell, params_template, **store_kw)
        built = z3_holder.get(id(store))
        if built is None:
            z_tx = zero_sharded(tx, plan, level=3,
                                decay_flags=store.decay_flags)
            built = training.make_train_step(
                loss_fn, z_tx, opt_level=opt_level,
                axis_name=plan.all_axes, reduce_grads=False,
                has_model_state=has_model_state,
                param_view=_gather_view(store, plan, gather_dtype),
                **train_kw)
            z3_holder.clear()            # one live template at a time
            z3_holder[id(store)] = built
            z3_holder["latest"] = built
        return store, built

    def init(params, model_state=None):
        store, (init_fn, _) = _build(params)
        packed = store.pack(params)
        packed = Packed(
            data=tuple(_pad_bucket(b, plan.fsdp) for b in packed.data),
            rest=packed.rest)
        return _place_state(init_fn(packed, model_state), plan, 3)

    def step_fn(state, batch):
        built = z3_holder.get("latest")
        if built is None:
            raise RuntimeError(
                "make_mesh_train_step(zero=3): call ms.init(params) "
                "before using step_fn — the bucket index map is built "
                "from the first init's parameter template")
        return built[1](state, batch)

    def state_spec(state):
        return _derive_spec(state, plan, 3)

    def gather_params(state):
        # Full replicated param tree from the sharded buckets — the
        # eval/export interchange boundary, on demand, NEVER in the
        # hot step.
        store = _latest_store(cell)
        full = []
        for bi, b in enumerate(store.buckets):
            arr = jax.device_get(state.params.data[bi])  # jaxlint: disable=J001 -- explicit interchange boundary: exporting sharded params to a host tree
            full.append(jnp.asarray(np.asarray(arr)[:b.size]))
        return store.unpack(Packed(data=tuple(full),
                                   rest=state.params.rest))

    return MeshTrainStep(plan=plan, zero=3, init=init, step_fn=step_fn,
                         state_spec=state_spec,
                         gather_params=gather_params,
                         store=_StoreRef(cell))


def _latest_store(cell: dict) -> BucketStore:
    if not cell:
        raise RuntimeError(
            "ZeRO-3 bucket store not built yet — call ms.init(params) "
            "first")
    return next(reversed(cell.values()))


class _StoreRef:
    """Late-bound handle to the ZeRO-3 BucketStore (built at ``init``):
    ``ms.store()`` returns it, attribute access passes through."""

    def __init__(self, cell):
        self._cell = cell

    def __call__(self) -> BucketStore:
        return _latest_store(self._cell)

    def __getattr__(self, name):
        return getattr(_latest_store(self._cell), name)


def _leaf_spec_flat(plan: MeshPlan):
    def spec(leaf):
        return plan.flat_spec if jnp.ndim(leaf) >= 1 else P()
    return spec


def _derive_spec(state, plan: MeshPlan, zero: int):
    """TrainState of PartitionSpecs for the sharded step: flat (1-D)
    optimizer/param buckets over fsdp, everything else replicated."""
    from ..training import TrainState

    spec_flat = _leaf_spec_flat(plan)
    if zero >= 3:
        params_spec = Packed(
            data=tuple(plan.flat_spec for _ in state.params.data),
            rest=tuple(P() for _ in state.params.rest))
    else:
        params_spec = jax.tree_util.tree_map(lambda _: P(), state.params)
    opt_spec = jax.tree_util.tree_map(spec_flat, state.opt_state)
    ms_spec = jax.tree_util.tree_map(lambda _: P(), state.model_state) \
        if state.model_state is not None else P()
    scaler_spec = jax.tree_util.tree_map(lambda _: P(), state.scaler)
    return TrainState(params=params_spec, opt_state=opt_spec,
                      scaler=scaler_spec, model_state=ms_spec)


def _place_state(state, plan: MeshPlan, zero: int):
    """device_put every leaf onto its derived NamedSharding — COMMITTED
    placements, so ``cache.abstractify`` pins them for AOT warmup and
    checkpoint restore re-places leaves correctly."""
    spec = _derive_spec(state, plan, zero)

    def place(leaf, sp):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.device_put(leaf, NamedSharding(plan.mesh, sp))
        return leaf

    return jax.tree_util.tree_map(place, state, spec,
                                  is_leaf=lambda x: x is None)
