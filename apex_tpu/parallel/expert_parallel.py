"""Expert parallelism — switch-style MoE with all_to_all dispatch.

Beyond-parity scope (the reference implements data parallelism only,
SURVEY.md §2.10).  The TPU-native expert layer: tokens and experts are
both sharded over the ``ep`` mesh axis (each rank hosts one expert and a
shard of the batch); routing dispatches tokens to their expert's rank
with one ``all_to_all``, the expert FFN runs as a dense local matmul,
and a second ``all_to_all`` returns the outputs — the classic
Switch-Transformer dataflow expressed as two ICI collectives.

Capacity semantics: each expert accepts at most
``capacity = ceil(tokens_per_rank * capacity_factor / n_experts)`` tokens
per source rank; overflowing tokens are *dropped* (contribute zero, the
standard switch behavior) and reported via the aux outputs.  The router
gate is applied on the combine side so gradients flow into the router.

Call inside ``shard_map``; one expert per ``ep`` rank (``n_experts ==
_axis_size(axis_name)``).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .distributed import _axis_size


class MoEAux(NamedTuple):
    """Routing diagnostics + the load-balancing loss term."""
    load_balance_loss: jnp.ndarray    # scalar, Switch aux loss
    dropped_fraction: jnp.ndarray     # scalar in [0, 1]


def _dispatch_indices(assign, n_experts, capacity):
    """Position of each token within its expert's capacity buckets.

    Returns ``(slot, kept)``: ``slot[t]`` = index in [0, capacity) of token
    ``t`` inside its expert bucket, ``kept[t]`` = False when the bucket was
    already full (token dropped).
    """
    onehot = jax.nn.one_hot(assign, n_experts, dtype=jnp.int32)  # [T, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot          # 1-based
    slot = jnp.sum(pos_in_expert, axis=1) - 1                    # [T]
    kept = slot < capacity
    return jnp.clip(slot, 0, capacity - 1), kept


def moe_layer(x, router_w, expert_fn: Callable, expert_params, *,
              axis_name: str, capacity_factor: float = 1.25):
    """Top-1 (switch) mixture-of-experts over the ``ep`` mesh axis.

    ``x``: ``[T, d]`` this rank's token shard.  ``router_w``: ``[d, E]``
    replicated router weights.  ``expert_fn(params, h) -> h`` applied by
    this rank to every token routed to its expert; ``expert_params`` is
    this rank's expert's parameter pytree (shard the stacked experts with
    ``P("ep")`` and squeeze, as with the pipeline's stage params).

    Returns ``(y [T, d], MoEAux)``.
    """
    n_experts = _axis_size(axis_name)
    if router_w.shape[-1] != n_experts:
        raise ValueError(
            f"router_w has {router_w.shape[-1]} expert columns but the "
            f"'{axis_name}' axis has {n_experts} ranks — this layer places "
            f"exactly one expert per rank")
    t_local, d = x.shape
    capacity = max(1, math.ceil(t_local * capacity_factor / n_experts))

    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                      # [T, E]
    gate = jnp.max(probs, axis=-1)                               # [T]
    assign = jnp.argmax(probs, axis=-1)                          # [T]

    # Switch load-balancing aux loss: E * sum_e f_e * P_e, with f (expert
    # token fractions) and P (router prob means) taken over the GLOBAL
    # batch — mean-of-local-products != product-of-global-means when
    # routing skews differ across ep ranks, so pmean both before the sum.
    f = jnp.mean(jax.nn.one_hot(assign, n_experts, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    f = lax.pmean(f, axis_name)
    p = lax.pmean(p, axis_name)
    lb_loss = n_experts * jnp.sum(f * p)

    slot, kept = _dispatch_indices(assign, n_experts, capacity)

    # Scatter tokens into per-expert capacity buckets [E, C, d].
    dispatch = jnp.zeros((n_experts, capacity, d), x.dtype)
    dispatch = dispatch.at[
        jnp.where(kept, assign, 0),
        slot].add(jnp.where(kept[:, None], x, 0.0).astype(x.dtype))

    # all_to_all #1: bucket e of every source rank lands on rank e.
    # [E, C, d] -> [E_src, C, d] on the expert's rank.
    arrived = lax.all_to_all(dispatch, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)

    out = expert_fn(expert_params, arrived.reshape(-1, d))
    out = out.reshape(n_experts, capacity, d)

    # all_to_all #2: return each source rank its tokens.
    returned = lax.all_to_all(out, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)           # [E, C, d]

    # Combine: gather each kept token's output, weight by its gate.
    y = returned[jnp.where(kept, assign, 0), slot]
    y = jnp.where(kept[:, None], y, 0.0)
    y = (y.astype(jnp.float32) * gate[:, None]).astype(x.dtype)

    dropped = 1.0 - jnp.mean(kept.astype(jnp.float32))
    return y, MoEAux(load_balance_loss=lb_loss, dropped_fraction=dropped)
