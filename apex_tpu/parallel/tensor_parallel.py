"""Tensor (model) parallelism over a mesh axis — Megatron-style split.

Beyond-parity scope (the reference implements data parallelism only,
SURVEY.md §2.10); on TPU tensor parallelism is the natural second mesh
axis, riding ICI with one ``psum`` per row-parallel matmul.

The canonical pattern (used by the dryrun's dp × tp phase and the tests):

* **column-parallel** weight ``[d_in, d_out/ntp]`` per shard — output is
  feature-sharded, NO collective (the gather is deferred);
* **row-parallel** weight ``[d_in/ntp, d_out]`` per shard — consumes the
  feature-sharded activation and ``psum``s the partial products over the
  tp axis.

A column→row pair (the transformer MLP / attention-out shape) therefore
costs exactly one all-reduce, and weight gradients stay local to each
shard — the dp gradient reduction must run over the *data* axis only for
these params (``reduce_gradients(axis_name="data")``), which is why they
live in a separate pytree subtree by convention.

Use inside ``shard_map`` with the weights' ``PartitionSpec`` carrying the
tp axis on the split dimension::

    mesh = Mesh(devices.reshape(dp, tp), ("data", "tp"))
    in_specs = (P(), {"w1": P(None, "tp"), "w2": P("tp", None)}, ...)

Sharded-parameter *initialization* helpers are provided so a replicated
fp32 master checkpoint maps deterministically onto shards
(``shard_column`` / ``shard_row``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .distributed import _axis_size


def _axis_index(axis_name):
    return lax.axis_index(axis_name)


def column_parallel_dense(x, w_local, b_local=None):
    """``y_local = x @ w_local (+ b_local)`` — output feature-sharded.

    ``w_local``: this shard's ``[d_in, d_out/ntp]`` slice, ``b_local`` the
    matching bias slice.  No collective.
    """
    y = jnp.dot(x, w_local.astype(x.dtype))
    if b_local is not None:
        y = y + b_local.astype(y.dtype)
    return y


def row_parallel_dense(x_local, w_local, axis_name: str, b=None):
    """``y = psum_tp(x_local @ w_local) (+ b)`` — the one collective of a
    column→row pair.

    ``x_local``: feature-sharded activation ``[..., d_in/ntp]``;
    ``w_local``: this shard's ``[d_in/ntp, d_out]`` slice; ``b`` is the
    full (replicated) bias, added AFTER the reduction so it isn't summed
    ntp times.
    """
    partial = jnp.dot(x_local, w_local.astype(x_local.dtype))
    y = lax.psum(partial, axis_name)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def tp_mlp(x, w1_local, b1_local, w2_local, b2, axis_name: str,
           activation=jax.nn.gelu):
    """Megatron MLP: column-parallel up-proj, activation, row-parallel
    down-proj — one psum total."""
    h = column_parallel_dense(x, w1_local, b1_local)
    h = activation(h.astype(jnp.float32)).astype(x.dtype)
    return row_parallel_dense(h, w2_local, axis_name, b=b2)


def tp_self_attention(x, wqkv_local, wo_local, num_heads_local: int,
                      axis_name: str, causal: bool = False,
                      attention_fn=None):
    """Head-parallel self-attention: each tp shard owns
    ``num_heads/ntp`` heads end-to-end; the output projection is
    row-parallel (one psum).

    ``wqkv_local``: ``[d, 3, heads_local, head_dim]``;
    ``wo_local``: ``[heads_local * head_dim, d]``.

    The default ``attention_fn`` is :func:`~apex_tpu.ops.flash_attention.
    flash_attention` (r3, VERDICT r2 weak #3): on TPU the tp shard's local
    heads run the Pallas flash kernel (which traces under shard_map's
    default vma tracking since the operand alignment fix); off-TPU or on
    non-tiling shapes it degrades to the same jnp blockwise math it used
    before, so the change is pure speedup.
    """
    if wqkv_local.shape[2] != num_heads_local:
        raise ValueError(
            f"num_heads_local={num_heads_local} does not match "
            f"wqkv_local's head dim {wqkv_local.shape[2]} — pass this "
            f"shard's head count (global heads / tp axis size)")
    b, t, d = x.shape
    qkv = jnp.einsum("btd,dche->btche", x, wqkv_local.astype(x.dtype))
    q, k, v = (qkv[:, :, i] for i in range(3))    # each [b, t, h_local, e]
    if attention_fn is None:
        from ..ops.flash_attention import flash_attention
        attention_fn = lambda q, k, v: flash_attention(q, k, v,
                                                       causal=causal)
    ctx = attention_fn(q, k, v)                       # [b, t, h_local, hd]
    ctx = ctx.reshape(b, t, -1)
    return row_parallel_dense(ctx, wo_local, axis_name)


# -- checkpoint <-> shard mapping ---------------------------------------------

def shard_column(w, axis_name: str, n: Optional[int] = None):
    """Slice a replicated ``[d_in, d_out]`` weight to this shard's
    column-parallel ``[d_in, d_out/n]`` piece (inside shard_map)."""
    n = n or _axis_size(axis_name)
    if w.shape[-1] % n:
        raise ValueError(
            f"column-parallel split needs d_out {w.shape[-1]} divisible by "
            f"the tp axis size {n} — trailing columns would be dropped")
    cols = w.shape[-1] // n
    return lax.dynamic_slice_in_dim(w, _axis_index(axis_name) * cols, cols,
                                    axis=w.ndim - 1)


def shard_row(w, axis_name: str, n: Optional[int] = None):
    """Slice a replicated ``[d_in, d_out]`` weight to this shard's
    row-parallel ``[d_in/n, d_out]`` piece (inside shard_map)."""
    n = n or _axis_size(axis_name)
    if w.shape[0] % n:
        raise ValueError(
            f"row-parallel split needs d_in {w.shape[0]} divisible by "
            f"the tp axis size {n} — trailing rows would be dropped")
    rows = w.shape[0] // n
    return lax.dynamic_slice_in_dim(w, _axis_index(axis_name) * rows, rows,
                                    axis=0)
