"""Multi-host runtime: real ``jax.distributed`` launch + the spawner.

On TPU pods the model is ONE process per host, each seeing its local
chips, coordinated by ``jax.distributed.initialize`` (reference
``apex/parallel/multiproc.py:12-34`` spawns N single-GPU workers; the
TPU analog spawns one worker per host).  Two layers live here
(ISSUE 12):

* :func:`initialize` — the per-process entry: coordinator address /
  process id / process count autodetected from the environment
  (``JAX_COORDINATOR_ADDRESS``/``JAX_PROCESS_ID``/``JAX_NUM_PROCESSES``,
  the torchrun-style ``MASTER_ADDR``+``MASTER_PORT``/``RANK``/
  ``WORLD_SIZE``, or cloud-TPU metadata via jax's own autodetect),
  idempotent, with gloo CPU collectives enabled so the SAME code path
  runs on a CPU CI box (``docker/run_matrix.sh``'s 2-process lane and
  the ``bench.py`` multi-host fixture are real multi-process runs).
  After it returns, ``jax.devices()`` spans every process and a
  :class:`~apex_tpu.parallel.mesh.MeshPlan` built from it is the
  per-process view of one global mesh.
* :func:`main` — the local spawner (``python -m
  apex_tpu.parallel.multiproc --nproc N train.py ...``): one worker per
  host entry with the env above set, rank>0 stdout to ``TPU_<i>.log``.

:func:`process_identity` / :func:`is_coordinator` are the single
source of process identity for the rest of the stack —
``CheckpointManager`` per-host shard writes and telemetry run stamps
read THEM instead of ad-hoc ``jax.process_index()`` calls, so a worker
that has not (yet) initialized the distributed runtime still shards
and stamps correctly from its environment.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Optional, Tuple

_STATE = {"initialized": False, "procs": None}

#: env spellings accepted for each field, first hit wins (jax-native
#: first, then the torchrun/reference convention the spawner sets).
_ENV_COORD = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
_ENV_NPROC = ("JAX_NUM_PROCESSES", "WORLD_SIZE")
_ENV_PID = ("JAX_PROCESS_ID", "RANK")


def _env_int(names) -> Optional[int]:
    for n in names:
        v = os.environ.get(n)
        if v is not None and v.strip():
            try:
                return int(v)
            except ValueError:
                raise ValueError(f"env {n}={v!r} is not an integer")
    return None


def _env_coordinator() -> Optional[str]:
    for n in _ENV_COORD:
        v = os.environ.get(n)
        if v:
            return v
    host, port = os.environ.get("MASTER_ADDR"), os.environ.get("MASTER_PORT")
    if host and port:
        return f"{host}:{port}"
    return None


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None) -> Tuple[int, int]:
    """Join the distributed runtime; returns ``(process_id, count)``.

    Every argument defaults from the environment (see module
    docstring).  Single-process (no env, no args, or count 1) is a
    no-op returning ``(0, 1)`` — safe to call unconditionally at the
    top of every entry point.  Idempotent: a second call returns the
    established identity without re-initializing (jax raises on double
    init; schedulers restart entry points).

    On CPU backends the gloo collectives implementation is enabled
    first (config is a no-op where jaxlib lacks the knob), so
    multi-process CPU runs exchange REAL collectives — the bench
    fixture's parity gate depends on it.
    """
    if _STATE["initialized"]:
        return _STATE["procs"]
    if coordinator_address is None:
        coordinator_address = _env_coordinator()
    if num_processes is None:
        num_processes = _env_int(_ENV_NPROC)
    if process_id is None:
        process_id = _env_int(_ENV_PID)

    if (num_processes is None or num_processes <= 1) \
            and coordinator_address is None:
        _STATE["initialized"] = True
        _STATE["procs"] = (0, 1)
        return _STATE["procs"]

    import jax

    try:
        # Cross-process CPU collectives (no-op on TPU jaxlibs without
        # the flag; TPU pods use ICI natively).
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:                                # pragma: no cover
        pass
    kw = {}
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id, **kw)
    _STATE["initialized"] = True
    _STATE["procs"] = (int(jax.process_index()), int(jax.process_count()))  # jaxlint: disable=J001 -- process identity is a host-side distributed-setup constant, not a device value
    return _STATE["procs"]


def process_identity() -> Tuple[int, int]:
    """``(process_index, process_count)`` of this host — THE identity
    the checkpoint shard writer and telemetry run stamps use.

    Resolution order: an :func:`initialize`-established identity; the
    live jax distributed state when someone else initialized it; the
    launcher environment (a spawned worker that has not called
    :func:`initialize` yet still owns its shard); single-process
    ``(0, 1)``."""
    if _STATE["initialized"]:
        return _STATE["procs"]
    try:
        import jax
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is not None:
            return (int(jax.process_index()), int(jax.process_count()))  # jaxlint: disable=J001 -- process identity is a host-side distributed-setup constant, not a device value
    except Exception:                                # pragma: no cover
        pass
    pid, n = _env_int(_ENV_PID), _env_int(_ENV_NPROC)
    if pid is not None and n is not None and n > 1:
        if not 0 <= pid < n:
            raise ValueError(f"process id {pid} not in [0, {n}) "
                             f"(check RANK/WORLD_SIZE env)")
        return (pid, n)
    try:
        import jax
        return (int(jax.process_index()), int(jax.process_count()))  # jaxlint: disable=J001 -- process identity is a host-side distributed-setup constant, not a device value
    except Exception:                                # pragma: no cover
        return (0, 1)


def is_coordinator() -> bool:
    """True on the elected coordinator (process 0) — gate single-writer
    work (run stamps, manifest extras, log lines) on THIS instead of
    re-deriving rank conventions per call site."""
    return process_identity()[0] == 0


def docstring_hack():
    """Multiproc file which will launch a set of processes locally for
    multi-host training (reference docstring parity)."""
    pass


def worker_env(rank: int, nproc: int, coordinator: str,
               base: Optional[dict] = None) -> dict:
    """The environment one spawned worker needs — shared by
    :func:`main` and the test/bench fixtures so the spawner and the
    autodetect in :func:`initialize` can never drift."""
    env = dict(os.environ if base is None else base)
    env.update(RANK=str(rank), WORLD_SIZE=str(nproc),
               JAX_COORDINATOR_ADDRESS=coordinator,
               JAX_NUM_PROCESSES=str(nproc),
               JAX_PROCESS_ID=str(rank))
    return env


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument("--nproc", type=int,
                        default=int(os.environ.get("WORLD_SIZE", "1")))
    parser.add_argument("--coordinator", type=str, default="127.0.0.1:12355")
    args, rest = parser.parse_known_args(argv)

    workers = []
    for rank in range(args.nproc):
        env = worker_env(rank, args.nproc, args.coordinator)
        cmd = [sys.executable] + rest + ["--rank", str(rank)]
        stdout = None if rank == 0 else open("TPU_{}.log".format(rank), "w")
        workers.append(subprocess.Popen(cmd, env=env, stdout=stdout))

    rc = 0
    for w in workers:
        w.wait()
        rc = rc or w.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
