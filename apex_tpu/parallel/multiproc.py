"""Multi-process launcher (reference ``apex/parallel/multiproc.py:12-34``).

On TPU pods the normal model is ONE process per host, each seeing its local
chips, coordinated via ``jax.distributed.initialize`` — not N processes per
device.  This launcher reproduces the reference's behavior for that model:
spawn one worker per host entry, append ``--rank i``, set the JAX
distributed env, and redirect rank>0 stdout to ``TPU_<i>.log``.

Usage::

    python -m apex_tpu.parallel.multiproc --nproc 2 train.py --args...
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def docstring_hack():
    """Multiproc file which will launch a set of processes locally for
    multi-host training (reference docstring parity)."""
    pass


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument("--nproc", type=int,
                        default=int(os.environ.get("WORLD_SIZE", "1")))
    parser.add_argument("--coordinator", type=str, default="127.0.0.1:12355")
    args, rest = parser.parse_known_args(argv)

    workers = []
    for rank in range(args.nproc):
        env = dict(os.environ,
                   RANK=str(rank),
                   WORLD_SIZE=str(args.nproc),
                   JAX_COORDINATOR_ADDRESS=args.coordinator,
                   JAX_NUM_PROCESSES=str(args.nproc),
                   JAX_PROCESS_ID=str(rank))
        cmd = [sys.executable] + rest + ["--rank", str(rank)]
        stdout = None if rank == 0 else open("TPU_{}.log".format(rank), "w")
        workers.append(subprocess.Popen(cmd, env=env, stdout=stdout))

    rc = 0
    for w in workers:
        w.wait()
        rc = rc or w.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
