"""Pipeline parallelism — SPMD collective-permute pipeline over a mesh axis.

Beyond-parity scope (the reference implements data parallelism only,
SURVEY.md §2.10).  The TPU-idiomatic pipeline is NOT a scheduler with
per-stage processes (the GPU pattern): it is ONE SPMD program in which

* each ``pp`` rank holds one stage's parameters (a ``[n_stages, ...]``
  stacked pytree sharded on the leading axis),
* a ``lax.scan`` over ``n_stages + n_microbatches - 1`` clock ticks runs
  every stage every tick, rotating activations to the next rank with a
  single ``ppermute`` per tick (riding the ICI ring),
* stage 0 injects microbatch ``t`` and the last stage collects output
  ``t - (n_stages-1)``; off-schedule positions compute on don't-care data
  that the output select masks out, so their gradients are exactly zero,
* the BACKWARD schedule is not hand-written at all: differentiating the
  scan reverses it, and the transpose of ``ppermute`` is the reverse
  rotation — jax.grad through ``spmd_pipeline`` IS the reverse pipeline.

This trades the classic pipeline bubble (every rank computes every tick)
for compiler-visible regularity — the standard SPMD pipelining recipe on
TPU meshes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _rotate(x, axis_name: str):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def spmd_pipeline(stage_fn: Callable, stage_params, x, *,
                  axis_name: str, num_microbatches: int):
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``,
    pipelined over the ``axis_name`` mesh axis.

    Call inside ``shard_map``.  Arguments:

    * ``stage_fn(params_i, h) -> h`` — one stage; applied by rank ``i``
      with its own parameters.  Activation shapes must be identical across
      stages (the homogeneous-stack restriction of scan-over-layers).
    * ``stage_params`` — this rank's slice of the ``[n_stages, ...]``
      stacked parameter pytree (shard the stack with ``P("pp")``); leading
      axis of length 1 is squeezed.
    * ``x`` — ``[batch, ...]`` input, replicated over the pp axis;
      split into ``num_microbatches`` along the batch dim.

    Returns ``[batch, ...]`` outputs, replicated over the pp axis.
    """
    n_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    params_i = jax.tree_util.tree_map(
        lambda p: jnp.squeeze(p, axis=0) if p.shape[0] == 1 else p,
        stage_params)

    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {num_microbatches}")
    mb = batch // num_microbatches
    micro = x.reshape(num_microbatches, mb, *x.shape[1:])

    ticks = n_stages + num_microbatches - 1
    # The scan carry varies per pp rank from tick 1 on; mark the zero
    # initializers as axis-varying so the carry type is stable under
    # shard_map's vma checking.
    def _pvary(v):
        try:
            return lax.pcast(v, (axis_name,), to="varying")
        except (AttributeError, TypeError):  # older jax spelling
            return lax.pvary(v, (axis_name,))
    buf0 = _pvary(jnp.zeros_like(micro[0]))
    out0 = _pvary(jnp.zeros_like(micro))

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (clamped; off-schedule data is
        # masked out at collection)
        feed = lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, num_microbatches - 1), keepdims=False)
        h_in = jnp.where(idx == 0, feed, buf)
        h_out = stage_fn(params_i, h_in)
        # last stage collects microbatch m = t - (n_stages - 1)
        m = t - (n_stages - 1)
        is_last = idx == n_stages - 1
        valid = jnp.logical_and(is_last, m >= 0)
        slot = jnp.clip(m, 0, num_microbatches - 1)
        cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        upd = jnp.where(valid, h_out, cur)
        outs = lax.dynamic_update_index_in_dim(outs, upd, slot, axis=0)
        # rotate activations to the next stage for the next tick
        buf = _rotate(h_out, axis_name)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))

    # Outputs live on the last rank; replicate them over the pp axis so the
    # loss (and its gradient path) is identical on every rank.
    outs = lax.psum(jnp.where(idx == n_stages - 1, outs, 0.0), axis_name)
    return outs.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees into the ``[n_stages,
    ...]`` pytree ``spmd_pipeline`` expects (shard its leading axis over
    the pp mesh axis with ``P("pp")``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
