"""Pipeline parallelism — SPMD collective-permute pipeline over a mesh axis.

Beyond-parity scope (the reference implements data parallelism only,
SURVEY.md §2.10).  The TPU-idiomatic pipeline is NOT a scheduler with
per-stage processes (the GPU pattern): it is ONE SPMD program in which

* each ``pp`` rank holds one stage's parameters (a ``[n_stages, ...]``
  stacked pytree sharded on the leading axis),
* a ``lax.scan`` over ``n_stages + n_microbatches - 1`` clock ticks runs
  every stage every tick, rotating activations to the next rank with a
  single ``ppermute`` per tick (riding the ICI ring),
* stage 0 injects microbatch ``t`` and the last stage collects output
  ``t - (n_stages-1)``; off-schedule positions compute on don't-care data
  that the output select masks out, so their gradients are exactly zero,
* the BACKWARD schedule is not hand-written at all: differentiating the
  scan reverses it, and the transpose of ``ppermute`` is the reverse
  rotation — jax.grad through ``spmd_pipeline`` IS the reverse pipeline.

This trades the classic pipeline bubble (every rank computes every tick)
for compiler-visible regularity — the standard SPMD pipelining recipe on
TPU meshes.

Bubble cost (VERDICT r2 weak #7, now documented): with ``p`` ranks and
``m`` microbatches, :func:`spmd_pipeline` runs ``p + m - 1`` ticks of
which only ``m`` carry useful work per rank — bubble fraction
``(p-1)/(p+m-1)``.  :func:`spmd_pipeline_interleaved` cuts that by the
``chunks_per_rank`` factor ``v`` (the Megatron-interleaved /
circular-pipeline recipe): the model is split into ``S = p*v`` virtual
stages assigned round-robin (stage ``s`` on rank ``s % p``), each tick
runs ONE virtual stage (1/v the work), and the schedule takes
``m*v + p - 1`` ticks — wall ∝ ``(m*v + p - 1)/v`` vs GPipe's
``(m + p - 1)``, i.e. bubble ``(p-1)/v`` full-stage units.  The backward
is still free: differentiating the scan reverses the interleaved
schedule exactly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .distributed import _axis_size


def _rotate(x, axis_name: str):
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def spmd_pipeline(stage_fn: Callable, stage_params, x, *,
                  axis_name: str, num_microbatches: int):
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``,
    pipelined over the ``axis_name`` mesh axis.

    Call inside ``shard_map``.  Arguments:

    * ``stage_fn(params_i, h) -> h`` — one stage; applied by rank ``i``
      with its own parameters.  Activation shapes must be identical across
      stages (the homogeneous-stack restriction of scan-over-layers).
    * ``stage_params`` — this rank's slice of the ``[n_stages, ...]``
      stacked parameter pytree (shard the stack with ``P("pp")``); leading
      axis of length 1 is squeezed.
    * ``x`` — ``[batch, ...]`` input, replicated over the pp axis;
      split into ``num_microbatches`` along the batch dim.

    Returns ``[batch, ...]`` outputs, replicated over the pp axis.
    """
    n_stages = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    params_i = jax.tree_util.tree_map(
        lambda p: jnp.squeeze(p, axis=0) if p.shape[0] == 1 else p,
        stage_params)

    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {num_microbatches}")
    mb = batch // num_microbatches
    micro = x.reshape(num_microbatches, mb, *x.shape[1:])

    ticks = n_stages + num_microbatches - 1
    # The scan carry varies per pp rank from tick 1 on; mark the zero
    # initializers as axis-varying so the carry type is stable under
    # shard_map's vma checking.
    def _pvary(v):
        try:
            return lax.pcast(v, (axis_name,), to="varying")
        except (AttributeError, TypeError):  # older jax spelling
            try:
                return lax.pvary(v, (axis_name,))
            except AttributeError:   # pre-vma jax: nothing to mark
                return v
    buf0 = _pvary(jnp.zeros_like(micro[0]))
    out0 = _pvary(jnp.zeros_like(micro))

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (clamped; off-schedule data is
        # masked out at collection)
        feed = lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, num_microbatches - 1), keepdims=False)
        h_in = jnp.where(idx == 0, feed, buf)
        h_out = stage_fn(params_i, h_in)
        # last stage collects microbatch m = t - (n_stages - 1)
        m = t - (n_stages - 1)
        is_last = idx == n_stages - 1
        valid = jnp.logical_and(is_last, m >= 0)
        slot = jnp.clip(m, 0, num_microbatches - 1)
        cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        upd = jnp.where(valid, h_out, cur)
        outs = lax.dynamic_update_index_in_dim(outs, upd, slot, axis=0)
        # rotate activations to the next stage for the next tick
        buf = _rotate(h_out, axis_name)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))

    # Outputs live on the last rank; replicate them over the pp axis so the
    # loss (and its gradient path) is identical on every rank.
    outs = lax.psum(jnp.where(idx == n_stages - 1, outs, 0.0), axis_name)
    return outs.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees into the ``[n_stages,
    ...]`` pytree ``spmd_pipeline`` expects (shard its leading axis over
    the pp mesh axis with ``P("pp")``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def spmd_pipeline_interleaved(stage_fn: Callable, stage_params, x, *,
                              axis_name: str, num_microbatches: int):
    """Interleaved (circular) pipeline: each rank holds ``v`` virtual
    stages assigned round-robin, cutting the bubble by ``v`` (module
    docstring has the arithmetic).

    Call inside ``shard_map``.  Arguments:

    * ``stage_fn(params_c, h) -> h`` — ONE virtual stage (1/v of the
      model); homogeneous activation shapes as in :func:`spmd_pipeline`.
    * ``stage_params`` — this rank's ``[v, ...]`` slice of the
      ``[v, p, ...]`` round-robin stack built by
      :func:`stack_interleaved_stage_params` (shard axis 1 with
      ``P(None, "pp")``); a kept axis of length 1 is squeezed.  EVERY
      leaf must carry the ``[v, 1, ...]`` leading axes — broadcast
      leaves shared across stages are not supported (stack them into
      the round-robin stack like any other leaf); an unstacked leaf
      raises rather than passing through ambiguously (ADVICE r3/r4).
    * ``x`` — ``[batch, ...]`` replicated input; ``num_microbatches``
      must divide the batch, and the microbatch count must be a multiple
      of the pp axis size (the schedule fills the ring in groups of
      ``p`` — pad the batch or lower ``num_microbatches`` otherwise).

    Schedule: virtual stage ``s = c*p + r`` (chunk ``c``, rank ``r``);
    microbatch group ``g``, member ``j`` enters chunk ``c`` on rank ``r``
    at tick ``τ = g*p*v + c*p + j + r``.  For a given ``(τ, r)`` the
    decomposition ``u = τ - r = ((g*v + c)*p + j)`` is unique, so every
    rank executes exactly one microbatch-chunk per tick — no collisions,
    ``m*v + p - 1`` ticks total, activations rotating one hop per tick.
    """
    p = _axis_size(axis_name)
    r = lax.axis_index(axis_name)
    leaves = jax.tree_util.tree_leaves(stage_params)
    v = int(leaves[0].shape[0])
    # The [v, p, ...] round-robin stack must arrive with axis 1 already
    # sharded to length 1 (P(None, "pp") inside shard_map).  Validate it
    # here: squeezing on shape alone would let an unsharded stack (or
    # pre-squeezed params) surface only as a confusing downstream shape
    # error inside stage_fn (ADVICE r3).
    bad = [tuple(q.shape) for q in leaves
           if not (q.ndim >= 2 and q.shape[1] == 1)]
    if bad:
        why = ("axis 1 has length != 1 — the stack arrived unsharded or "
               "pre-squeezed" if all(len(s) >= 2 for s in bad)
               else "some leaves lack the [v, p] leading axes entirely")
        raise ValueError(
            f"stage_params must be this rank's [v, 1, ...] slice of the "
            f"[v, p, ...] stack from stack_interleaved_stage_params, "
            f"sharded over the pp axis with P(None, {axis_name!r}) inside "
            f"shard_map; got leaves with shapes {bad[:3]} ({why}). "
            f"Pass the UN-squeezed stack and shard axis 1.")
    params_v = jax.tree_util.tree_map(
        lambda q: jnp.squeeze(q, axis=1), stage_params)

    m = num_microbatches
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by "
                         f"num_microbatches {m}")
    if m % p:
        # the ring fills in groups of p; a partial last group would drain
        # past the m*v + p - 1 tick horizon and silently lose outputs
        raise ValueError(f"num_microbatches {m} must be a multiple of the "
                         f"pp axis size {p} for the interleaved schedule")
    mb = batch // m
    micro = x.reshape(m, mb, *x.shape[1:])

    ticks = m * v + p - 1

    def _pvary(val):
        try:
            return lax.pcast(val, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            try:
                return lax.pvary(val, (axis_name,))
            except AttributeError:   # pre-vma jax: nothing to mark
                return val

    buf0 = _pvary(jnp.zeros_like(micro[0]))
    out0 = _pvary(jnp.zeros_like(micro))

    def tick(carry, tau):
        buf, outs = carry
        u = tau - r
        upos = jnp.maximum(u, 0)
        g = upos // (p * v)
        rem = upos % (p * v)
        c = rem // p                      # this rank's active chunk
        j = rem % p                       # group member
        t_mb = g * p + j                  # global microbatch id
        valid = jnp.logical_and(u >= 0, t_mb < m)
        feed_idx = jnp.clip(t_mb, 0, m - 1)
        feed = lax.dynamic_index_in_dim(micro, feed_idx, keepdims=False)
        # rank 0 / chunk 0 injects; everything else consumes the rotated
        # activation (stage s-1 output: rank r-1 same chunk, or rank p-1
        # chunk c-1 when r == 0)
        h_in = jnp.where(jnp.logical_and(r == 0, c == 0), feed, buf)
        params_c = jax.tree_util.tree_map(
            lambda q: lax.dynamic_index_in_dim(q, c, keepdims=False),
            params_v)
        h_out = stage_fn(params_c, h_in)
        emit = jnp.logical_and(
            jnp.logical_and(r == p - 1, c == v - 1), valid)
        cur = lax.dynamic_index_in_dim(outs, feed_idx, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(emit, h_out, cur), feed_idx, axis=0)
        buf = _rotate(h_out, axis_name)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    outs = lax.psum(jnp.where(r == p - 1, outs, 0.0), axis_name)
    return outs.reshape(batch, *x.shape[1:])


def stack_interleaved_stage_params(per_stage_params, n_ranks: int):
    """Stack ``S = v * n_ranks`` per-stage pytrees into the ``[v, p, ...]``
    round-robin layout of :func:`spmd_pipeline_interleaved` (virtual stage
    ``s`` at ``[s // p, s % p]``); shard axis 1 with ``P(None, "pp")``."""
    S = len(per_stage_params)
    if S % n_ranks:
        raise ValueError(f"{S} stages not divisible by pp size {n_ranks}")
    v = S // n_ranks
    rows = [
        jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves),
            *(per_stage_params[c * n_ranks + r] for r in range(n_ranks)))
        for c in range(v)
    ]
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *rows)
