"""ZeRO-1: optimizer-state sharding over the data axis.

Beyond-parity scope (the reference is plain DP: every rank holds the full
optimizer state).  The TPU-idiomatic ZeRO stage 1:

* gradients are **reduce-scattered** (mean) over the axis — each rank
  receives only its 1/n chunk of the flat gradient, replacing the DDP
  all-reduce at *half* the collective cost;
* the optimizer update runs on the local chunk only — moments and masters
  for 1/n of the parameters live on each rank;
* the updated chunk is **all-gathered** back into full replicated
  parameters for the next forward.

reduce_scatter + all_gather together move exactly what one all-reduce
moves, so ZeRO-1 costs no extra communication while dividing optimizer
memory by the axis size.

The whole-model flat-buffer view reuses the multi-tensor capability
(SURVEY §2.6: "whole-model single-launch updates"): the parameter pytree
is raveled into ONE padded fp32 vector, chunked over the axis.  With
``bucketed=True`` the ravel goes through a
:class:`~apex_tpu.multi_tensor.BucketStore` instead — one padded flat
buffer per parameter *dtype*, each sharded evenly over the axis — which
lifts the uniform-dtype restriction (mixed fp32/bf16 trees shard
per-bucket) while keeping O(buckets) collectives.  Works with
elementwise optimizers (adam, sgd); per-tensor-norm optimizers (lamb,
novograd) need tensor-granular sharding and are rejected — their trust
ratios are wrong on arbitrary flat chunks.

Usage (inside shard_map; the state's flat leaves are sharded over the
axis with ``P(axis)``)::

    tx = zero1(training.adam(1e-3), "data", num_shards=n)
    init_fn, step_fn = make_train_step(
        loss_fn, tx, opt_level="O2",
        axis_name=("data",), reduce_grads=False)  # zero1 owns the
        # reduction; axis_name still drives the mesh-wide dynamic-scaler
        # overflow agreement (a locally-computed skip mask would desync
        # scaler state and poison the moments of non-overflowing ranks
        # whose reduce-scattered chunk contains another rank's inf).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .distributed import _axis_size

try:        # jax>=0.8: Varying->Invariant gather for the vma type system;
    from jax._src.lax.parallel import (     # not yet re-exported publicly
        all_gather_invariant as _all_gather_invariant)
except ImportError:  # pragma: no cover
    _all_gather_invariant = None


class Zero1State(NamedTuple):
    inner: Any                    # wrapped optimizer's state over the chunk


def _flatten(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    dtypes = {jnp.asarray(l).dtype for l in leaves}
    if len(dtypes) != 1:
        raise ValueError(
            f"zero1 needs a uniform parameter dtype to build the flat "
            f"buffer; got {sorted(map(str, dtypes))} — under amp O2 the "
            f"fp32 masters satisfy this, or pass bucketed=True to shard "
            f"per-dtype flat buckets")
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def _unflatten(flat, like):
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out, off = [], 0
    for l in leaves:
        size = l.size
        out.append(flat[off:off + size].reshape(l.shape).astype(l.dtype))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def _gather_replicated(new_local, flat_like, idx, chunk, axis_name):
    """All-gather a rank's updated chunk back into the full replicated
    flat buffer, choosing the cheapest lowering the trace allows (see
    the vma discussion in ``distributed.py``)."""
    from .distributed import vma_tracking_live
    if not vma_tracking_live(axis_name):
        return lax.all_gather(new_local, axis_name, tiled=True)
    if _all_gather_invariant is not None:
        # Varying -> Invariant all-gather (r3, VERDICT r2 weak #8):
        # the plain all_gather's output is *typed* varying even though
        # it is semantically replicated, which would force a costly
        # masked-psum workaround; this primitive carries the
        # replicated type (and transposes to a cheap dynamic_slice),
        # so the default-config user pays one real all-gather — the
        # same collective as with check_vma=False.
        #
        # It is a PRIVATE jax API (jax._src.lax.parallel), so its
        # signature may drift between releases; a TypeError here must
        # degrade to the masked-psum fallback below, not explode at
        # trace time (ADVICE r3).
        try:
            return _all_gather_invariant(new_local, axis_name, tiled=True)
        except TypeError:
            pass
    # Very old jax without the primitive: gather as a masked psum
    # (invariant output) — a full all-reduce of a zeros-placed
    # buffer, correct but 2x the bytes on the wire.
    placed = lax.dynamic_update_slice_in_dim(
        jnp.zeros_like(flat_like), new_local, idx * chunk, axis=0)
    return lax.psum(placed, axis_name)


def _shard_one(flat_p, flat_g, state_inner, tx, n, idx, num_shards,
               axis_name, apply_mask, kw, *, pre_axes=(), denom=None):
    """reduce-scatter + local update + gather for ONE flat buffer.

    ``pre_axes`` names extra mesh axes the gradient must be psummed over
    BEFORE the scatter (the mesh frontend's pure-DP axis: replicas that
    hold the same shard chunks but saw different data); the psum runs on
    the already-scattered chunk, so the dp wire cost is 1/shards of the
    bucket.  ``denom`` overrides the mean divisor (the full data-replica
    count — ``n`` times the pre-axes' sizes); default ``n``, the single-
    axis zero1 contract."""
    from .distributed import _axis_size, _note_collective

    chunk0 = -(-flat_p.size // num_shards)
    pad = chunk0 * num_shards - flat_p.size
    if pad:
        flat_p = jnp.pad(flat_p, (0, pad))
        flat_g = jnp.pad(flat_g, (0, pad))
    chunk = flat_p.size // n
    # Telemetry (trace-time, ISSUE 5): the ZeRO collective pair moves
    # exactly one all-reduce's worth of bytes over the shard axis —
    # half on the scatter, half on the gather — plus one chunk-sized
    # psum per pure-DP axis.  Each event carries ITS axis name so the
    # fleet/timeline attribution can split traffic per mesh axis
    # (dp vs fsdp) instead of pooling it (ISSUE 12).
    _note_collective("psum_scatter", axis_name,
                     flat_g.size * jnp.dtype(flat_g.dtype).itemsize, 1,
                     dtype=flat_g.dtype)
    _note_collective("all_gather", axis_name,
                     flat_p.size * jnp.dtype(flat_p.dtype).itemsize, 1,
                     dtype=flat_p.dtype)
    # reduce-scatter(mean): the DDP gradient averaging, at half an
    # all-reduce, delivering only this rank's chunk.
    g_local = lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                               tiled=True)
    for ax in pre_axes:
        if _axis_size(ax) > 1:
            _note_collective("psum", ax,
                             chunk * jnp.dtype(flat_g.dtype).itemsize, 1,
                             dtype=flat_g.dtype)
            g_local = lax.psum(g_local, ax)
    g_local = g_local / (n if denom is None else denom)
    p_local = lax.dynamic_slice_in_dim(flat_p, idx * chunk, chunk)
    new_p_local, new_inner = tx.update(
        g_local, state_inner, p_local, apply_mask=apply_mask, **kw)
    flat_new = _gather_replicated(new_p_local, flat_p, idx, chunk,
                                  axis_name)
    if pad:
        flat_new = flat_new[:flat_p.size - pad]
    return flat_new, new_inner


def zero1(tx, axis_name: str, *, num_shards: int, bucketed: bool = False):
    """Wrap a :class:`~apex_tpu.training.FunctionalOptimizer` with ZeRO-1
    state sharding over ``axis_name`` (``num_shards`` = axis size, needed
    at init time, which runs outside shard_map).

    Returned optimizer contract: ``init(params)`` builds the FULL flat
    state (shard its flat leaves over the axis via ``P(axis_name)`` in
    your shard_map specs); ``update`` must run inside shard_map — it
    reduce-scatters the gradients itself, so build the train step with
    ``reduce_grads=False`` and keep ``axis_name`` set (the step still
    needs it for the mesh-wide overflow agreement under dynamic scaling
    and for the metric pmean).

    ``bucketed=True`` routes the flat view through a
    :class:`~apex_tpu.multi_tensor.BucketStore`: one padded flat bucket
    per parameter dtype, each sharded over the axis with its own inner
    optimizer state — mixed-dtype trees work, collectives stay
    O(buckets).
    """
    from ..training import FunctionalOptimizer

    if not getattr(tx, "elementwise", False):
        raise ValueError(
            "zero1 requires an optimizer that declares elementwise=True "
            "(FunctionalOptimizer capability flag) — adam/sgd qualify; "
            "per-tensor-norm optimizers (lamb, novograd) compute wrong "
            "trust ratios on arbitrary flat chunks, and unknown optimizers "
            "are rejected by default.  Shard at tensor granularity instead, "
            "or set elementwise=True on your FunctionalOptimizer if its "
            "update truly treats every element independently")

    from ..multi_tensor.buckets import padded_shard_len

    def _padded_len(n_elems):
        # The SAME rule the checkpoint manifest's bucket layout records
        # (elastic reshard-on-read re-slices against it).
        return padded_shard_len(n_elems, num_shards)

    if bucketed:
        from ..multi_tensor.buckets import BucketStore, cached_store

        cell = {}

        def _store(params) -> BucketStore:
            return cached_store(cell, params)

        def init(params):
            packed = _store(params).pack(params)
            inner = tuple(
                tx.init(jnp.pad(b, (0, _padded_len(b.size) - b.size)))
                for b in packed.data)
            return Zero1State(inner=inner)

        def update(grads, state, params, *, apply_mask=None, **kw):
            store = _store(params)
            n = _axis_size(axis_name)
            idx = lax.axis_index(axis_name)
            packed_p = store.pack(params)
            packed_g = store.pack(grads, cast=True)
            new_data, new_inner = [], []
            for flat_p, flat_g, st in zip(packed_p.data, packed_g.data,
                                          state.inner):
                flat_new, ni = _shard_one(
                    flat_p, flat_g.astype(flat_p.dtype), st, tx, n, idx,
                    num_shards, axis_name, apply_mask, kw)
                new_data.append(flat_new)
                new_inner.append(ni)
            from ..multi_tensor.buckets import Packed
            out = Packed(data=tuple(new_data), rest=packed_p.rest)
            return store.unpack(out), Zero1State(inner=tuple(new_inner))

        return FunctionalOptimizer(init=init, update=update)

    def init(params):
        flat = _flatten(params)
        pad = _padded_len(flat.size) - flat.size
        flat = jnp.pad(flat, (0, pad))
        return Zero1State(inner=tx.init(flat))

    def update(grads, state, params, *, apply_mask=None, **kw):
        n = _axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        flat_p = _flatten(params)
        flat_g = _flatten(grads).astype(flat_p.dtype)
        flat_new, new_inner = _shard_one(
            flat_p, flat_g, state.inner, tx, n, idx, num_shards,
            axis_name, apply_mask, kw)
        return _unflatten(flat_new, params), Zero1State(inner=new_inner)

    return FunctionalOptimizer(init=init, update=update)


def zero1_partition_spec(state: Zero1State, axis_name: str):
    """PartitionSpec pytree for a :class:`Zero1State`: flat (chunked)
    leaves sharded over the axis, scalars replicated."""
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        return P(axis_name) if jnp.ndim(leaf) >= 1 else P()

    return Zero1State(inner=jax.tree_util.tree_map(spec, state.inner))
