"""LARC — layerwise adaptive rate control.

Reference: ``apex/parallel/LARC.py:5-97``.  Per-param adaptive LR
``trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)``, clip mode
(``min(adaptive/lr, 1)``) or scale mode; implemented by rewriting gradients
before delegating to the wrapped optimizer, absorbing weight decay into the
rewritten grad (the reference temporarily zeroes group weight decay the same
way).

Two forms: ``LARC`` wraps an ``apex_tpu.optimizers`` class instance;
``larc_transform`` is the optax-style gradient transformation.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def larc_gradients(grads, params, *, lr, trust_coefficient=0.02, clip=True,
                   eps=1e-8, weight_decay=0.0):
    """Rewrite grads with the LARC adaptive rate (pure, jit-safe)."""
    def one(g, p):
        gf = jnp.asarray(g, jnp.float32)
        pf = jnp.asarray(p, jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
        adaptive_lr = (trust_coefficient * p_norm
                       / (g_norm + p_norm * weight_decay + eps))
        ok = (p_norm != 0) & (g_norm != 0)
        adaptive_lr = jnp.where(ok, adaptive_lr, 1.0)
        if clip:
            adaptive_lr = jnp.minimum(adaptive_lr / lr, 1.0)
        new_g = (gf + weight_decay * pf) * adaptive_lr
        return new_g.astype(jnp.asarray(g).dtype)

    return jax.tree_util.tree_map(one, grads, params)


class LARC:
    """Optimizer wrapper (reference class).  ``optim`` is an
    ``apex_tpu.optimizers.FusedOptimizer``; its weight decay is absorbed into
    the LARC grad rewrite exactly like the reference absorbs/restores group
    weight decay."""

    def __init__(self, optimizer, trust_coefficient=0.02, clip=True, eps=1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.eps = eps
        self.clip = clip

    def __getattr__(self, name):
        return getattr(self.optim, name)

    @property
    def param_groups(self):
        return self.optim.param_groups

    def step(self, grads=None, closure=None):
        from ..multi_tensor.buckets import Packed

        if grads is None:
            grads = self.optim._master_grads or self.optim._pending_grads
        if isinstance(grads, Packed) and not self.optim._grouped:
            # A bucketed amp optimizer delivers master grads as flat
            # buckets; LARC's per-tensor rewrite needs the pytree view.
            grads = self.optim.param_groups[0]["_store"].unpack_jit(grads)
        masters = self.optim.master_params    # unpacked, user-facing
        targets = (self.optim._to_groups(masters)
                   if masters is not None
                   else [g["params"] for g in self.optim.param_groups])
        # Per-group rewrite with the group's own lr and weight decay
        # (reference absorbs/restores wd per group, LARC.py:71-97).
        new_groups = []
        for gr, tgt, g in zip(self.optim._to_groups(grads), targets,
                              self.optim.param_groups):
            wd = g.get("weight_decay", 0.0)
            lr = g.get("lr", self.optim.defaults.get("lr"))
            new_groups.append(larc_gradients(
                gr, tgt, lr=lr, trust_coefficient=self.trust_coefficient,
                clip=self.clip, eps=self.eps, weight_decay=wd))
        new_grads = self.optim._from_groups(new_groups)
        # Absorb wd: temporarily zero it in the inner update (reference :42-97).
        saved = [g.get("weight_decay", 0.0) for g in self.optim.param_groups]
        saved_default = self.optim.defaults.get("weight_decay", 0.0)
        for g in self.optim.param_groups:
            g["weight_decay"] = 0.0
        self.optim.defaults["weight_decay"] = 0.0
        try:
            return self.optim.step(grads=new_grads, closure=closure)
        finally:
            self.optim.defaults["weight_decay"] = saved_default
            for g, wd in zip(self.optim.param_groups, saved):
                g["weight_decay"] = wd

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, sd):
        self.optim.load_state_dict(sd)


def larc_transform(lr, trust_coefficient=0.02, clip=True, eps=1e-8,
                   weight_decay=0.0) -> optax.GradientTransformation:
    """optax gradient transformation: chain before any base optimizer."""
    def init(params):
        return optax.EmptyState()

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc_transform requires params")
        lr_v = lr(0) if callable(lr) else lr
        return larc_gradients(grads, params, lr=lr_v,
                              trust_coefficient=trust_coefficient,
                              clip=clip, eps=eps,
                              weight_decay=weight_decay), state

    return optax.GradientTransformation(init, update)
