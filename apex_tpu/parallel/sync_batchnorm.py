"""SyncBatchNorm — cross-replica batch normalization over mesh axes.

TPU-native re-design of reference ``apex/parallel/sync_batchnorm.py`` (python
fallback) and ``optimized_sync_batchnorm*.py`` + ``csrc/welford.cu`` (CUDA
path).  One implementation replaces both:

* Local statistics are computed per replica, then combined across the mesh
  axis with a **count-weighted Welford-style parallel combine**
  (``welford_parallel``: reference ``csrc/welford.cu:558-586`` Chan et al.
  algorithm) expressed with ``lax.psum`` of (sum, sum_sq, count) — this
  handles unequal per-replica batches, which the reference python fallback's
  plain mean-of-means does not.
* The backward pass needs no hand-written kernel: the transpose of ``psum``
  is ``psum``, so autodiff derives exactly the reference's
  ``mean_dy``/``mean_dy_xmu`` allreduce structure
  (``sync_batchnorm_kernel.py:54-70``) from the forward.
* ``channel_last`` is the native layout on TPU (NHWC); ``fuse_relu``
  reproduces the optimized module's fused BN(+z)+ReLU epilogue
  (``optimized_sync_batchnorm.py:9-89``) — XLA fuses it into the normalize.
* BN process groups (``group_size`` sub-worlds) map to ``axis_index_groups``
  (reference ``create_syncbn_process_group``, ``parallel/__init__.py:55-96``).

Running stats follow the torch convention: ``running = (1-momentum)*running +
momentum*batch_stat`` with *unbiased* batch variance (reference
``sync_batchnorm.py:95-131``), stored in the flax ``batch_stats`` collection.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn


def welford_parallel(mean, var, count):
    """Combine per-replica (mean, biased var, count) into global stats.

    Functional form of ``syncbn.welford_parallel`` (``welford.cu:558-586``):
    given stacked per-group stats along axis 0, returns combined (mean, var).
    Used directly by tests as the oracle; inside the module the same math is
    expressed with psums for efficiency.
    """
    count = jnp.asarray(count, jnp.float32)
    total = jnp.sum(count, axis=0)
    mean_all = jnp.sum(mean * count, axis=0) / total
    # E[x^2] recombination: var_g + mean_g^2 weighted.
    ex2 = jnp.sum((var + mean ** 2) * count, axis=0) / total
    return mean_all, ex2 - mean_all ** 2


def _global_moments(x, reduce_axes, axis_name, axis_index_groups):
    """Cross-replica mean/var over ``reduce_axes`` of x (fp32 accumulation).

    Equivalent of welford_mean_var + all_gather + welford_parallel
    (``optimized_sync_batchnorm_kernel.py:22-55``), expressed as psum of
    (sum, sum_sq, count) — one fused all-reduce on the wire.
    """
    xf = x.astype(jnp.float32)
    local_sum = jnp.sum(xf, axis=reduce_axes)
    local_sqr = jnp.sum(jnp.square(xf), axis=reduce_axes)
    local_count = jnp.float32(1.0)
    for a in reduce_axes:
        local_count = local_count * x.shape[a]
    count = jnp.broadcast_to(local_count, local_sum.shape)
    if axis_name is not None:
        stacked = jnp.concatenate([local_sum, local_sqr, count])
        from .distributed import group_psum
        stacked = group_psum(stacked, axis_name, axis_index_groups)
        n = local_sum.shape[0]
        total_sum, total_sqr, total_count = (stacked[:n], stacked[n:2 * n],
                                             stacked[2 * n:])
    else:
        total_sum, total_sqr, total_count = local_sum, local_sqr, count
    mean = total_sum / total_count
    var = total_sqr / total_count - jnp.square(mean)
    return mean, var, total_count


class SyncBatchNorm(nn.Module):
    """Flax module with ``_BatchNorm`` semantics synced across a mesh axis.

    Args mirror the reference module (``sync_batchnorm.py:9-134`` +
    ``optimized_sync_batchnorm.py``): ``momentum`` is the *torch* momentum
    (weight of the new batch stat), ``process_group`` is an
    ``axis_index_groups`` list, ``channel_last`` chooses NHWC (the TPU-native
    layout, default True), ``fuse_relu`` fuses the optional ``z``-add and
    ReLU epilogue.
    """
    num_features: Optional[int] = None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = None
    process_group: Optional[Sequence[Sequence[int]]] = None
    channel_last: bool = True
    fuse_relu: bool = False
    use_running_average: Optional[bool] = None
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, z=None, use_running_average: Optional[bool] = None):
        use_ra = use_running_average
        if use_ra is None:
            use_ra = self.use_running_average
        if use_ra is None:
            use_ra = False

        if self.channel_last:
            channel_axis = x.ndim - 1
        else:
            channel_axis = 1
        reduce_axes = tuple(a for a in range(x.ndim) if a != channel_axis)
        num_features = self.num_features or x.shape[channel_axis]
        stat_shape = tuple(num_features if a == channel_axis else 1
                           for a in range(x.ndim))

        ra_mean = self.variable("batch_stats", "running_mean",
                                lambda: jnp.zeros((num_features,), jnp.float32))
        ra_var = self.variable("batch_stats", "running_var",
                               lambda: jnp.ones((num_features,), jnp.float32))

        if use_ra:
            # Eval: F.batch_norm fallback on running stats (reference
            # sync_batchnorm.py:85-88).
            mean, var = ra_mean.value, ra_var.value
        else:
            # During module init there is no bound mesh axis; stats stay
            # local (same convention as flax BatchNorm).
            axis = None if self.is_initializing() else self.axis_name
            mean, var, total_count = _global_moments(
                x, reduce_axes, axis, self.process_group)
            if self.track_running_stats and not self.is_initializing():
                # Unbiased var for running stats (reference :95-131).
                unbiased = var * total_count / jnp.maximum(total_count - 1, 1)
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)

        invstd = lax.rsqrt(var + self.eps)
        weight = bias = None
        if self.affine:
            weight = self.param("scale", self.scale_init,
                                (num_features,), jnp.float32)
            bias = self.param("bias", self.bias_init,
                              (num_features,), jnp.float32)
        if self.channel_last:
            # The whole elementwise tail — normalize, affine, the
            # optional ``z`` residual add (reference batch_norm_add_relu)
            # and the fused ReLU — is ONE conv-side epilogue: a Pallas
            # pass on TPU, the op-identical jnp reference elsewhere
            # (ISSUE 7).  Statistics (the psum above, running stats)
            # stay in XLA; the epilogue's custom VJP hands their
            # cotangents back exactly.
            from ..normalization.fused_bn_act import bn_relu_residual
            return bn_relu_residual(x, mean, invstd, weight, bias, z=z,
                                    relu=self.fuse_relu)
        out = (x.astype(jnp.float32)
               - mean.reshape(stat_shape)) * invstd.reshape(stat_shape)
        if self.affine:
            out = out * weight.reshape(stat_shape) + bias.reshape(stat_shape)
        if z is not None:
            # BN-add(-relu) fusion input (reference batch_norm_add_relu).
            out = out + z.astype(jnp.float32)
        if self.fuse_relu:
            out = jax.nn.relu(out)
        return out.astype(x.dtype)


def adopt_batchnorm_stats(batch_stats):
    """Rename plain flax ``BatchNorm`` running stats (``mean``/``var``)
    to :class:`SyncBatchNorm`'s reference names
    (``running_mean``/``running_var``), recursively, leaving everything
    else untouched.

    The standard init recipe uses plain ``BatchNorm`` (SyncBatchNorm's
    collectives need a bound mesh axis, absent at init) and swaps in the
    sync module for training.  Without the rename the first sync apply
    would CREATE its differently-named stats, growing the
    ``batch_stats`` pytree mid-training — a silent retrace on the
    jitted-per-step path and a hard error for scan-carried state
    (:class:`apex_tpu.runtime.StepPipeline` requires structure-stable
    carries).  Values are preserved (both modules init zeros/ones).
    """
    def _rename(d):
        if isinstance(d, dict):
            if set(d) == {"mean", "var"}:
                return {"running_mean": d["mean"],
                        "running_var": d["var"]}
            return {k: _rename(v) for k, v in d.items()}
        return d
    return _rename(batch_stats)
