"""Distributed data parallelism — the DDP contract over XLA collectives.

TPU-native re-design of reference ``apex/parallel/distributed.py:129-639``.

The reference DDP is a *scheduling layer* over NCCL: backward hooks, dtype
buckets built in backward-arrival order, flatten/allreduce/unflatten on side
CUDA streams.  Under SPMD compilation all of that machinery dissolves — XLA
schedules and overlaps collectives itself (SURVEY.md §5) — but the DDP
*contract* is preserved:

* params synced across replicas at wrap time          (``broadcast_params``)
* grads averaged across replicas by step time          (``reduce_gradients``)
* ``delay_allreduce`` / ``no_sync``-style accumulation (``no_sync``)
* ``gradient_average`` + ``gradient_predivide_factor`` (pre/post divide to
  protect reduced-precision dynamic range, reference ``:445-454``)
* ``allreduce_always_fp32``                            (reference ``:442-457``)
* sub-groups / round-robin communicators → ``axis_index_groups`` on the HLO
  all-reduce (reference process groups ``:604-624``)

Usage inside ``shard_map``/``pmap`` over a mesh axis::

    ddp = DistributedDataParallel(axis_name="data",
                                  allreduce_always_fp32=True)
    grads = ddp.reduce_gradients(grads)        # inside the mapped fn

or functionally: ``reduce_gradients(grads, axis_name="data", ...)``.
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def _note_collective(op: str, axis_names, tree_bytes: int, n: int,
                     dtype=None) -> None:
    """Report one collective's per-invocation traffic to the active
    telemetry recorder (ISSUE 5).  Runs at TRACE time — the byte counts
    are static aval properties — so the compiled program is unchanged
    and the event appears once per compile, not once per step.

    ``participants`` (ISSUE 10): the product of the collective's axis
    sizes, read at trace time, rides the event so the fleet merge can
    model each host's wire share (``prof.fleet``'s wait-vs-wire split)
    without re-deriving the mesh from the stream."""
    from .. import telemetry as _telemetry
    rec = _telemetry.get_recorder()
    if rec is not None and n:
        participants = 1
        try:
            names = (axis_names if isinstance(axis_names, (tuple, list))
                     else (axis_names,))
            for a in names:
                participants *= int(_axis_size(a))
        except Exception:
            participants = None
        rec.note_collective(op, axis_names, tree_bytes, n,
                            dtype=str(dtype) if dtype is not None else None,
                            participants=participants)


def _axis_size(axis_name) -> int:
    """``lax.axis_size`` with a fallback for jaxlibs that predate it:
    ``psum(1, axis)`` of a Python int constant-folds to the axis size at
    trace time (no collective is emitted)."""
    try:
        return lax.axis_size(axis_name)
    except AttributeError:
        return lax.psum(1, axis_name)


def import_shard_map():
    """Version-portable ``shard_map``: the top-level export on jax >= 0.6,
    else a compat wrapper over the experimental home that accepts (and
    drops) the new ``check_vma`` kwarg and pins ``check_rep=False`` —
    the legacy rep checker mis-infers scan-carry replication under
    K-step device loops; the vma tracking that replaced it copes."""
    try:                                # jax >= 0.6
        from jax import shard_map
        return shard_map
    except ImportError:                 # older jax: experimental home
        import functools

        from jax.experimental.shard_map import shard_map as _legacy

        def _compat(f=None, **kw):
            kw.pop("check_vma", None)
            kw["check_rep"] = False
            if f is None:               # decorator form: shard_map(mesh=...)
                return functools.partial(_compat, **kw)
            return _legacy(f, **kw)

        return _compat


def _is_float(x):
    dt = getattr(x, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


def vma_tracking_live(axis_name) -> bool:
    """Whether varying-manual-axes tracking is live on this trace.

    Under ``shard_map(check_vma=False)`` every aval reports an empty vma
    set, which must NOT be read as "already reduced"/"replicated" — there
    the implicit-broadcast transpose does not insert a psum either, so
    grads arrive per-shard.  ``axis_index`` is axis-varying by
    construction, so it probes tracking.  Shared by the gradient
    reduction here, the overflow agreement in ``training._por_varying``,
    and the ring-flash dispatch.
    """
    try:
        return axis_name in jax.typeof(lax.axis_index(axis_name)).vma
    except Exception:
        return False


def group_psum(x, axis_name: str, axis_index_groups=None):
    """``psum`` over ``axis_name``, optionally restricted to rank sub-groups.

    Sub-grouped all-reduce is the HLO ``replica_groups`` feature (reference
    process groups, SURVEY.md §5).  Lowering strategy, most to least
    scalable:

    1. native ``psum(axis_index_groups=...)`` where the trace allows it
       (pmap; shard_map raises NotImplementedError as of this jax version);
    2. butterfly (recursive-doubling) all-reduce over ``ppermute`` when all
       groups share a power-of-two size — O(|tensor|) memory, log2(k)
       collectives riding ICI, and a rank-invariant reduction tree (bitwise
       identical results on every member, like a real grouped all-reduce);
    3. fallback for irregular groups: ``all_gather`` + a static membership
       mask contraction (O(world x |tensor|) — fine on test meshes, not for
       pods; numerically fp32-accumulated).
    """
    if axis_index_groups is None:
        return lax.psum(x, axis_name)
    groups = [list(g) for g in axis_index_groups]
    try:
        return lax.psum(x, axis_name, axis_index_groups=groups)
    except NotImplementedError:
        pass
    sizes = {len(g) for g in groups}
    if len(sizes) == 1:
        k = sizes.pop()
        if k > 0 and (k & (k - 1)) == 0:
            return _group_psum_butterfly(x, axis_name, groups, k)
    return _group_psum_gather_mask(x, axis_name, groups)


def _group_psum_butterfly(x, axis_name: str, groups, k: int):
    """Grouped all-reduce as log2(k) XOR-partner exchange-and-add rounds.

    Every member of a group applies the SAME pairwise summation tree, so all
    members finish with bitwise-identical sums (commutativity of IEEE
    addition), matching the determinism contract of an HLO grouped
    all-reduce."""
    step = 1
    while step < k:
        perm = [(g[m ^ step], g[m]) for g in groups for m in range(k)]
        x = x + lax.ppermute(x, axis_name, perm)
        step <<= 1
    return x


def _group_psum_gather_mask(x, axis_name: str, groups):
    world = _axis_size(axis_name)
    import numpy as _np
    from ..amp._amp_state import maybe_print
    # O(world x |tensor|) on the wire — fine for a handful of hosts,
    # not for pods.  Warn ONCE per trace so an irregular BN group on a
    # large mesh doesn't silently take this path (VERDICT r3 weak #5);
    # tracing happens once per jit compile, so this is not a per-step
    # print.
    maybe_print(
        f"apex_tpu.parallel: grouped psum over irregular groups "
        f"{[len(g) for g in groups]} lowers to the masked-gather fallback "
        f"(all_gather of the full tensor across {world} ranks) — "
        f"equal power-of-two group sizes use the butterfly lowering "
        f"instead; not recommended on pods")
    member = _np.zeros((world, world), _np.float32)
    for g in groups:
        for i in g:
            for j in g:
                member[i, j] = 1.0
    idx = lax.axis_index(axis_name)
    gathered = lax.all_gather(x, axis_name)              # [world, ...]
    w = jnp.take(jnp.asarray(member), idx, axis=0)       # [world]
    out = jnp.tensordot(w, gathered.astype(jnp.float32), axes=1)
    return out.astype(jnp.asarray(x).dtype)


def reduce_gradients(grads,
                     axis_name: str,
                     *,
                     gradient_average: bool = True,
                     gradient_predivide_factor: float = 1.0,
                     allreduce_always_fp32: bool = False,
                     axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
                     world_size: Optional[int] = None,
                     bucket_store=None):
    """All-reduce a gradient pytree across ``axis_name`` replicas.

    Equivalent of ``allreduce_bucket`` (reference ``distributed.py:425-475``):
    optional fp32 upcast, predivide by ``gradient_predivide_factor`` before
    the reduce and postdivide by ``world/predivide`` after, so reduced-
    precision sums stay in range.

    ``axis_name`` may be a tuple of mesh axes (e.g. ``("data", "sp")``) —
    the DP contract then spans their product, as when a model is replicated
    over a 2-D data × sequence-parallel mesh.  ``axis_index_groups``
    requires a single axis.

    ``bucket_store`` (a :class:`~apex_tpu.multi_tensor.BucketStore` built
    from the grad tree) is the apex-DDP flat-bucket path: grads are packed
    into per-dtype flat buffers and the reduction is ONE ``psum`` per
    bucket — with ``allreduce_always_fp32`` casting at the bucket level —
    instead of one collective per leaf.  An already-``Packed`` ``grads``
    stays packed in the output.
    """
    axis_names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if len(axis_names) > 1 and axis_index_groups:
        raise ValueError("axis_index_groups requires a single axis name")
    full_world = 1
    for a in axis_names:
        full_world *= _axis_size(a)
    explicit_world = world_size is not None
    if world_size is None:
        world_size = full_world
        if axis_index_groups:
            world_size = len(axis_index_groups[0])

    _vma_tracking = vma_tracking_live(axis_names[0])

    def _already_reduced(g) -> bool:
        """shard_map autodiff inserts the psum itself when differentiating
        w.r.t. replicated params (the transpose of the implicit broadcast),
        so such grads arrive already *summed* over the axis.  They carry an
        empty varying-manual-axes (vma) set; axis-varying grads (per-shard
        values, e.g. under pmap-style code) still need the collective."""
        if not _vma_tracking:
            return False
        try:
            vma = jax.typeof(g).vma
        except AttributeError:
            return False
        return not any(a in vma for a in axis_names)

    def _axes_still_varying(g):
        """Mesh axes this grad still varies over (needs explicit psum);
        axes absent from the vma set were already summed by shard_map's
        implicit-broadcast transpose."""
        if not _vma_tracking:
            return axis_names
        try:
            vma = jax.typeof(g).vma
        except AttributeError:
            return axis_names
        return tuple(a for a in axis_names if a in vma)

    # Telemetry collector: per-leaf (or per-bucket) psum bytes summed at
    # trace time into ONE ``collective`` event per reduce_gradients call.
    coll = {"bytes": 0, "n": 0, "dtypes": set()}

    def one(g):
        if not _is_float(g):
            return g
        need = _axes_still_varying(g)
        if need:
            wire_dtype = (jnp.dtype(jnp.float32) if allreduce_always_fp32
                          else jnp.dtype(g.dtype))
            coll["bytes"] += ((math.prod(g.shape) if g.shape else 1)
                              * wire_dtype.itemsize)
            coll["n"] += 1
            coll["dtypes"].add(str(wire_dtype))
        if not need:
            # Fully pre-summed by the implicit psum — which spans the FULL
            # axes (subgroup structure is invisible to the transpose), so
            # average over the full product regardless of axis_index_groups —
            # unless the caller passed world_size, which always wins (same
            # contract as the explicit branch below).  With
            # gradient_average=False the explicit branch's predivide/
            # postmultiply cancel to a plain sum, which is what the implicit
            # psum already produced, so the raw sum is returned either way.
            if gradient_average:
                denom = world_size if explicit_world else full_world
                return (g / denom).astype(jnp.asarray(g).dtype)
            return g
        orig_dtype = jnp.asarray(g).dtype
        if allreduce_always_fp32:
            g = jnp.asarray(g, jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = group_psum(g, need if len(need) > 1 else need[0],
                       axis_index_groups)
        if gradient_average:
            # After implicit (axes not in `need`) + explicit sums the grad
            # is summed over the full product; with subgroups (single axis,
            # nothing implicit) it is summed over the group only.  An
            # explicitly passed world_size always wins (public contract).
            denom = (world_size if (axis_index_groups or explicit_world)
                     else full_world)
            postdiv = denom / gradient_predivide_factor
            if postdiv != 1.0:
                g = g / postdiv
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        if allreduce_always_fp32:
            g = g.astype(orig_dtype)
        return g

    from ..multi_tensor.buckets import Packed

    def _wire_dtype():
        # One dtype crossed the wire, or an honest "mixed" label — a
        # last-leaf-wins dtype would misattribute the summed bytes.
        if len(coll["dtypes"]) == 1:
            return next(iter(coll["dtypes"]))
        return "mixed" if coll["dtypes"] else None

    if bucket_store is not None or isinstance(grads, Packed):
        packed = (grads if isinstance(grads, Packed)
                  else bucket_store.pack(grads))
        # Collective/compute overlap (ISSUE 7): issue the per-bucket
        # psums in REVERSE-TOPOLOGICAL bucket order — each bucket's
        # collective is emitted as soon as its grads are final (its
        # pack depends only on its own leaves, so with a chunked store
        # — BucketStore(max_bucket_elems=...) — the deepest layers'
        # psum starts while earlier layers are still differentiating;
        # XLA's latency-hiding scheduler turns the issue order + closed
        # data deps into async start/done pairs riding the wire under
        # the remaining backward).  One monolithic bucket degenerates
        # to the old end-of-backward barrier.
        order = (bucket_store.reverse_topological_order()
                 if bucket_store is not None
                 else tuple(range(len(packed.data))))
        data = list(packed.data)
        for bi in order:
            data[bi] = one(data[bi])
        out = Packed(data=tuple(data), rest=packed.rest)
        _note_collective("psum", axis_names, coll["bytes"], coll["n"],
                         dtype=_wire_dtype())
        if isinstance(grads, Packed):
            return out
        return bucket_store.unpack(out)
    out = jax.tree_util.tree_map(one, grads)
    _note_collective("psum", axis_names, coll["bytes"], coll["n"],
                     dtype=_wire_dtype())
    return out


def broadcast_params(params, axis_name: str,
                     root: int = 0,
                     axis_index_groups=None):
    """Make every replica's params equal to ``root``'s (reference ctor
    broadcast, ``distributed.py:253``).  Implemented as mask+psum — the XLA
    idiom for broadcast-from-rank."""
    idx = lax.axis_index(axis_name)
    mask = (idx == root).astype(jnp.float32)

    def one(p):
        if not _is_float(p):
            return p
        contrib = jnp.asarray(p, jnp.float32) * mask
        return group_psum(contrib, axis_name, axis_index_groups).astype(
            jnp.asarray(p).dtype)

    return jax.tree_util.tree_map(one, params)


class DistributedDataParallel:
    """Object form carrying the DDP options (reference ctor flags).

    ``message_size``, ``num_allreduce_streams`` and ``delay_allreduce`` are
    accepted for API parity; on TPU message bucketing and stream scheduling
    are XLA's responsibility, so they only affect bookkeeping (``delay_
    allreduce`` is honored: reduction happens in ``reduce_gradients`` which
    the caller invokes at the end of backward either way — there are no
    per-param hooks to delay).
    """

    def __init__(self,
                 module: Optional[Callable] = None,
                 axis_name: str = "data",
                 message_size: int = 10000000,
                 delay_allreduce: bool = False,
                 shared_param=None,
                 allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_index_groups=None,
                 prof: bool = False,
                 bucket_store=None):
        if shared_param is not None:
            raise ValueError("shared_param is deprecated (reference parity: "
                             "distributed.py:149-151); use delay_allreduce.")
        self.module = module
        self.axis_name = axis_name
        self.delay_allreduce = delay_allreduce
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.axis_index_groups = axis_index_groups
        self.retain_allreduce_buffers = retain_allreduce_buffers
        self.prof = prof
        self.bucket_store = bucket_store
        self._disable_allreduce = False

    # Forward passes through to the wrapped module (reference module wrapper).
    def __call__(self, *args, **kwargs):
        if self.module is None:
            raise ValueError("DistributedDataParallel wraps no module")
        return self.module(*args, **kwargs)

    def sync_params(self, params, root: int = 0):
        return broadcast_params(params, self.axis_name, root,
                                self.axis_index_groups)

    def reduce_gradients(self, grads):
        if self._disable_allreduce:
            return grads
        scope = jax.named_scope("apex_tpu.ddp.allreduce")  # prof marker
        with scope:
            return reduce_gradients(
                grads, self.axis_name,
                gradient_average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                allreduce_always_fp32=self.allreduce_always_fp32,
                axis_index_groups=self.axis_index_groups,
                bucket_store=self.bucket_store)

    @contextlib.contextmanager
    def no_sync(self):
        """Disable grad reduction inside the context (reference
        ``disable_allreduce`` flag, ``distributed.py:275-279``) — the grad
        accumulation idiom.  Trace-time switch, like the reference's Python
        flag."""
        saved = self._disable_allreduce
        self._disable_allreduce = True
        try:
            yield
        finally:
            self._disable_allreduce = saved

    def wrap_grad_fn(self, grad_fn: Callable) -> Callable:
        """Return a grad_fn whose output grads are reduced — the "hook"
        equivalent for functional code."""
        def wrapped(*args, **kwargs):
            out = grad_fn(*args, **kwargs)
            if isinstance(out, tuple) and len(out) == 2:
                value, grads = out
                return value, self.reduce_gradients(grads)
            return self.reduce_gradients(out)
        return wrapped


class Reducer:
    """Manually-triggered allreduce of a param/grad tree (reference
    ``Reducer``, ``distributed.py:89-126``)."""

    def __init__(self, module_or_grads_list=None, axis_name: str = "data",
                 axis_index_groups=None):
        self.axis_name = axis_name
        self.axis_index_groups = axis_index_groups
        self.target = module_or_grads_list

    def reduce(self, tree=None, average: bool = True):
        tree = tree if tree is not None else self.target
        return reduce_gradients(tree, self.axis_name,
                                gradient_average=average,
                                axis_index_groups=self.axis_index_groups)
