"""Fully-jitted amp training steps — the TPU-idiomatic path.

The reference's training iteration is an imperative choreography of hooks and
patched methods (SURVEY.md §3.2).  On TPU the whole iteration — input cast,
bf16 forward, backward, gradient all-reduce, unscale + overflow flag, the
loss-scale state machine, and the skip-masked optimizer update — compiles
into ONE XLA program.  ``make_train_step`` builds that program from the same
opt-level semantics as ``amp.initialize``:

* O0: fp32 end to end.
* O1: autocast policy active inside the traced loss (enable via
  ``amp.init()``); params fp32.
* O2: params stored ONCE as fp32 masters; the bf16 model copy exists only
  *inside* the step (cast at trace time, keep-norm-fp32 honored) — this is
  the master-weights design with zero duplicate storage, the TPU-first
  answer to ``_process_optimizer``'s master machinery.
* O3: params stored bf16, no masters.
* O4: EXACTLY O2's storage/scaling semantics; the int8 matmul routing is
  a property of the MODEL (the ``quant=`` hook of ``apex_tpu.models`` +
  ``apex_tpu.quant``, ISSUE 13) — a model without frozen calibration
  runs bitwise as O2.

Step skipping is a device-side select (``apply_mask``), so dynamic loss
scaling costs no host sync at all (the reference pays one D2H per step,
``scaler.py:199-200``).

Usage::

    tx = apex_tpu.training.adam(lr=1e-3)
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level="O2",
                                       axis_name="data")
    state = init_fn(params)
    state, metrics = jax.jit(step_fn)(state, batch)       # single chip
    # or shard_map(step_fn, mesh, ...) for DP over a mesh axis
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .amp import policy as _policy
from .amp.loss_scaler import LossScaler, LossScalerState
from .amp.properties import opt_levels
from .optimizers import functional as F
from .parallel.distributed import reduce_gradients


def _pmean_varying(x, axis_name):
    """pmean over only the axes ``x`` actually varies on (pmean over an
    invarying axis is rejected by shard_map's vma checking — and would be
    the identity anyway)."""
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    try:
        vma = jax.typeof(x).vma
        names = tuple(a for a in names if a in vma)
    except AttributeError:
        pass
    if names:
        return jax.lax.pmean(x, names)
    return x


def _por_varying(flag, axis_name):
    """Logical OR of a bool scalar over the mesh axes it varies on.  With
    tensor-parallel (sharded) gradients each shard sees only its slice, so
    the overflow flag must be agreed mesh-wide or the scaler state — and
    then the parameters — would diverge across ranks.

    Under shard_map the flag's vma names EVERY axis it varies on — e.g.
    "tp" even when the caller only reduces grads over ("data",) — so the
    vma, when available, wins over ``axis_name``.  Without vma the
    ``axis_name`` list is used as-is: psum of an already-replicated flag
    over an extra axis is ``n * flag``, and the ``> 0`` turns either form
    into the OR.
    """
    from .parallel.distributed import vma_tracking_live

    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    # Trust an empty vma only when vma tracking is actually live on this
    # trace: under shard_map(check_vma=False) every aval reports an empty
    # vma, which must NOT be read as "already replicated".
    if names and vma_tracking_live(names[0]):
        names = tuple(jax.typeof(flag).vma)
    if names:
        return jax.lax.psum(flag.astype(jnp.int32), names) > 0
    return flag


class FunctionalOptimizer(NamedTuple):
    init: Callable
    update: Callable      # (grads, state, params, lr, grad_scale, apply_mask)
    # Declared capability, not inferred: True iff ``update`` treats every
    # parameter element independently (no per-tensor norms / trust ratios),
    # so it remains correct on arbitrary flat chunks of the parameter
    # vector.  ``parallel.zero.zero1`` requires it; third-party optimizers
    # must opt in explicitly — the conservative default keeps unknown
    # optimizers out of chunk-sharded paths.
    elementwise: bool = False


def _bucketed_tx(init_fn, update_fn, *, elementwise) -> FunctionalOptimizer:
    """FunctionalOptimizer over the flat-bucket engine (ISSUE 4): the
    BucketStore is built lazily from the first ``init(params)`` call (a
    static shape/dtype read — safe under jit tracing), and the optimizer
    state lives as a few large ``Packed`` buffers, so a ``lax.scan``
    carry (``runtime.StepPipeline`` K-step device loops) holds O(buckets)
    moment arrays instead of two per parameter leaf."""
    cell = {}

    def _store(params):
        from .multi_tensor.buckets import cached_store
        return cached_store(cell, params)

    def init(params):
        return init_fn(params, store=_store(params))

    def update(grads, state, params, **kw):
        return update_fn(grads, state, params, store=_store(params), **kw)

    return FunctionalOptimizer(init, update, elementwise=elementwise)


def adam(lr=1e-3, *, bucketed=False, **kw) -> FunctionalOptimizer:
    if bucketed:
        return _bucketed_tx(F.adam_init,
                            functools.partial(F.adam_update, lr=lr, **kw),
                            elementwise=True)
    return FunctionalOptimizer(
        F.adam_init, functools.partial(F.adam_update, lr=lr, **kw),
        elementwise=True)


def sgd(lr=1e-3, momentum=0.0, *, bucketed=False, **kw) -> FunctionalOptimizer:
    if bucketed:
        return _bucketed_tx(
            functools.partial(F.sgd_init, momentum=momentum),
            functools.partial(F.sgd_update, lr=lr, momentum=momentum, **kw),
            elementwise=True)
    return FunctionalOptimizer(
        functools.partial(F.sgd_init, momentum=momentum),
        functools.partial(F.sgd_update, lr=lr, momentum=momentum, **kw),
        elementwise=True)


def lamb(lr=1e-3, *, bucketed=False, **kw) -> FunctionalOptimizer:
    if bucketed:
        return _bucketed_tx(F.lamb_init,
                            functools.partial(F.lamb_update, lr=lr, **kw),
                            elementwise=False)
    return FunctionalOptimizer(
        F.lamb_init, functools.partial(F.lamb_update, lr=lr, **kw))


def novograd(lr=1e-3, *, bucketed=False, **kw) -> FunctionalOptimizer:
    if bucketed:
        return _bucketed_tx(
            F.novograd_init,
            functools.partial(F.novograd_update, lr=lr, **kw),
            elementwise=False)
    return FunctionalOptimizer(
        F.novograd_init, functools.partial(F.novograd_update, lr=lr, **kw))


class TrainState(NamedTuple):
    """Carry of the jitted step.  ``params`` is the single source of truth:
    fp32 for O0/O1/O2/O4 (O2/O4 cast inside the step), bf16 for O3."""
    params: Any
    opt_state: Any
    scaler: LossScalerState
    model_state: Any      # batch_stats etc; None if unused


def chain_steps(step_fn: Callable) -> Callable:
    """Device loop: K train steps as ONE compiled program.

    ``chain_steps(step_fn)(state, batches)`` runs ``lax.scan`` of the step
    over ``batches`` (every leaf stacked on a leading K axis — a
    pre-staged pool, like a prefetching input pipeline's lookahead) and
    returns ``(state, metrics)`` with per-step metrics stacked.

    This is the standard TPU training-loop shape: host dispatch costs are
    paid once per PROGRAM, not per step, so chaining K steps amortizes
    them by K.  Measured on the tunneled v5e, one jitted call costs ~7 ms
    fixed plus ~22 us per argument (a ResNet-50 TrainState is ~430
    leaves) — ~9 ms of pure dispatch on a 47 ms device step; at K=8 that
    overhead drops to ~1 ms/step.  On a real pod the constants are far
    smaller but the shape is the same (cf. steps_per_execution in other
    TPU frameworks).  The jitted-per-step path stays the right choice
    when the host must see metrics every step (e.g. imperative loops).

    Donate BOTH the carried state and the consumed window: the stacked
    batch buffer is K full batches of HBM (2.4 GB at K=32, b128, 224px)
    and without donation it stays pinned for the whole call — donating
    it lets XLA release/reuse that memory while the loop still runs, so
    the next staged window's H2D never doubles peak footprint.  A
    donated window is consumed: build a FRESH stack per call (a reused
    pool must not donate).  :class:`apex_tpu.runtime.StepPipeline` wraps
    this pattern — windows staged through the prefetcher, ragged tails,
    deferred metric reads — for the user-facing training path.

    Usage::

        chained = jax.jit(chain_steps(step_fn), donate_argnums=(0, 1))
        batches = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *pool)            # pool -> [K, ...]
        state, metrics = chained(state, batches)         # K real steps
    """
    def chained(state, batches):
        return jax.lax.scan(step_fn, state, batches)
    return chained


def make_train_step(loss_fn: Callable,
                    optimizer: FunctionalOptimizer,
                    *,
                    opt_level: str = "O2",
                    loss_scale=None,
                    keep_batchnorm_fp32: Optional[bool] = None,
                    cast_model_type=None,
                    axis_name: Optional[str] = None,
                    reduce_grads: bool = True,
                    accum_steps: int = 1,
                    gradient_average: bool = True,
                    gradient_predivide_factor: float = 1.0,
                    allreduce_always_fp32: bool = False,
                    axis_index_groups=None,
                    norm_predicate=None,
                    has_model_state: bool = False,
                    scale_window: int = 2000,
                    min_loss_scale=None,
                    max_loss_scale: float = 2.**24,
                    param_view: Optional[Callable] = None):
    """Build ``(init_fn, step_fn)`` for one amp training step.

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)`` when
    ``has_model_state`` else ``loss_fn(params, batch) -> loss``.  Inside the
    step, ``params`` arrive already cast to the compute dtype per opt level.

    ``reduce_grads=False`` keeps ``axis_name`` driving the mesh-wide
    overflow agreement and the metric pmean but skips the DDP gradient
    all-reduce — for optimizers that own the reduction themselves
    (``parallel.zero.zero1`` reduce-scatters inside ``update``).

    ``param_view`` maps the STORED parameter pytree to the tree
    ``loss_fn`` consumes, INSIDE the differentiated function — so its
    transpose runs in the backward and the optimizer sees gradients in
    the stored layout.  This is the ZeRO-3 hook
    (``apex_tpu.parallel.mesh``): the stored params are sharded flat
    buckets, the view all-gathers and unpacks them, and autodiff
    transposes the gather into exactly the reduce-scatter a ZeRO
    optimizer wants — per-bucket, so chunked stores overlap the
    collectives with the surrounding compute.  The opt-level compute
    cast applies AFTER the view (on the full tree, normal O2
    semantics).  Under ``accum_steps > 1`` the view is hoisted out of
    the microbatch scan alongside the cast — one gather per step, not
    per microbatch.  Default: identity.

    ``accum_steps=N`` is gradient accumulation compiled INTO the step —
    the jitted analog of the reference's ``delay_unscale`` micro-batch
    loop (``handle.py`` grad-accumulation contract): every array in
    ``batch`` is split into N microbatches along its leading axis, a
    ``lax.scan`` accumulates the mean of the scaled gradients (model
    state threads through sequentially, like N real steps), and the
    unscale / overflow check / reduction / update run ONCE on the
    accumulated gradients.  Peak activation memory drops by ~N; the
    result matches the full-batch step exactly for batch-size-invariant
    losses (mean-reduced, no cross-microbatch batch stats).
    """
    props = opt_levels[opt_level]()
    if loss_scale is not None:
        props.loss_scale = loss_scale
    if keep_batchnorm_fp32 is not None:
        props.keep_batchnorm_fp32 = keep_batchnorm_fp32
    if cast_model_type is not None:
        props.cast_model_type = cast_model_type

    scaler = LossScaler(props.loss_scale, scale_window=scale_window,
                        min_loss_scale=min_loss_scale,
                        max_loss_scale=max_loss_scale)
    dynamic = scaler.dynamic

    cast_dtype = props.cast_model_type
    cast_in_step = (cast_dtype is not None
                    and jnp.dtype(cast_dtype) != jnp.dtype(jnp.float32)
                    and props.master_weights)
    store_dtype_cast = (cast_dtype is not None
                        and jnp.dtype(cast_dtype) != jnp.dtype(jnp.float32)
                        and not props.master_weights)
    keep_bn = props.keep_batchnorm_fp32
    keep_bn = True if keep_bn is None else keep_bn

    view = param_view if param_view is not None else (lambda p: p)

    def cast_only(params):
        if cast_in_step:
            return _policy.convert_params(params, cast_dtype,
                                          keep_norm_fp32=keep_bn,
                                          norm_predicate=norm_predicate)
        return params

    def compute_cast(params):
        return cast_only(view(params))

    def init_fn(params, model_state=None):
        if store_dtype_cast:  # O3: store reduced precision, no masters
            params = _policy.convert_params(params, cast_dtype,
                                            keep_norm_fp32=keep_bn,
                                            norm_predicate=norm_predicate)
        return TrainState(params=params,
                          opt_state=optimizer.init(params),
                          scaler=scaler.init(),
                          model_state=model_state)

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def step_fn(state: TrainState, batch):
        def scaled_loss(p, ms, mb):
            cp = compute_cast(p)
            if has_model_state:
                loss, new_ms = loss_fn(cp, ms, mb)
            else:
                loss = loss_fn(cp, mb)
                new_ms = ms
            return (jnp.asarray(loss, jnp.float32)
                    * state.scaler.loss_scale), (loss, new_ms)

        if accum_steps == 1:
            grads, (loss, new_ms) = jax.grad(
                scaled_loss, has_aux=True)(state.params, state.model_state,
                                           batch)
        else:
            for leaf in jax.tree_util.tree_leaves(batch):
                if leaf.shape[0] % accum_steps:
                    raise ValueError(
                        f"batch leading dim {leaf.shape[0]} not divisible "
                        f"by accum_steps={accum_steps}")
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps,
                                    *x.shape[1:]), batch)

            # The O2/O3 compute cast is hoisted OUT of the scan (one
            # whole-tree cast per step, not per microbatch).  Its
            # transpose is an upcast, which is the identity on the fp32
            # accumulator — so the mean gradient w.r.t. the cast params
            # IS the master gradient.  The param_view is hoisted the
            # same way, but its transpose (the ZeRO-3 reduce-scatter)
            # is NOT the identity: jax.vjp stages it once so the
            # accumulated full-tree gradient is mapped back to the
            # stored layout after the scan — one gather and one scatter
            # per step, not per microbatch.
            if param_view is not None:
                full, view_vjp = jax.vjp(view, state.params)
            else:
                full, view_vjp = state.params, None
            cp = cast_only(full)

            def scaled_loss_cp(cp_, ms, mb):
                if has_model_state:
                    loss, new_ms = loss_fn(cp_, ms, mb)
                else:
                    loss = loss_fn(cp_, mb)
                    new_ms = ms
                return (jnp.asarray(loss, jnp.float32)
                        * state.scaler.loss_scale), (loss, new_ms)

            def one_micro(carry, mb):
                ms, g_acc, l_acc = carry
                g, (l, new_ms) = jax.grad(scaled_loss_cp, has_aux=True)(
                    cp, ms, mb)
                g_acc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(a.dtype) / accum_steps,
                    g_acc, g)
                return (new_ms, g_acc, l_acc + l / accum_steps), None

            g0 = jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), full)
            (new_ms, grads, loss), _ = jax.lax.scan(
                one_micro, (state.model_state, g0, jnp.float32(0.0)), micro)
            if view_vjp is not None:
                grads, = view_vjp(grads)

        if axis_name is not None and reduce_grads:
            grads = reduce_gradients(
                grads, axis_name,
                gradient_average=gradient_average,
                gradient_predivide_factor=gradient_predivide_factor,
                allreduce_always_fp32=allreduce_always_fp32,
                axis_index_groups=axis_index_groups)

        grads, scaler_state = scaler.unscale(grads, state.scaler)
        if dynamic and axis_name is not None:
            # Sharded (e.g. tensor-parallel) grads: agree on overflow
            # mesh-wide so every rank skips (or steps) together.
            scaler_state = scaler_state._replace(
                overflow=_por_varying(scaler_state.overflow, axis_name))
        if dynamic:
            apply_mask = jnp.logical_not(scaler_state.overflow)
        else:
            apply_mask = None
        new_params, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params, apply_mask=apply_mask)
        scaler_state = scaler.update_scale(scaler_state)

        if axis_name is not None:
            # Replicated metric, like the reference examples' allreduced
            # loss prints (main_amp.py:356-394); batch stats (BN running
            # mean/var) averaged across replicas so the carried state stays
            # replicated — the reference leaves stats per-rank, which only
            # works because each rank owns its module copy; under SPMD a
            # replicated pytree is the contract.  Each value is averaged
            # only over axes it actually varies on.
            loss = _pmean_varying(loss, axis_name)
            if new_ms is not None:
                new_ms = jax.tree_util.tree_map(
                    lambda x: _pmean_varying(x, axis_name), new_ms)
        metrics = {"loss": loss,
                   "loss_scale": scaler_state.loss_scale,
                   "overflow": (jnp.logical_not(apply_mask)
                                if apply_mask is not None
                                else jnp.asarray(False))}
        return TrainState(params=new_params, opt_state=new_opt_state,
                          scaler=scaler_state, model_state=new_ms), metrics

    return init_fn, step_fn
