"""Zero-downtime weight hot-swap — a manifest watcher on
:class:`~apex_tpu.checkpoint.CheckpointManager` directories.

A serving fleet cannot drain to pick up a newly trained checkpoint: the
training job publishes ``step_*/`` directories (per-shard npz + JSON
manifests, committed by the manifest rename), and the serving side must
adopt each new one WITHOUT failing in-flight requests.  The watcher
splits that into the two halves with different costs:

* **staging** (slow, background): a poll thread watches the directory
  with :func:`~apex_tpu.checkpoint.latest_checkpoint` — which already
  skips mid-write ``.tmp`` debris, truncated shards, and
  missing-manifest-part checkpoints, so an in-flight training save is
  invisible until its manifests commit — and loads the newest VALID
  step against the serving template (``load_checkpoint_dir`` device-puts
  every leaf onto the template's committed shardings, so the staged
  tree is already resident where the decode executables expect it);
* **swap** (cheap, on the serving loop): :meth:`WeightWatcher.take`
  hands the staged tree over between decode steps — one Python
  reference assignment, zero dispatch cost, so the swap window is the
  gap between two decode dispatches and no request ever observes a
  half-updated tree.

Validation is the checkpoint engine's own: a corrupt or in-flight
checkpoint is never adopted, and a newer-but-invalid step falls back to
the previous valid one (tested against the test_checkpoint debris
fixtures — ISSUE 11 satellite).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Tuple

from .. import telemetry as _telemetry
from ..checkpoint import latest_checkpoint, load_checkpoint_dir

__all__ = ["WeightWatcher"]


class WeightWatcher:
    """Watch a checkpoint directory and stage new weights for hot-swap.

    ``like`` is the params-template pytree (shapes/dtypes/shardings the
    serving engine runs with); ``extract`` maps a
    :class:`~apex_tpu.checkpoint.Restored` to the params tree when the
    checkpoint stores more than bare params (e.g. a training
    ``TrainState`` — pass ``lambda r: r.state.params``).  Default:
    ``r.state`` (the checkpoint IS the params tree).

    Use :meth:`poll_once` for synchronous control (tests, the engine's
    own cadence) or :meth:`start` for the background poll thread; either
    way :meth:`take` returns a freshly staged ``(step, params)`` at most
    once per adopted checkpoint.  Load failures of an individual
    checkpoint are recorded (``last_error``) and retried on the next
    poll — a torn checkpoint must never take the serving loop down.
    """

    def __init__(self, directory: str, like, *,
                 extract: Optional[Callable] = None,
                 poll_every_s: float = 1.0,
                 initial_step: Optional[int] = None, telemetry=None):
        self.directory = directory
        self._like = like
        self._extract = extract or (lambda restored: restored.state)
        self.poll_every_s = float(poll_every_s)
        self._telemetry = telemetry
        self._lock = threading.Lock()
        self._staged: Optional[Tuple[int, Any]] = None
        #: step of the newest checkpoint staged or taken so far.  A
        #: deployment that LOADED its starting weights from this same
        #: directory passes ``initial_step=restored.step`` so the
        #: watcher doesn't spuriously re-stage them as a "new" swap.
        self.adopted_step: Optional[int] = initial_step
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _rec(self):
        return (self._telemetry if self._telemetry is not None
                else _telemetry.get_recorder())

    # -- staging ------------------------------------------------------------
    def poll_once(self) -> bool:
        """Check the directory once; stage the newest VALID checkpoint
        when it is newer than anything adopted so far.  Returns True
        when something fresh was staged."""
        import os
        import re
        found = latest_checkpoint(self.directory)
        if found is None:
            return False
        m = re.match(r"^step_(\d+)$", os.path.basename(found))
        step = int(m.group(1)) if m else -1
        if self.adopted_step is not None and step <= self.adopted_step:
            return False
        t0 = time.perf_counter()
        try:
            restored = load_checkpoint_dir(found, self._like)
            params = self._extract(restored)
        except Exception as e:          # stage failures retry next poll
            self.last_error = f"{type(e).__name__}: {e}"
            rec = self._rec()
            if rec is not None:
                rec.event("serving", phase="stage_error", step=step,
                          error=self.last_error)
            return False
        with self._lock:
            self._staged = (step, params)
            self.adopted_step = step
        rec = self._rec()
        if rec is not None:
            rec.event("serving", phase="stage", step=step,
                      dur=round(time.perf_counter() - t0, 6))
        return True

    def take(self) -> Optional[Tuple[int, Any]]:
        """The staged ``(step, params)``, at most once per staged
        checkpoint — the serving loop's swap point."""
        with self._lock:
            staged, self._staged = self._staged, None
        return staged

    # -- background poll thread ---------------------------------------------
    def start(self) -> "WeightWatcher":
        """Start the background poll thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="apex-tpu-weight-watcher")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:     # pragma: no cover - defensive
                self.last_error = f"{type(e).__name__}: {e}"
            self._stop.wait(self.poll_every_s)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "WeightWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
