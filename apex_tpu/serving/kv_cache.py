"""Block-paged KV cache — the serving engine's memory substrate.

Training owns one contiguous activation workspace per step; serving owns
a POOL: sequences of wildly different lengths arrive and finish at
arbitrary times, and a per-sequence ``[max_len]`` dense cache would
strand most of its HBM in padding (a 2k-token model serving 50-token
chats wastes 97%).  The standard answer (vLLM's PagedAttention) is to
page the cache: a global pool of fixed-size token pages, per-sequence
page tables, allocation at page granularity — admission never fragments
and occupancy tracks REAL tokens, not padding.

This module is that substrate, shaped for the XLA/TPU constraints of
this codebase:

* the **pool** is two device arrays ``[n_layers, n_pages, page_size,
  n_kv_heads, head_dim]`` (k and v), donated through every serving step
  so updates reuse the same HBM;
* the **page table** is host state (:class:`PageAllocator`): a free
  list plus per-sequence page lists.  Page id 0 is RESERVED as the
  trash page — dead batch slots and the padded tail of short sequences
  point there, so a masked lane can never corrupt a live page;
* :func:`gather_views` / :func:`scatter_prefill` /
  :func:`scatter_token` are the pure jit-safe bridges between the pool
  and the dense ``[S, bucket, n_kv, head_dim]`` views
  ``apex_tpu.models.gpt``'s incremental forward consumes.  The gather
  reads each attended page exactly once — the same bytes attention
  itself must stream, so paging adds page-table indexing, not a second
  pass over HBM.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PageAllocator", "QuantPool", "make_pool", "gather_views",
           "scatter_prefill", "scatter_token", "kv_bytes_per_token",
           "pages_for_budget", "storage_dtype"]

#: page id 0 is the trash page: dead slots and table padding point at it.
TRASH_PAGE = 0


class QuantPool:
    """One int8 half of the KV pool (ISSUE 13, serving layer).

    Decode is bandwidth-bound and the KV cache IS the bandwidth: int8
    storage halves the bytes attention streams per token AND halves the
    HBM a page pins, so the same pool budget admits ~2x the concurrent
    sequences.  The numerics recipe is per-(token, head) symmetric
    absmax — ``data`` int8 ``[n_layers, n_pages, page, n_kv, head_dim]``
    plus ``scale`` fp32 ``[n_layers, n_pages, page, n_kv]`` (one scale
    per cached row: 4 bytes against ``head_dim`` saved, and the finest
    granularity the page layout stores for free).  Quantization happens
    INSIDE :func:`scatter_prefill` / :func:`scatter_token`;
    :func:`gather_views` dequantizes into the ``out_dtype`` dense views
    the incremental forward consumes — callers never see int8.

    Registered as a pytree (children ``data``/``scale``), so the pool
    donates through every serving dispatch exactly like the plain
    arrays it replaces."""

    def __init__(self, data, scale, out_dtype):
        self.data = data
        self.scale = scale
        self.out_dtype = jnp.dtype(out_dtype)

    @property
    def shape(self):
        """The logical (dense-view) pool shape — the plain pool's."""
        return self.data.shape

    @property
    def dtype(self):
        """The DENSE VIEW dtype (what gather_views hands the model);
        the storage dtype is ``data.dtype`` (int8)."""
        return self.out_dtype


jax.tree_util.register_pytree_node(
    QuantPool,
    lambda p: ((p.data, p.scale), str(p.out_dtype)),
    lambda aux, ch: QuantPool(ch[0], ch[1], aux))


def storage_dtype(pool) -> str:
    """The dtype a pool half actually stores (``"int8"`` for a
    :class:`QuantPool`) — the ``kv_cache_dtype`` run-info label."""
    if isinstance(pool, QuantPool):
        return str(pool.data.dtype)
    return str(jnp.dtype(pool.dtype))


def _model_kv_dims(model) -> Tuple[int, int, int]:
    n_kv = model.num_kv_heads or model.num_heads
    return model.num_layers, n_kv, model.hidden_size // model.num_heads


def kv_bytes_per_token(model, dtype=None) -> int:
    """HBM bytes ONE cached token costs across all layers (k + v,
    scales included for int8) — the ``kv_bytes_per_token`` serving
    stat."""
    n_layers, n_kv, head_dim = _model_kv_dims(model)
    dt = jnp.dtype(model.dtype if dtype is None else dtype)
    if dt == jnp.dtype(jnp.int8):
        per_head = head_dim * 1 + 4          # int8 row + one fp32 scale
    else:
        per_head = head_dim * dt.itemsize
    return 2 * n_layers * n_kv * per_head


def pages_for_budget(model, page_size: int, budget_bytes: int,
                     dtype=None) -> int:
    """How many KV pages fit a byte budget at ``dtype`` storage — the
    equal-HBM capacity comparison of the bench gate (int8 admits
    >= 1.5x the pages bf16 does at the same budget)."""
    per_page = kv_bytes_per_token(model, dtype) * int(page_size)
    return int(budget_bytes) // per_page if per_page else 0


def make_pool(model, n_pages: int, page_size: int, dtype=None):
    """Zeroed ``(pool_k, pool_v)`` for a
    :class:`~apex_tpu.models.gpt.GPT` config: plain device arrays
    ``[n_layers, n_pages, page_size, n_kv_heads, head_dim]``, or
    :class:`QuantPool` halves when ``dtype`` is ``jnp.int8`` (int8
    storage + per-row scales; dense views dequantize to the model's
    compute dtype).  GQA models pool only the kv heads (the
    cache-bandwidth saving is real at decode, which is
    bandwidth-bound)."""
    n_layers, n_kv, head_dim = _model_kv_dims(model)
    dt = model.dtype if dtype is None else dtype
    shape = (n_layers, n_pages, page_size, n_kv, head_dim)
    if jnp.dtype(dt) == jnp.dtype(jnp.int8):
        def half():
            return QuantPool(jnp.zeros(shape, jnp.int8),
                             jnp.ones(shape[:-1], jnp.float32),
                             model.dtype)
        return half(), half()
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def _quant_rows(x):
    """Symmetric int8 per-row quantization over the trailing head_dim
    axis: ``(q int8, scale f32[...])`` — same rounding/zero-amax rules
    as :mod:`apex_tpu.quant.kernels` (shared helpers)."""
    from ..quant.kernels import amax_to_scale, quantize
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax_to_scale(amax)
    return quantize(x, scale[..., None]), scale


def gather_views(pool_k, pool_v, tables):
    """Dense per-layer cache views from the page pool.

    ``tables``: ``[S, n_pages_b]`` int32 page ids (a bucket-width slice
    of the host page table).  Returns a list of per-layer ``(k, v)``
    pairs, each ``[S, n_pages_b * page_size, n_kv, head_dim]`` — exactly
    the ``kv_caches`` shape the GPT incremental forward takes.  An int8
    pool dequantizes here, INSIDE the jitted step feeding the
    suffix-aligned flash-attention decode path — the gather reads the
    (halved) int8 bytes once and the fp path never touches HBM."""
    n_layers, _, page_size, n_kv, head_dim = pool_k.shape
    s, n_pages_b = tables.shape

    def dense(pool):
        if isinstance(pool, QuantPool):
            g = pool.data[:, tables]     # [L, S, nb, page, n_kv, hd] i8
            sc = pool.scale[:, tables]   # [L, S, nb, page, n_kv]
            d = (g.astype(jnp.float32) * sc[..., None]).astype(
                pool.out_dtype)
        else:
            d = pool[:, tables]          # [L, S, n_pages_b, page, ...]
        return d.reshape(n_layers, s, n_pages_b * page_size, n_kv,
                         head_dim)

    kd, vd = dense(pool_k), dense(pool_v)
    return [(kd[i], vd[i]) for i in range(n_layers)]


def scatter_prefill(pool, pages, dense):
    """Write one sequence's prefilled cache back into its pages.

    ``pages``: ``[n_pages_b]`` int32; ``dense``: ``[n_layers, bucket,
    n_kv, head_dim]`` (the batch-1 view the prefill forward produced).
    Page-granular scatter: one ``.at[].set`` over the page axis.  An
    int8 pool quantizes per (token, head) on the way in."""
    n_layers, _, page_size, n_kv, head_dim = pool.shape
    n_pages_b = pages.shape[0]
    if isinstance(pool, QuantPool):
        q, sc = _quant_rows(dense)
        return QuantPool(
            pool.data.at[:, pages].set(
                q.reshape(n_layers, n_pages_b, page_size, n_kv,
                          head_dim)),
            pool.scale.at[:, pages].set(
                sc.reshape(n_layers, n_pages_b, page_size, n_kv)),
            pool.out_dtype)
    paged = dense.reshape(n_layers, n_pages_b, page_size, n_kv,
                          head_dim)
    return pool.at[:, pages].set(paged.astype(pool.dtype))


def scatter_token(pool, page_ids, offsets, tok):
    """Write one fresh token's k or v per batch slot.

    ``page_ids``/``offsets``: ``[S]`` int32 (page and in-page offset of
    each slot's current position — dead slots point at the trash page);
    ``tok``: ``[n_layers, S, n_kv, head_dim]``.  An int8 pool
    quantizes per (token, head) on the way in."""
    if isinstance(pool, QuantPool):
        q, sc = _quant_rows(tok)
        return QuantPool(
            pool.data.at[:, page_ids, offsets].set(q),
            pool.scale.at[:, page_ids, offsets].set(sc),
            pool.out_dtype)
    return pool.at[:, page_ids, offsets].set(tok.astype(pool.dtype))


class PageAllocator:
    """Host-side page accounting: a free list over ``n_pages - 1`` real
    pages (page 0 is the trash page and never allocated).  Thread-safe;
    :meth:`alloc` is all-or-nothing so a request can never be admitted
    half-resident."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (one is the trash page), "
                             f"got {n_pages}")
        self.n_pages = int(n_pages)
        self._free = list(range(n_pages - 1, TRASH_PAGE, -1))
        self._lock = threading.Lock()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def total_pages(self) -> int:
        """Allocatable pages (the trash page excluded)."""
        return self.n_pages - 1

    @property
    def occupancy_pct(self) -> float:
        """Percent of allocatable pages currently held by sequences —
        the ``serving_kv_page_occupancy_pct`` gauge."""
        total = self.total_pages
        return 100.0 * (total - len(self._free)) / total if total else 0.0

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None when fewer are free (all-or-nothing)."""
        with self._lock:
            if n > len(self._free):
                return None
            out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    raise ValueError("attempted to free the trash page")
                if p in self._free:
                    raise ValueError(f"double free of page {p}")
                self._free.append(p)

    def padded_row(self, pages: Sequence[int], width: int) -> np.ndarray:
        """One page-table row padded to ``width`` with the trash page.
        A sequence holding MORE pages than the view is truncated: a
        long-bucket sequence still early in its life decodes through a
        smaller bucket's table, whose view covers exactly the first
        ``width`` pages (its live positions all fit there)."""
        row = np.full((width,), TRASH_PAGE, np.int32)
        n = min(len(pages), width)
        row[:n] = np.asarray(pages[:n], np.int32)
        return row
