"""Block-paged KV cache — the serving engine's memory substrate.

Training owns one contiguous activation workspace per step; serving owns
a POOL: sequences of wildly different lengths arrive and finish at
arbitrary times, and a per-sequence ``[max_len]`` dense cache would
strand most of its HBM in padding (a 2k-token model serving 50-token
chats wastes 97%).  The standard answer (vLLM's PagedAttention) is to
page the cache: a global pool of fixed-size token pages, per-sequence
page tables, allocation at page granularity — admission never fragments
and occupancy tracks REAL tokens, not padding.

This module is that substrate, shaped for the XLA/TPU constraints of
this codebase:

* the **pool** is two device arrays ``[n_layers, n_pages, page_size,
  n_kv_heads, head_dim]`` (k and v), donated through every serving step
  so updates reuse the same HBM;
* the **page table** is host state (:class:`PageAllocator`): a free
  list plus per-sequence page lists.  Page id 0 is RESERVED as the
  trash page — dead batch slots and the padded tail of short sequences
  point there, so a masked lane can never corrupt a live page;
* :func:`gather_views` / :func:`scatter_prefill` /
  :func:`scatter_token` are the pure jit-safe bridges between the pool
  and the dense ``[S, bucket, n_kv, head_dim]`` views
  ``apex_tpu.models.gpt``'s incremental forward consumes.  The gather
  reads each attended page exactly once — the same bytes attention
  itself must stream, so paging adds page-table indexing, not a second
  pass over HBM.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["PageAllocator", "make_pool", "gather_views",
           "scatter_prefill", "scatter_token"]

#: page id 0 is the trash page: dead slots and table padding point at it.
TRASH_PAGE = 0


def make_pool(model, n_pages: int, page_size: int, dtype=None
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed ``(pool_k, pool_v)`` device arrays
    ``[n_layers, n_pages, page_size, n_kv_heads, head_dim]`` for a
    :class:`~apex_tpu.models.gpt.GPT` config.  GQA models pool only the
    kv heads (the cache-bandwidth saving is real at decode, which is
    bandwidth-bound)."""
    n_kv = model.num_kv_heads or model.num_heads
    head_dim = model.hidden_size // model.num_heads
    dt = model.dtype if dtype is None else dtype
    shape = (model.num_layers, n_pages, page_size, n_kv, head_dim)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def gather_views(pool_k, pool_v, tables):
    """Dense per-layer cache views from the page pool.

    ``tables``: ``[S, n_pages_b]`` int32 page ids (a bucket-width slice
    of the host page table).  Returns a list of per-layer ``(k, v)``
    pairs, each ``[S, n_pages_b * page_size, n_kv, head_dim]`` — exactly
    the ``kv_caches`` shape the GPT incremental forward takes."""
    n_layers, _, page_size, n_kv, head_dim = pool_k.shape
    s, n_pages_b = tables.shape

    def dense(pool):
        g = pool[:, tables]          # [L, S, n_pages_b, page, n_kv, hd]
        return g.reshape(n_layers, s, n_pages_b * page_size, n_kv,
                         head_dim)

    kd, vd = dense(pool_k), dense(pool_v)
    return [(kd[i], vd[i]) for i in range(n_layers)]


def scatter_prefill(pool, pages, dense):
    """Write one sequence's prefilled cache back into its pages.

    ``pages``: ``[n_pages_b]`` int32; ``dense``: ``[n_layers, bucket,
    n_kv, head_dim]`` (the batch-1 view the prefill forward produced).
    Page-granular scatter: one ``.at[].set`` over the page axis."""
    n_layers, _, page_size, n_kv, head_dim = pool.shape
    paged = dense.reshape(n_layers, pages.shape[0], page_size, n_kv,
                          head_dim)
    return pool.at[:, pages].set(paged.astype(pool.dtype))


def scatter_token(pool, page_ids, offsets, tok):
    """Write one fresh token's k or v per batch slot.

    ``page_ids``/``offsets``: ``[S]`` int32 (page and in-page offset of
    each slot's current position — dead slots point at the trash page);
    ``tok``: ``[n_layers, S, n_kv, head_dim]``."""
    return pool.at[:, page_ids, offsets].set(tok.astype(pool.dtype))


class PageAllocator:
    """Host-side page accounting: a free list over ``n_pages - 1`` real
    pages (page 0 is the trash page and never allocated).  Thread-safe;
    :meth:`alloc` is all-or-nothing so a request can never be admitted
    half-resident."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (one is the trash page), "
                             f"got {n_pages}")
        self.n_pages = int(n_pages)
        self._free = list(range(n_pages - 1, TRASH_PAGE, -1))
        self._lock = threading.Lock()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def total_pages(self) -> int:
        """Allocatable pages (the trash page excluded)."""
        return self.n_pages - 1

    @property
    def occupancy_pct(self) -> float:
        """Percent of allocatable pages currently held by sequences —
        the ``serving_kv_page_occupancy_pct`` gauge."""
        total = self.total_pages
        return 100.0 * (total - len(self._free)) / total if total else 0.0

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None when fewer are free (all-or-nothing)."""
        with self._lock:
            if n > len(self._free):
                return None
            out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    raise ValueError("attempted to free the trash page")
                if p in self._free:
                    raise ValueError(f"double free of page {p}")
                self._free.append(p)

    def padded_row(self, pages: Sequence[int], width: int) -> np.ndarray:
        """One page-table row padded to ``width`` with the trash page.
        A sequence holding MORE pages than the view is truncated: a
        long-bucket sequence still early in its life decodes through a
        smaller bucket's table, whose view covers exactly the first
        ``width`` pages (its live positions all fit there)."""
        row = np.full((width,), TRASH_PAGE, np.int32)
        n = min(len(pages), width)
        row[:n] = np.asarray(pages[:n], np.int32)
        return row
