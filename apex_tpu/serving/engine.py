"""AOT-bucketed inference engine: continuous batching over a paged KV
cache with zero steady-state compiles (ISSUE 11 tentpole).

The training stack built everything this engine needs — it just needs
them pointed at requests instead of batches:

* **zero compiles in steady state** — prefill and single-token decode
  are AOT-lowered per sequence-length bucket BEFORE the first request
  (``jax.jit(...).lower().compile()`` over abstract shapes, the
  :mod:`apex_tpu.cache` warmup machinery); dispatches go straight to the
  compiled executables, keyed by
  :func:`apex_tpu.cache.signature(..., static=(kind, bucket))`.  A
  bucket that was never warmed is a clean lookup MISS served by the jit
  path (one compile, identical numerics) and counted in
  ``stats["aot_misses"]`` — never a wrong-executable dispatch;
* **continuous batching** — a bounded request queue (the
  :class:`~apex_tpu.data.PrefetchLoader` back-pressure discipline:
  ``submit`` blocks when the queue is full) feeds a scheduler that
  admits requests into free KV pages at every step boundary, runs ONE
  batched decode dispatch for every active sequence regardless of how
  staggered their positions are (the per-sequence ``positions`` of the
  GPT incremental forward), and evicts finished sequences immediately —
  a finishing chat frees its pages for the next admission without
  waiting for its batch peers;
* **paged, donated KV cache** — :mod:`apex_tpu.serving.kv_cache`: the
  pool arrays are donated through every prefill/decode dispatch, so the
  cache never pays a copy across steps;
* **weight hot-swap** — a :class:`~apex_tpu.serving.hotswap.WeightWatcher`
  stages newly committed training checkpoints in the background and the
  scheduler swaps the params reference between decode steps: zero
  downtime, no failed requests, and every post-swap token comes from
  the new weights;
* **per-request observability** — queue-wait / prefill / per-token
  decode spans as ``serving`` telemetry events, and live
  ``serving_queue_depth`` / ``serving_active_seqs`` /
  ``serving_kv_page_occupancy_pct`` / ``serving_tokens_per_s`` gauges
  through the existing recorder into the Prometheus export; the
  ``serving_queue_stall`` watchdog rule folds the admit events.  With
  a tracer attached (``telemetry.start(trace_sample_n=N)``, ISSUE 20)
  every Nth request additionally emits a ``span`` tree
  (queue/prefill/per-step decode/hotswap under a ``request`` root),
  and every finished request records TTFT / TPOT / e2e into the
  ``serving_ttft_s`` / ``serving_tpot_s`` / ``serving_e2e_s``
  histograms and its ``done`` event — the inputs of the SLO engine
  (:mod:`apex_tpu.telemetry.slo`) and the offline request analyzer
  (``python -m apex_tpu.prof.requests``).

Decoding is greedy (``argmax``) — deliberately: bitwise-reproducible
outputs are what make the hot-swap acceptance gate (post-swap output ==
the new checkpoint's single-request output) and the continuous-batching
parity tests meaningful.  Sampling belongs to a later PR.

Usage::

    from apex_tpu import serving

    eng = serving.ServingEngine(model, params, buckets=(128, 256),
                                max_seqs=8, watch_dir=ckpt_dir)
    eng.warmup()                        # AOT: all buckets, before traffic
    results = eng.generate([prompt_a, prompt_b], max_new_tokens=64)
    eng.close()

or threaded: ``eng.start()`` + ``eng.submit(...).result()``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import cache as _cache
from .. import telemetry as _telemetry
from . import kv_cache as _kv
from .hotswap import WeightWatcher

__all__ = ["Request", "ServedResult", "Completion", "ServingEngine"]


class Request(NamedTuple):
    """One generation request: ``prompt`` int32 token ids ``[T]``,
    ``max_new_tokens`` the decode budget, ``stop_token`` an optional
    early-finish id (checked on sampled tokens)."""
    prompt: np.ndarray
    max_new_tokens: int
    stop_token: Optional[int] = None


class ServedResult(NamedTuple):
    """A finished request: generated ``tokens`` (prompt excluded),
    timing spans, and ``error`` (None on success — a rejection, e.g. a
    prompt that fits no bucket, reports here instead of raising on the
    serving thread)."""
    tokens: np.ndarray
    timings: dict
    bucket: Optional[int] = None
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class Completion:
    """Future-ish handle for a submitted request."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Optional[ServedResult] = None

    def _set(self, result: ServedResult) -> None:
        self._result = result
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ServedResult:
        if not self._done.wait(timeout):
            raise TimeoutError("request not finished")
        return self._result


class _Active(NamedTuple):
    """One admitted sequence (a batch slot).  ``trace``/``root`` are
    the request's trace id and root span id when it was sampled by the
    recorder's tracer (ISSUE 20), else None — the untraced hot path
    carries two Nones and emits nothing."""
    request: Request
    completion: Completion
    bucket: int
    pages: List[int]
    t_submit: float
    t_admit: float
    t_prefill_done: float
    trace: Optional[str] = None
    root: Optional[str] = None


class ServingEngine:
    """Continuous-batching engine for a
    :class:`~apex_tpu.models.gpt.GPT` model (see module docstring).

    ``buckets`` are the sequence-length capacities prefill AND decode
    specialize on (each must divide by ``page_size`` and fit
    ``model.max_len``); a request takes the smallest bucket holding
    ``len(prompt) + max_new_tokens``.  ``max_seqs`` is the decode batch
    width; ``n_pages`` sizes the pool (default: enough for ``max_seqs``
    sequences of the largest bucket, plus the trash page).

    ``watch_dir`` enables weight hot-swap: a
    :class:`~apex_tpu.serving.hotswap.WeightWatcher` on that checkpoint
    directory (``extract`` maps its :class:`~apex_tpu.checkpoint.Restored`
    to the params tree), polled by a background thread
    (``poll_every_s``) and swapped between steps."""

    def __init__(self, model, params, *,
                 buckets: Sequence[int] = (128, 256),
                 page_size: int = 16,
                 max_seqs: int = 4,
                 n_pages: Optional[int] = None,
                 max_queue: int = 64,
                 cache_dtype=None,
                 watch_dir: Optional[str] = None,
                 extract: Optional[Callable] = None,
                 poll_every_s: float = 1.0,
                 watch_from_step: Optional[int] = None,
                 telemetry=None):
        buckets = sorted(int(b) for b in buckets)
        if not buckets:
            raise ValueError("need at least one sequence-length bucket")
        for b in buckets:
            if b % page_size:
                raise ValueError(f"bucket {b} must divide by page_size "
                                 f"{page_size}")
            if b > model.max_len:
                raise ValueError(f"bucket {b} exceeds model.max_len "
                                 f"{model.max_len}")
        self.model = model
        self.params = params
        self.buckets = tuple(buckets)
        self.page_size = int(page_size)
        self.max_seqs = int(max_seqs)
        if n_pages is None:
            n_pages = 1 + self.max_seqs * (buckets[-1] // page_size)
        self.pool_k, self.pool_v = _kv.make_pool(
            model, n_pages, page_size, dtype=cache_dtype)
        #: what the pool actually stores ("int8" under the quantized KV
        #: cache, else the compute dtype) — the Prometheus run-info
        #: label and the capacity-planning stat ride on it (ISSUE 13).
        self.kv_cache_dtype = _kv.storage_dtype(self.pool_k)
        self.pages = _kv.PageAllocator(n_pages)
        self._slots: List[Optional[_Active]] = [None] * self.max_seqs
        # per-slot decode state (host): current write position, last
        # sampled token, generated tokens so far
        self._pos = np.zeros((self.max_seqs,), np.int32)
        self._tok = np.zeros((self.max_seqs,), np.int32)
        self._gen: List[List[int]] = [[] for _ in range(self.max_seqs)]
        # bounded request queue (PrefetchLoader-style back-pressure)
        self.max_queue = int(max_queue)
        self._queue: List[tuple] = []          # (Request, Completion, t)
        self._qlock = threading.Lock()
        self._qcond = threading.Condition(self._qlock)
        # jit callables + AOT executables, keyed per (kind, bucket)
        self._jit: dict = {}
        self._aot: dict = {}
        self.stats = {"submitted": 0, "completed": 0, "rejected": 0,
                      "aot_misses": 0, "hotswaps": 0, "tokens_out": 0,
                      "decode_steps": 0, "prefills": 0,
                      "kv_bytes_per_token": _kv.kv_bytes_per_token(
                          model, cache_dtype)}
        self._telemetry = telemetry
        self._t_rate = None                    # tokens/s gauge anchor
        #: idle horizon for the tokens/s gauge when no exporter is
        #: attached (with one, its ``every_s`` is the horizon): no
        #: decode dispatch within this window zeroes the rate gauge.
        self.rate_idle_s = 5.0
        self.watcher: Optional[WeightWatcher] = None
        if watch_dir is not None:
            # watch_from_step: the checkpoint step `params` came from
            # (when it came from this same directory), so the watcher
            # only stages checkpoints NEWER than what is already serving.
            self.watcher = WeightWatcher(
                watch_dir, like=params, extract=extract,
                poll_every_s=poll_every_s,
                initial_step=watch_from_step,
                telemetry=telemetry).start()
        self._serve_stop = threading.Event()
        self._serve_thread: Optional[threading.Thread] = None
        self._closed = False

    # -- telemetry ----------------------------------------------------------
    def _rec(self):
        return (self._telemetry if self._telemetry is not None
                else _telemetry.get_recorder())

    def _event(self, phase: str, **fields) -> None:
        rec = self._rec()
        if rec is not None:
            rec.event("serving", phase=phase, **fields)

    def _tracer(self):
        rec = self._rec()
        return getattr(rec, "tracer", None) if rec is not None else None

    def _gauges(self) -> None:
        rec = self._rec()
        if rec is None:
            return
        with self._qlock:
            depth = len(self._queue)
        rec.metrics.gauge("serving_queue_depth").set(depth)
        rec.metrics.gauge("serving_active_seqs").set(
            sum(1 for s in self._slots if s is not None))
        rec.metrics.gauge("serving_kv_page_occupancy_pct").set(
            self.pages.occupancy_pct)
        rec.metrics.gauge("serving_kv_bytes_per_token").set(
            self.stats["kv_bytes_per_token"])
        # tokens/s idle decay (ISSUE 20 satellite): the rate gauge is
        # computed from inter-dispatch gaps, so with no decode landing
        # it would keep exporting the LAST burst's rate forever — zero
        # it once nothing dispatched within the export interval and
        # drop the anchor, so the next burst's first sample doesn't
        # divide by the idle gap either.
        if self._t_rate is not None:
            exp = getattr(rec, "exporter", None)
            idle_s = (exp.every_s if exp is not None
                      else self.rate_idle_s)
            if time.perf_counter() - self._t_rate > idle_s:
                rec.metrics.gauge("serving_tokens_per_s").set(0.0)
                self._t_rate = None
        # dark counters (ISSUE 20 satellite): stats that only lived in
        # the exit dict become scrapeable monotonic counters — exported
        # by delta so the registry stays the single Prometheus source.
        for key in ("aot_misses", "rejected"):
            c = rec.metrics.counter(f"serving_{key}")
            delta = self.stats[key] - c.value
            if delta > 0:
                c.inc(delta)
        # run-info label, not a sample: capacity dashboards slice
        # tokens/sec and occupancy by the KV storage dtype (ISSUE 13)
        rec.run_info["kv_cache_dtype"] = self.kv_cache_dtype

    # -- bucketed step programs ---------------------------------------------
    def _bucket_for(self, total_len: int) -> Optional[int]:
        for b in self.buckets:
            if total_len <= b:
                return b
        return None

    def _prefill_jit(self, bucket: int):
        fn = self._jit.get(("prefill", bucket))
        if fn is None:
            model = self.model
            n_kv = model.num_kv_heads or model.num_heads
            head_dim = model.hidden_size // model.num_heads
            cdtype = self.pool_k.dtype

            def prefill(params, pool_k, pool_v, pages, tokens, length):
                # tokens [1, bucket]; pages [bucket/page]; length scalar
                zeros = [(jnp.zeros((1, bucket, n_kv, head_dim), cdtype),
                          jnp.zeros((1, bucket, n_kv, head_dim), cdtype))
                         for _ in range(model.num_layers)]
                logits, caches = model.apply(
                    {"params": params}, tokens, kv_caches=zeros,
                    positions=jnp.zeros((1,), jnp.int32))
                k_dense = jnp.stack([k[0] for k, _ in caches])
                v_dense = jnp.stack([v[0] for _, v in caches])
                pool_k = _kv.scatter_prefill(pool_k, pages, k_dense)
                pool_v = _kv.scatter_prefill(pool_v, pages, v_dense)
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], length - 1, axis=0, keepdims=False)
                nxt = jnp.argmax(last, -1).astype(jnp.int32)
                return pool_k, pool_v, nxt

            fn = jax.jit(prefill, donate_argnums=(1, 2))
            self._jit[("prefill", bucket)] = fn
        return fn

    def _decode_jit(self, bucket: int):
        fn = self._jit.get(("decode", bucket))
        if fn is None:
            model, page = self.model, self.page_size

            def decode(params, pool_k, pool_v, tables, positions, tokens):
                # tables [S, bucket/page]; positions/tokens [S]
                caches = _kv.gather_views(pool_k, pool_v, tables)
                logits, new = model.apply(
                    {"params": params}, tokens[:, None],
                    kv_caches=caches, positions=positions)
                idx = positions[:, None, None, None]

                def tok_rows(dense):
                    # [S, bucket, n_kv, hd] -> this step's row per slot
                    return jnp.take_along_axis(dense, idx, axis=1)[:, 0]

                k_tok = jnp.stack([tok_rows(k) for k, _ in new])
                v_tok = jnp.stack([tok_rows(v) for _, v in new])
                pid = jnp.take_along_axis(
                    tables, (positions // page)[:, None], axis=1)[:, 0]
                off = positions % page
                pool_k = _kv.scatter_token(pool_k, pid, off, k_tok)
                pool_v = _kv.scatter_token(pool_v, pid, off, v_tok)
                nxt = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
                return pool_k, pool_v, nxt

            fn = jax.jit(decode, donate_argnums=(1, 2))
            self._jit[("decode", bucket)] = fn
        return fn

    def _dispatch(self, kind: str, bucket: int, args: tuple):
        """AOT fast path with jit lookup-miss fallback: the compiled
        executable for (kind, bucket) if warmed, else the jit callable
        (one compile, counted — identical numerics either way)."""
        key = _cache.signature(args, static=(kind, bucket))
        compiled = self._aot.get(key)
        jit_fn = (self._prefill_jit if kind == "prefill"
                  else self._decode_jit)(bucket)
        if compiled is not None:
            try:
                return compiled(*args)
            except (ValueError, TypeError):
                # layout/sharding drift: drop the stale entry, let jit
                # handle anything (same contract as runtime._AotLoop)
                self._aot.pop(key, None)
        self.stats["aot_misses"] += 1
        return jit_fn(*args)

    def warmup(self, buckets: Optional[Sequence[int]] = None
               ) -> "ServingEngine":
        """AOT-compile prefill + decode for every bucket BEFORE traffic
        (``lower().compile()`` over abstract shapes — nothing runs,
        nothing is donated).  With :func:`apex_tpu.cache.enable` the
        backend compiles are disk hits on the second process start.
        After this, steady-state serving pays ZERO compiles: pin with
        ``prof.assert_trace_count`` on the engine's jit callables."""
        s = self.max_seqs
        for b in (self.buckets if buckets is None else buckets):
            n_pages_b = b // self.page_size
            pre_args = (self.params, self.pool_k, self.pool_v,
                        np.zeros((n_pages_b,), np.int32),
                        np.zeros((1, b), np.int32),
                        np.int32(1))
            key = _cache.signature(pre_args, static=("prefill", b))
            self._aot[key] = _cache.warmup(self._prefill_jit(b), *pre_args)
            dec_args = (self.params, self.pool_k, self.pool_v,
                        np.zeros((s, n_pages_b), np.int32),
                        np.zeros((s,), np.int32),
                        np.zeros((s,), np.int32))
            key = _cache.signature(dec_args, static=("decode", b))
            self._aot[key] = _cache.warmup(self._decode_jit(b), *dec_args)
        return self

    # -- request intake ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               stop_token: Optional[int] = None,
               block: bool = True,
               timeout: Optional[float] = None) -> Completion:
        """Enqueue one request; returns its :class:`Completion`.

        The queue is bounded (``max_queue``): when full, ``block=True``
        waits (back-pressure onto the caller, the PrefetchLoader
        discipline) and ``block=False`` raises ``queue.Full``-style
        ``RuntimeError``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        req = Request(prompt, int(max_new_tokens), stop_token)
        comp = Completion()
        # Trace sampling (ISSUE 20): one counter read per request; a
        # sampled request gets its trace id + root span id HERE so
        # every later phase (even across the queue) parents to it.
        tracer = self._tracer()
        trace = tracer.sample() if tracer is not None else None
        root = tracer.next_span_id() if trace is not None else None
        with self._qcond:
            # closed-check under the SAME lock close() drains under — a
            # request appended after the drain would strand its caller
            if self._closed:
                raise RuntimeError("ServingEngine is closed")
            while len(self._queue) >= self.max_queue:
                if not block:
                    raise RuntimeError(
                        f"request queue full ({self.max_queue})")
                if not self._qcond.wait(timeout=timeout or 30.0):
                    raise TimeoutError("request queue stayed full")
                if self._closed:
                    raise RuntimeError("ServingEngine is closed")
            self._queue.append((req, comp, time.perf_counter(),
                                trace, root))
            depth = len(self._queue)
        self.stats["submitted"] += 1
        fields = {}
        if trace is not None:
            fields["trace"] = trace
        self._event("submit", prompt_len=int(prompt.size),
                    max_new=int(max_new_tokens), queue_depth=depth,
                    **fields)
        rec = self._rec()
        if rec is not None:
            rec.metrics.gauge("serving_queue_depth").set(depth)
        return comp

    # -- scheduler ----------------------------------------------------------
    def step(self) -> bool:
        """One scheduler iteration: adopt staged weights, admit what
        fits, run one batched decode step.  Returns True when any work
        was done (the serve thread idles briefly otherwise)."""
        did = self._adopt_weights()
        did = self._admit() or did
        did = self._decode_once() or did
        self._gauges()
        return did

    def run_until_idle(self, max_steps: int = 100000) -> None:
        """Drive :meth:`step` until queue and slots are empty (the
        synchronous harness tests and the bench load generator use).
        Refuses to run beside an active :meth:`start` thread — two
        drivers would race the scheduler state and the DONATED pool
        buffers (the second dispatch would consume deleted arrays)."""
        if self._serve_thread is not None and self._serve_thread.is_alive():
            raise RuntimeError(
                "run_until_idle() cannot drive the scheduler while the "
                "start() serve thread is running — submit() and wait on "
                "the Completions instead")
        for _ in range(max_steps):
            with self._qlock:
                queued = len(self._queue)
            active = any(s is not None for s in self._slots)
            if not queued and not active:
                return
            self.step()
        raise RuntimeError(f"not idle after {max_steps} scheduler steps")

    def generate(self, prompts: Sequence, max_new_tokens: int, *,
                 timeout: Optional[float] = 600.0,
                 **kw) -> List[ServedResult]:
        """Closed-loop convenience: submit every prompt, wait for all,
        return results in order.  With the :meth:`start` thread running
        it only submits and waits; otherwise it drives the scheduler
        on this thread."""
        threaded = (self._serve_thread is not None
                    and self._serve_thread.is_alive())
        comps = [self.submit(p, max_new_tokens, **kw) for p in prompts]
        if not threaded:
            self.run_until_idle()
        return [c.result(timeout=timeout if threaded else 0)
                for c in comps]

    def _adopt_weights(self) -> bool:
        if self.watcher is None:
            return False
        staged = self.watcher.take()
        if staged is None:
            return False
        step, params = staged
        self.params = params
        self.stats["hotswaps"] += 1
        self._event("hotswap", step=step,
                    in_flight=sum(1 for s in self._slots if s is not None))
        tracer = self._tracer()
        if tracer is not None:
            # the swap joins every in-flight traced request's tree: an
            # instant child span per participant, so a waterfall shows
            # exactly which decode gap the adoption (and the watcher's
            # CheckpointManager restore, the `stage` event preceding
            # it) landed in — the swap's latency impact is attributable
            for act in self._slots:
                if act is not None and act.trace is not None:
                    tracer.emit("hotswap", act.trace, parent=act.root,
                                step=step)
        return True

    def _admit(self) -> bool:
        admitted = False
        while True:
            free_slot = next((i for i, s in enumerate(self._slots)
                              if s is None), None)
            if free_slot is None:
                break
            with self._qcond:
                if not self._queue:
                    break
                req, comp, t_submit, trace, root = self._queue[0]
                bucket = self._bucket_for(req.prompt.size
                                          + req.max_new_tokens)
                if bucket is None:
                    # fits no bucket: reject (never silently truncate)
                    self._queue.pop(0)
                    self._qcond.notify_all()
                    reject = True
                else:
                    pages = self.pages.alloc(bucket // self.page_size)
                    if pages is None:
                        break           # no pages free: wait for evictions
                    self._queue.pop(0)
                    self._qcond.notify_all()
                    reject = False
            if reject:
                self.stats["rejected"] += 1
                self._event("reject", prompt_len=int(req.prompt.size),
                            max_new=req.max_new_tokens)
                comp._set(ServedResult(
                    tokens=np.zeros((0,), np.int32), timings={},
                    error=f"prompt {req.prompt.size} + max_new "
                          f"{req.max_new_tokens} fits no bucket "
                          f"(max {self.buckets[-1]})"))
                continue
            self._prefill_into(free_slot, req, comp, t_submit, bucket,
                               pages, trace, root)
            admitted = True
        return admitted

    def _prefill_into(self, slot: int, req: Request, comp: Completion,
                      t_submit: float, bucket: int, pages: List[int],
                      trace: Optional[str] = None,
                      root: Optional[str] = None) -> None:
        t_admit = time.perf_counter()
        queue_wait = t_admit - t_submit
        tracer = self._tracer() if trace is not None else None
        if tracer is not None:
            # emitted AT admission so the span's end (`t`) is now and
            # its start lands back at submit — the waterfall's first bar
            tracer.emit("queue", trace, parent=root, dur=queue_wait,
                        slot=slot, bucket=bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :req.prompt.size] = req.prompt
        args = (self.params, self.pool_k, self.pool_v,
                np.asarray(pages, np.int32), tokens,
                np.int32(req.prompt.size))
        self.pool_k, self.pool_v, nxt = self._dispatch(
            "prefill", bucket, args)
        # Response boundary: the first sampled token must reach the host
        # — it seeds the decode batch and may already finish the request.
        first = int(np.asarray(nxt))  # jaxlint: disable=J001,J012 -- the sanctioned response-boundary sync: prefill's sampled token seeds the decode batch and the scheduler's admission/termination decisions are host control flow
        t_done = time.perf_counter()
        self.stats["prefills"] += 1
        self._event("admit", slot=slot, bucket=bucket,
                    prompt_len=int(req.prompt.size),
                    queue_wait=round(queue_wait, 6),
                    prefill_dur=round(t_done - t_admit, 6))
        if tracer is not None:
            tracer.emit("prefill", trace, parent=root,
                        dur=t_done - t_admit, slot=slot, bucket=bucket,
                        prompt_len=int(req.prompt.size))
        rec = self._rec()
        if rec is not None:
            rec.metrics.histogram("serving_queue_wait_s").observe(
                queue_wait)
            rec.metrics.histogram("serving_prefill_s").observe(
                t_done - t_admit)
        self._slots[slot] = _Active(req, comp, bucket, pages,
                                    t_submit, t_admit, t_done,
                                    trace, root)
        self._pos[slot] = req.prompt.size
        self._tok[slot] = first
        self._gen[slot] = [first]
        if req.max_new_tokens == 1 or first == req.stop_token:
            self._finish(slot)

    def _decode_once(self) -> bool:
        live = [i for i, s in enumerate(self._slots) if s is not None]
        if not live:
            return False
        # one batched dispatch at the smallest bucket covering every
        # live sequence's NEXT position — short traffic keeps small
        # executables even while a long sequence occupies a slot
        bucket = self._bucket_for(int(max(self._pos[i] for i in live)) + 1)
        n_pages_b = bucket // self.page_size
        tables = np.zeros((self.max_seqs, n_pages_b), np.int32)
        for i in live:
            row = self.pages.padded_row(self._slots[i].pages, n_pages_b)
            tables[i] = row[:n_pages_b]
        t0 = time.perf_counter()
        args = (self.params, self.pool_k, self.pool_v, tables,
                self._pos.copy(), self._tok.copy())
        self.pool_k, self.pool_v, nxt = self._dispatch(
            "decode", bucket, args)
        self._handle_decoded(nxt, live, bucket, t0)
        return True

    def _handle_decoded(self, nxt, live: List[int], bucket: int,
                        t0: float) -> None:
        """Fold one decode dispatch's sampled tokens back into the
        scheduler (the per-step response boundary)."""
        toks = np.asarray(nxt)  # jaxlint: disable=J001,J012 -- the sanctioned response-boundary sync: sampled tokens drive termination/eviction/admission (host control flow) and stream back to waiting callers
        dur = time.perf_counter() - t0
        self.stats["decode_steps"] += 1
        n_tok = len(live)
        self.stats["tokens_out"] += n_tok
        # capture traced participants BEFORE the finish loop clears
        # their slots — the step's span belongs to every traced request
        # that decoded in it, finished or not
        traced = [(i, self._slots[i]) for i in live
                  if self._slots[i].trace is not None]
        for i in live:
            self._pos[i] += 1
            tok = int(toks[i])
            self._tok[i] = tok
            self._gen[i].append(tok)
            act = self._slots[i]
            if (len(self._gen[i]) >= act.request.max_new_tokens
                    or tok == act.request.stop_token):
                self._finish(i)
        rec = self._rec()
        self._event("decode", active=n_tok, bucket=bucket,
                    dur=round(dur, 6))
        if traced:
            tracer = self._tracer()
            if tracer is not None:
                for i, act in traced:
                    # the ONE batched dispatch, as a child span per
                    # traced participant: slot + batch size make the
                    # continuous-batching interference visible per
                    # request (the batch-size/TPOT join reads these)
                    tracer.emit("decode_step", act.trace,
                                parent=act.root, dur=dur, slot=i,
                                bucket=bucket, batch_size=n_tok)
        if rec is not None:
            rec.metrics.histogram("serving_decode_step_s").observe(dur)
            now = time.perf_counter()
            if self._t_rate is not None:
                rec.metrics.gauge("serving_tokens_per_s").set(
                    n_tok / max(now - self._t_rate, 1e-9))
            self._t_rate = now

    def _finish(self, slot: int) -> None:
        act = self._slots[slot]
        gen = self._gen[slot]
        req = act.request
        if req.stop_token is not None and req.stop_token in gen:
            gen = gen[:gen.index(req.stop_token) + 1]
        t_done = time.perf_counter()
        decode_s = t_done - act.t_prefill_done
        # The headline LLM serving metrics (ISSUE 20): TTFT is
        # submit -> first token (prefill already materializes it on the
        # host, so no new sync), TPOT the mean inter-token time over
        # the remaining tokens, e2e the whole journey.
        ttft_s = act.t_prefill_done - act.t_submit
        tpot_s = (decode_s / (len(gen) - 1)
                  if decode_s > 0 and len(gen) > 1 else None)
        e2e_s = t_done - act.t_submit
        timings = {
            "queue_wait_s": round(act.t_admit - act.t_submit, 6),
            "prefill_s": round(act.t_prefill_done - act.t_admit, 6),
            "decode_s": round(decode_s, 6),
            "total_s": round(e2e_s, 6),
            "ttft_s": round(ttft_s, 6),
            "tpot_s": round(tpot_s, 6) if tpot_s is not None else None,
            "tok_per_s": (round((len(gen) - 1) / decode_s, 2)
                          if decode_s > 0 and len(gen) > 1 else None),
        }
        self.pages.free(act.pages)
        self._slots[slot] = None
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._gen[slot] = []
        self.stats["completed"] += 1
        rec = self._rec()
        if rec is not None:
            rec.metrics.histogram("serving_ttft_s").observe(ttft_s)
            if tpot_s is not None:
                rec.metrics.histogram("serving_tpot_s").observe(tpot_s)
            rec.metrics.histogram("serving_e2e_s").observe(e2e_s)
        fields = {}
        if act.trace is not None:
            fields["trace"] = act.trace
        self._event("done", slot=slot, bucket=act.bucket,
                    n_tokens=len(gen), **fields, **timings)
        if act.trace is not None:
            tracer = self._tracer()
            if tracer is not None:
                # the root: emitted LAST with the span id allocated at
                # submit, so every child already points at it
                tracer.emit("request", act.trace, span=act.root,
                            dur=e2e_s, slot=slot, bucket=act.bucket,
                            n_tokens=len(gen),
                            ttft_s=round(ttft_s, 6))
        act.completion._set(ServedResult(
            tokens=np.asarray(gen, np.int32), timings=timings,
            bucket=act.bucket))

    # -- threaded serving ----------------------------------------------------
    def start(self) -> "ServingEngine":
        """Run the scheduler on a background thread (idempotent): the
        deployment shape — callers just :meth:`submit` and wait."""
        if self._serve_thread is None or not self._serve_thread.is_alive():
            self._serve_stop.clear()
            self._serve_thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name="apex-tpu-serving")
            self._serve_thread.start()
        return self

    def _serve_loop(self) -> None:
        while not self._serve_stop.is_set():
            if not self.step():
                self._serve_stop.wait(0.002)    # idle: don't spin

    def close(self) -> None:
        """Stop the serve thread and the weight watcher; fail queued
        AND in-flight (admitted) requests so no caller waits forever,
        and return their KV pages to the pool."""
        with self._qcond:
            if self._closed:
                return
            self._closed = True
        self._serve_stop.set()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)
            self._serve_thread = None
        if self.watcher is not None:
            self.watcher.close()
        with self._qcond:
            abandoned, self._queue = self._queue, []
            self._qcond.notify_all()
        closed = ServedResult(tokens=np.zeros((0,), np.int32),
                              timings={}, error="engine closed")
        for _req, comp, _t, _trace, _root in abandoned:
            comp._set(closed)
        # admitted-but-unfinished sequences: the serve thread is down,
        # so no further decode step will ever finish them
        for i, act in enumerate(self._slots):
            if act is None:
                continue
            self.pages.free(act.pages)
            self._slots[i] = None
            act.completion._set(closed)

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
