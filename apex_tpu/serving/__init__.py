"""apex_tpu.serving — AOT-bucketed inference on the training stack
(ISSUE 11): continuous batching over a block-paged, donated KV cache,
zero steady-state compiles, zero-downtime weight hot-swap, and
per-request telemetry through the existing recorder/Prometheus export.

See ``docs/serving.md`` for the recipe and the gauge table.
"""

from .engine import (Completion, Request, ServedResult,  # noqa: F401
                     ServingEngine)
from .hotswap import WeightWatcher                       # noqa: F401
from .kv_cache import PageAllocator, make_pool           # noqa: F401

__all__ = ["ServingEngine", "Request", "ServedResult", "Completion",
           "WeightWatcher", "PageAllocator", "make_pool"]
